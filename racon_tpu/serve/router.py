"""Shard-aware serve router: one logical polishing service over N warm
`PolishServer` replicas, surviving the loss of any one of them mid-job.

Everything through the fused serve path still lives in one process on
one mesh: a crashed server loses every queued and in-flight job, and
the only scale-out story is the wrapper's cold file-level scatter.
`PolishRouter` is the replicated serve fabric on top of the existing
pieces — it speaks the SAME submit frame as a replica (protocol.py), so
`racon_tpu submit` pointed at a router works unchanged:

  - **Shard fan-out.** A submit's target FASTA is split by CONTIG into
    `min(routable replicas, contigs)` shards using the wrapper's lo/hi
    contiguous-block partition math (`wrapper.py` — concatenating shard
    outputs in shard order reproduces the unsharded output byte for
    byte, a pinned contract the router inherits: per-contig polishing
    is independent, so routing whole contigs preserves identity). Each
    shard goes to a replica as a child job tagged with the parent
    (``parent`` / ``shard`` / ``shards`` submit keys, child trace id
    ``<parent>.s<k>``), always with ``stream: true`` so finished
    contigs flow back the moment they land.
  - **Window-range sharding.** When routable replicas EXCEED the
    contig count (the one-mega-contig case the wrapper's file-level
    scatter could never scale), the largest contigs split further by
    target-coordinate range at window-grid boundaries — the grid is
    deterministic from ``window_length``, so split points are exact
    and every window is owned by exactly one shard. Each range child
    carries ``range_lo``/``range_hi`` (protocol.py "Child-job
    fields"), polishes only that window slice, and streams raw contig
    SEGMENTS with stitch accounting; the merge ledger buffers a
    contig's segments until all its shards are done, then re-derives
    the solo LN/RC/XC tags — byte-identical to the unsharded run,
    with requeue-after-kill deduping at segment granularity. Rounds
    requests fall back to contig sharding (a re-draft round over a
    segment is not the solo computation).
  - **Contig-order merge.** Replies merge via `ContigStreamer`
    semantics at shard granularity: shard k's parts are forwarded (or
    buffered, for a non-streaming client) only once shards 0..k-1 have
    fully shipped, so the client sees one job in exact target order.
    The final result frame aggregates the shards' stats and carries a
    ``router`` block (shards / requeues / parts).
  - **Journal-backed requeue.** The router keeps its own durable
    journal (obs/journal.py) as the retry ledger: parent lifecycle
    lines (received / started / finished / failed) plus annotation
    events — ``shard-dispatched``, one ``part-routed`` per contig
    forwarded to the client, ``shard-finished``, ``requeued``,
    ``replica-down`` / ``replica-up`` (all outside LIFECYCLE_EVENTS,
    so older journal checkers ignore them). A replica that dies
    mid-shard — connection drop, kill -9, a healthz that never comes
    back — gets that shard re-dispatched to a healthy replica; parts
    the ledger already counted as routed are deduped by position
    (replica output is deterministic, so the re-run re-streams
    byte-identical parts and the router skips the first `arrived`
    ones), and the client sees each contig EXACTLY once.
  - **Health + rolling restarts.** Replica health rides the PR-12
    obs/fleet.py machinery: a background `FleetAggregator` poll
    (healthz + scrape) marks replicas routable / draining / down, and
    the router's own /metrics federates the replicas' scrapes behind
    one endpoint plus ``racon_tpu_router_*`` families. `drain` on a
    replica flips it unroutable (its healthz answers draining/503) —
    in-flight shards finish there, new shards route elsewhere — and a
    restarted replica rejoins on its first clean healthz. The router's
    own healthz reports the live routable count throughout.

Env knobs (all strict-parsed at startup, the --metrics-port
discipline): RACON_TPU_ROUTER_REPLICAS (comma-separated replica RPC
endpoints — unix socket paths or localhost host:port; http:// metrics
bases cannot take submits and are rejected), RACON_TPU_ROUTER_SOCKET /
RACON_TPU_ROUTER_PORT (the router's own listener),
RACON_TPU_ROUTER_JOURNAL (retry-ledger path; pair with
RACON_TPU_JOURNAL_FSYNC=1 for fsync-per-record durability),
RACON_TPU_ROUTER_METRICS_PORT, RACON_TPU_ROUTER_HEALTH_INTERVAL
(replica poll seconds, default 2), RACON_TPU_ROUTER_MAX_SHARDS (cap on
shards per job, default 0 = one per routable replica),
RACON_TPU_ROUTER_RETRIES (replica losses tolerated per shard, default
3), RACON_TPU_ROUTER_WAIT_S (how long a shard waits for any routable
replica before the job fails, default 60).

Elastic autoscaling (serve/autoscale.py, ``racon_tpu router
--autoscale``): an `Autoscaler` loop drives `add_replica` /
`remove_replica` from the fleet poll's burn-rate / queue-depth /
admission-EMA signals — warm replica subprocesses spawn on sustained
pressure and drain on idle (SIGTERM -> graceful drain; a kill mid-job
is the same journal-backed requeue as any replica loss, so scale-down
loses zero jobs). Knobs: RACON_TPU_ROUTER_AUTOSCALE_* (strict-parsed;
see autoscale.py). README "Elastic fleet" is the runbook.

CLI: ``racon_tpu router --replicas /tmp/a.sock,/tmp/b.sock`` (cli.py);
benchmarks: ``tools/servebench.py --router N``; failure matrix:
``tools/faultcheck.py`` router column. See README "Serving" for the
rolling-restart runbook.
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

from ..errors import RaconError
from ..obs import flight as obs_flight
from ..obs import prom as obs_prom
from ..obs.fleet import FleetAggregator
from ..obs.journal import Journal
from ..utils.logger import log_info
from .client import (JobFailed, PolishClient, QueueFull, ServeError,
                     ServerDraining, _retry_delay)
from .protocol import (ProtocolError, error_response, max_frame_bytes,
                       recv_frame, send_frame)

DEFAULT_ROUTER_SOCKET = "/tmp/racon_tpu_router.sock"

#: journal annotation events the router emits alongside the parent
#: job's lifecycle lines. Deliberately OUTSIDE obs.journal's
#: LIFECYCLE_EVENTS: the consistency checker must ignore them, so an
#: older obsreport reading a router journal never reds out on them.
ROUTER_EVENTS = frozenset((
    "router-start", "router-stop", "shard-dispatched", "shard-finished",
    "part-routed", "requeued", "replica-down", "replica-up",
    "cancelled", "siblings-cancelled", "range-plan", "frag-plan",
    "replica-added", "replica-removed", "autoscale-up",
    "autoscale-down", "hold"))

#: trace-id charset (mirrors PolishServer._TRACE_ID_OK — "." is legal,
#: which is what makes the `<parent>.s<k>` child ids valid replica-side)
_TRACE_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise RaconError(
            "router", f"{name} must be an integer, got {raw!r}") from None


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise RaconError(
            "router", f"{name} must be a number, got {raw!r}") from None


class RouterConfig:
    """Router knobs; every constructor override has an env twin (module
    docstring) and parse failures raise NOW, not at the first job."""

    def __init__(self, **kw):
        replicas = kw.pop("replicas", None)
        if replicas is None:
            replicas = os.environ.get("RACON_TPU_ROUTER_REPLICAS", "")
        if isinstance(replicas, str):
            replicas = [s.strip() for s in replicas.split(",") if s.strip()]
        self.replicas = list(replicas)
        if not self.replicas:
            raise RaconError(
                "router", "no replicas configured (pass replicas= / "
                "--replicas or set RACON_TPU_ROUTER_REPLICAS)")
        for spec in self.replicas:
            if spec.startswith(("http://", "https://")):
                raise RaconError(
                    "router",
                    f"replica {spec!r} is an http:// metrics base — "
                    "the router submits jobs, so replicas must be RPC "
                    "endpoints (unix socket path or localhost "
                    "host:port)")
            if "/" not in spec and os.path.sep not in spec:
                host = spec.rpartition(":")[0]
                if host not in ("", "127.0.0.1", "localhost"):
                    raise RaconError(
                        "router",
                        f"replica {spec!r}: TCP replicas must be "
                        "localhost (the serve transport binds "
                        "127.0.0.1 only)")
        self.socket_path = (kw.pop("socket_path", None)
                            or os.environ.get("RACON_TPU_ROUTER_SOCKET")
                            or DEFAULT_ROUTER_SOCKET)
        port = kw.pop("port", None)
        if port is None:
            raw = os.environ.get("RACON_TPU_ROUTER_PORT", "")
            port = _env_int("RACON_TPU_ROUTER_PORT", -1) if raw else None
        self.port = port
        self.journal_path = kw.pop("journal", None)
        if self.journal_path is None:
            self.journal_path = os.environ.get(
                "RACON_TPU_ROUTER_JOURNAL", "")
        mp = kw.pop("metrics_port", None)
        if mp is None:
            raw = os.environ.get("RACON_TPU_ROUTER_METRICS_PORT", "")
            mp = _env_int("RACON_TPU_ROUTER_METRICS_PORT", 0) if raw \
                else None
        self.metrics_port = mp
        hi = kw.pop("health_interval_s", None)
        self.health_interval_s = (
            float(hi) if hi is not None
            else _env_float("RACON_TPU_ROUTER_HEALTH_INTERVAL", 2.0))
        ms = kw.pop("max_shards", None)
        self.max_shards = (int(ms) if ms is not None
                           else _env_int("RACON_TPU_ROUTER_MAX_SHARDS", 0))
        sr = kw.pop("shard_retries", None)
        self.shard_retries = (
            int(sr) if sr is not None
            else _env_int("RACON_TPU_ROUTER_RETRIES", 3))
        ws = kw.pop("replica_wait_s", None)
        self.replica_wait_s = (
            float(ws) if ws is not None
            else _env_float("RACON_TPU_ROUTER_WAIT_S", 60.0))
        pt = kw.pop("probe_timeout_s", None)
        self.probe_timeout_s = (
            float(pt) if pt is not None
            else _env_float("RACON_TPU_ROUTER_PROBE_TIMEOUT", 2.0))
        # RACON_TPU_ROUTER_TRACE=<out.json>: dump the router's own
        # flight ring (plan/dispatch/stream/merge/requeue spans for
        # every routed job still in the ring) as Chrome-trace JSON at
        # router stop — the standalone-router twin of the per-job
        # --trace-out pull
        tp = kw.pop("trace_path", None)
        self.trace_path = (tp if tp is not None
                           else os.environ.get(
                               "RACON_TPU_ROUTER_TRACE", "")) or None
        self.max_frame = max_frame_bytes()
        if kw:
            raise RaconError(
                "router",
                f"unknown router option(s): {', '.join(sorted(kw))}")

    @property
    def address(self) -> str:
        if self.port is not None:
            return f"127.0.0.1:{self.port}"
        return self.socket_path


class ReplicaState:
    """One replica's live routing state. `ok`/`draining` come from the
    fleet poll (the authority); `down_forced` bridges the gap between
    polls when a submit observed the replica dead — cleared by the next
    poll, which re-probes for real."""

    def __init__(self, spec: str):
        self.spec = spec
        self.ok = True  # optimistic until the first poll lands
        self.draining = False
        self.down_forced = False
        self.error: str | None = None
        self.inflight = 0  # shards currently dispatched here

    @property
    def routable(self) -> bool:
        return self.ok and not self.draining and not self.down_forced

    def client(self, timeout: float | None = None) -> PolishClient:
        if "/" in self.spec or os.path.sep in self.spec:
            return PolishClient(socket_path=self.spec, timeout=timeout)
        port = int(self.spec.rpartition(":")[2])
        return PolishClient(port=port, timeout=timeout)


class _ShardFailure(Exception):
    """Internal: a shard (and therefore the parent job) failed typed."""

    def __init__(self, code: str, message: str, **extra):
        super().__init__(message)
        self.code = code
        self.extra = extra


class _JobMerge:
    """Per-job merge + dedupe ledger: buffers each shard's streamed
    parts, forwards them in global contig order (shard k only after
    shards 0..k-1 fully shipped — ContigStreamer semantics one level
    up), and dedupes a requeued shard's re-streamed parts by position
    (`arrived` counts the CURRENT attempt; anything below the buffered
    length is a byte-identical duplicate and is skipped).

    Range mode (`groups` set — sub-contig window-range sharding): each
    shard is one (contig, [lo, hi)) slice streaming ONE bare-named raw
    segment with its stitch accounting (`seg`); a group = one contig's
    shards in lo order. A group's segments buffer until EVERY member
    shard is done, then assemble into ONE whole-contig part with the
    solo LN/RC/XC tags re-derived from the summed accounting — so the
    merged output is byte-identical to the unsharded run, and the
    requeue dedupe above operates at segment granularity."""

    def __init__(self, n_shards: int, emit_part=None, on_routed=None,
                 groups: list[dict] | None = None,
                 fragment_correction: bool = False,
                 drop_unpolished: bool = True):
        self.lock = threading.Lock()
        self.parts: list[list[tuple]] = [[] for _ in range(n_shards)]
        self.arrived = [0] * n_shards
        self.done = [False] * n_shards
        self.results: list[dict | None] = [None] * n_shards
        self.failure: _ShardFailure | None = None
        #: shards currently in flight on a replica: shard k ->
        #: (ReplicaState, child trace id) — the sibling-cancel fan-out
        #: reads this to reach every other shard's replica by child
        #: trace id when one shard's failure dooms the whole parent
        self.dispatched: dict[int, tuple] = {}
        #: every replica that EVER took a shard of this job (spec ->
        #: ReplicaState), including ones that later died — the trace
        #: collection resolves pull targets through it
        self.replicas_seen: dict[str, object] = {}
        #: shard k -> (replica spec, child trace id) of the attempt
        #: that COMPLETED the shard (never popped, unlike
        #: `dispatched`): the trace collection pulls each replica for
        #: exactly the child traces it finished, so co-resident
        #: replicas sharing one process flight ring (in-process tests)
        #: never duplicate each other's spans
        self.shard_owner: dict[int, tuple] = {}
        self._emit_part = emit_part
        self._on_routed = on_routed
        self._cursor_shard = 0
        self._cursor_part = 0
        self.total_routed = 0
        #: range mode: [{"name": contig, "shards": [k...]} ...] in
        #: contig order, member shards in lo order
        self.groups = groups
        self._fragment_correction = fragment_correction
        self._drop_unpolished = drop_unpolished
        self._group_cursor = 0
        self._assembled: list[tuple[str, str]] = []
        #: accepted range segments (post-dedupe) — the obsreport
        #: receipt unit; classic mode leaves it 0
        self.segments_routed = 0
        #: fragment mode: (shard, buffered-part position) -> the
        #: frame's global read-axis receipt (frag_lo, frag_hi, reads).
        #: Keyed by BUFFER position so a requeued shard's re-streamed
        #: duplicates (dropped above by the arrived/len dedupe) never
        #: re-record a receipt — part-routed stays one line per
        #: accepted read group.
        self._frag_meta: dict[tuple[int, int], tuple] = {}
        #: corrected reads routed (sum of accepted groups' `reads`)
        self.reads_routed = 0

    def on_part(self, k: int, frame: dict) -> None:
        with self.lock:
            idx = self.arrived[k]
            self.arrived[k] += 1
            if idx < len(self.parts[k]):
                return  # requeued re-run duplicate: ledger dedupe
            if self.groups is not None:
                seg = frame.get("seg")
                if not isinstance(seg, dict):
                    # a pre-range replica ignored range_lo/range_hi and
                    # polished the WHOLE contig — merging its bytes
                    # would corrupt the output, so the job fails typed
                    if self.failure is None:
                        self.failure = _ShardFailure(
                            "replica-incompatible",
                            f"shard {k}: part arrived without range "
                            "segment accounting (replica predates "
                            "range sharding?)")
                    return
                self.parts[k].append(
                    (frame.get("name"), frame.get("fasta", ""), seg))
                self.segments_routed += 1
                if self._on_routed is not None:
                    self._on_routed(k, idx, frame.get("name"),
                                    len(frame.get("fasta", "")),
                                    lo=seg.get("lo"), hi=seg.get("hi"))
                self._pump_locked()
                return
            frag = frame.get("frag")
            if isinstance(frag, (list, tuple)) and len(frag) == 2:
                # fragment group: remember the read-axis receipt for
                # this buffered position so the in-order pump can
                # journal it (part-routed frag_lo/frag_hi tiling)
                self._frag_meta[(k, len(self.parts[k]))] = (
                    frag[0], frag[1], frame.get("reads"))
                self.reads_routed += int(frame.get("reads") or 0)
            self.parts[k].append(
                (frame.get("name"), frame.get("fasta", "")))
            self._pump_locked()

    def shard_done(self, k: int, resp: dict) -> None:
        with self.lock:
            self.done[k] = True
            self.results[k] = resp
            self._pump_locked()

    def requeue(self, k: int) -> None:
        with self.lock:
            self.arrived[k] = 0  # the re-run streams from its contig 0

    def fail(self, failure: _ShardFailure) -> None:
        with self.lock:
            if self.failure is None:
                self.failure = failure

    def _pump_locked(self) -> None:
        if self.groups is not None:
            self._pump_groups_locked()
            return
        n = len(self.parts)
        while self._cursor_shard < n:
            k = self._cursor_shard
            while self._cursor_part < len(self.parts[k]):
                name, fasta = self.parts[k][self._cursor_part]
                meta = self._frag_meta.get((k, self._cursor_part))
                part_index = self.total_routed
                self.total_routed += 1
                self._cursor_part += 1
                if self._on_routed is not None:
                    if meta is not None:
                        self._on_routed(k, part_index, name,
                                        len(fasta), frag_lo=meta[0],
                                        frag_hi=meta[1], reads=meta[2])
                    else:
                        self._on_routed(k, part_index, name,
                                        len(fasta))
                if self._emit_part is not None:
                    self._emit_part(k, part_index, name, fasta)
            if not self.done[k]:
                return
            self._cursor_shard += 1
            self._cursor_part = 0

    def _pump_groups_locked(self) -> None:
        """Range mode forward: a contig ships the moment ALL its range
        shards are done (every segment final) and every earlier contig
        has shipped. `on_routed` is deliberately NOT fired here —
        range mode journals per-SEGMENT receipts at arrival instead."""
        if self.failure is not None:
            # a rejected part (or any shard failure) may have left a
            # hole: never assemble — the client gets the typed error
            return
        while self._group_cursor < len(self.groups):
            g = self.groups[self._group_cursor]
            if not all(self.done[k] for k in g["shards"]):
                return
            part = self._assemble_locked(g)
            self._group_cursor += 1
            if part is None:
                continue  # dropped as fully unpolished (solo rule)
            name, fasta = part
            self._assembled.append((name, fasta))
            part_index = self.total_routed
            self.total_routed += 1
            if self._emit_part is not None:
                self._emit_part(g["shards"][0], part_index, name, fasta)

    def _assemble_locked(self, g: dict) -> tuple[str, str] | None:
        """Stitch one contig's segments (lo order) into the whole-contig
        FASTA entry a solo run would emit: body = segment concat, LN =
        body length, RC = coverage (every range child parses ALL
        overlaps, so each reports the identical count), XC =
        sum(polished) / total grid windows — the same integer inputs as
        the solo ratio, hence the same float and the same ``:.6f``
        rendering (core/polisher._stitch_contig)."""
        segs = []
        for k in g["shards"]:
            for _name, fasta, seg in self.parts[k]:
                segs.append((int(seg.get("lo", 0)), fasta, seg))
        segs.sort(key=lambda s: s[0])
        total = max((int(s.get("total_windows", 0))
                     for _lo, _f, s in segs), default=0)
        if not segs or not total:
            return None
        body = "".join(f for _lo, f, _s in segs)
        polished = sum(int(s.get("polished", 0)) for _lo, _f, s in segs)
        coverage = max(int(s.get("coverage", 0)) for _lo, _f, s in segs)
        ratio = polished / float(total)
        if self._drop_unpolished and ratio <= 0:
            return None
        tags = "r" if self._fragment_correction else ""
        tags += f" LN:i:{len(body)}"
        tags += f" RC:i:{coverage}"
        tags += f" XC:f:{ratio:.6f}"
        name = g["name"] + tags
        return name, f">{name}\n{body}\n"

    def fasta(self) -> str:
        """The merged body (latin-1 text, as it rides the wire)."""
        with self.lock:
            if self.groups is not None:
                return "".join(f for _name, f in self._assembled)
            return "".join(fasta for shard in self.parts
                           for _name, fasta in shard)


class PolishRouter:
    """The replicated serve front-end (module docstring). Mirrors
    PolishServer's transport shape — same frame protocol, same
    accept/handle/dispatch skeleton, same typed-error discipline — but
    executes nothing itself: every submit fans out to replicas."""

    def __init__(self, config: RouterConfig | None = None, **overrides):
        self.config = config if config is not None \
            else RouterConfig(**overrides)
        cfg = self.config
        self.replicas = [ReplicaState(s) for s in cfg.replicas]
        #: PR-12 reuse: the fleet aggregator IS the health poller and
        #: the scrape federation source behind the router's /metrics
        self.fleet = FleetAggregator(cfg.replicas,
                                     timeout_s=cfg.probe_timeout_s)
        self.journal: Journal | None = None
        self._listener: socket.socket | None = None
        self._http = None
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conn_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._job_seq = 0
        #: active fan-outs by router job id -> (trace_id, merge): the
        #: parent-level cancel RPC resolves its target here
        self._active: dict[str, tuple] = {}
        self._inflight_jobs = 0
        self._requeued_outstanding = 0
        #: shards currently holding in _run_shard for an idle replica
        #: (autoscale hold); the autoscaler counts these as backlog
        self._dispatch_waiting = 0
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._t_start = time.perf_counter()
        self.counters = {"jobs_submitted": 0, "jobs_completed": 0,
                         "jobs_failed": 0, "shards_dispatched": 0,
                         "parts_routed": 0, "requeues": 0}
        #: attached Autoscaler (serve/autoscale.py) or None — healthz
        #: and /metrics surface its state only when armed, so the
        #: off-knob exposition stays byte-identical; while armed with
        #: headroom, _run_shard also holds for idle capacity
        self.autoscaler = None
        #: the router's own always-on flight ring (obs/flight.py):
        #: plan / dispatch(+hold) / stream / merge / requeue / cancel
        #: spans per routed job, tagged with the parent trace id and
        #: the child `<trace>.s<k>` ids. Deliberately NOT installed as
        #: the process-global tracer — routers share processes with
        #: replicas in tests and embedded runs, and the global slot
        #: belongs to the serve layer's ring
        self.recorder = obs_flight.FlightRecorder()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "PolishRouter":
        cfg = self.config
        if cfg.journal_path:
            try:
                self.journal = Journal(cfg.journal_path)
            except OSError as exc:
                raise RaconError(
                    "router",
                    f"cannot open router journal {cfg.journal_path!r} "
                    f"({exc}); point --journal / "
                    "RACON_TPU_ROUTER_JOURNAL at a writable path") \
                    from None
        # first poll before accepting: replica state starts honest, not
        # optimistic (a dead replica configured at startup is already
        # unroutable when the first submit arrives)
        self._apply_poll(self.fleet.poll())
        if cfg.metrics_port is not None:
            self._start_metrics_http()
        if cfg.port is not None:
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind(("127.0.0.1", max(0, cfg.port)))
            if cfg.port <= 0:
                cfg.port = lst.getsockname()[1]
        else:
            with contextlib.suppress(OSError):
                os.unlink(cfg.socket_path)
            lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            lst.bind(cfg.socket_path)
        lst.listen(64)
        lst.settimeout(0.2)
        self._listener = lst
        for target, name in ((self._accept_loop, "racon-tpu-router-accept"),
                             (self._health_loop, "racon-tpu-router-health")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if self.journal is not None:
            self.journal.record("router-start", address=cfg.address,
                                pid=os.getpid(),
                                replicas=len(self.replicas))
        log_info(f"[racon_tpu::router] routing on {cfg.address} over "
                 f"{len(self.replicas)} replica(s), "
                 f"{self._routable_count()} routable"
                 + (f", metrics on 127.0.0.1:{cfg.metrics_port}"
                    if self._http is not None else "")
                 + (f", journal {cfg.journal_path}"
                    if self.journal is not None else ""))
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting, let in-flight fan-outs finish (bounded by
        `timeout`), close the transport and the journal."""
        if self._draining.is_set():
            self._stopped.wait()
            return True
        self._draining.set()
        if self._listener is not None:
            with contextlib.suppress(OSError):
                self._listener.close()
        deadline = time.monotonic() + timeout
        clean = True
        while time.monotonic() < deadline:
            with self._state_lock:
                if self._inflight_jobs == 0:
                    break
            time.sleep(0.05)
        else:
            clean = False
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)
        if self._http is not None:
            with contextlib.suppress(Exception):
                self._http.shutdown()
                self._http.server_close()
            self._http = None
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        if self.config.port is None:
            with contextlib.suppress(OSError):
                os.unlink(self.config.socket_path)
        self.fleet.close()
        if self.config.trace_path:
            # RACON_TPU_ROUTER_TRACE: best-effort ring dump at stop —
            # a full disk loses the artifact, never the drain
            try:
                obs_flight.dump(self.recorder, self.config.trace_path)
                log_info(f"[racon_tpu::router] trace written to "
                         f"{self.config.trace_path}")
            except Exception as exc:  # noqa: BLE001 — see above
                log_info(f"[racon_tpu::router] warning: could not "
                         f"write trace ({type(exc).__name__}: {exc})")
        if self.journal is not None:
            self.journal.record(
                "router-stop", clean=clean,
                completed=self.counters["jobs_completed"],
                failed=self.counters["jobs_failed"],
                requeues=self.counters["requeues"])
            self.journal.close()
        self._stopped.set()
        return clean

    # --------------------------------------------------------------- health
    def _health_loop(self) -> None:
        while not self._draining.is_set():
            self._draining.wait(self.config.health_interval_s)
            if self._draining.is_set():
                return
            with contextlib.suppress(Exception):
                self._apply_poll(self.fleet.poll())

    def _apply_poll(self, snap) -> None:
        by_spec = {rs.endpoint: rs for rs in snap.replicas}
        with self._state_lock:
            for r in self.replicas:
                rs = by_spec.get(r.spec)
                if rs is None:
                    continue
                was = r.routable
                r.ok = rs.ok
                r.draining = rs.draining
                r.error = rs.error
                # the poll re-probed for real: it overrides any
                # submit-observed failure either way
                r.down_forced = False
                now = r.routable
                if was != now and self.journal is not None:
                    self.journal.record(
                        "replica-up" if now else "replica-down",
                        replica=r.spec,
                        draining=r.draining or None,
                        error=r.error)
                if was != now:
                    log_info(f"[racon_tpu::router] replica {r.spec} "
                             + ("rejoined"
                                if now else
                                ("draining" if r.draining
                                 else f"down ({r.error})")))

    def _routable_count(self) -> int:
        with self._state_lock:
            return sum(1 for r in self.replicas if r.routable)

    # ---------------------------------------------------- elastic fleet
    def add_replica(self, spec: str) -> bool:
        """Join a replica to the live routing set (the autoscaler's
        scale-up seam; also usable operationally). Idempotent; the
        next health poll (or a submit) takes it from there."""
        with self._state_lock:
            if any(r.spec == spec for r in self.replicas):
                return False
            self.replicas.append(ReplicaState(spec))
        self.fleet.add_endpoint(spec)
        if self.journal is not None:
            self.journal.record("replica-added", replica=spec)
        log_info(f"[racon_tpu::router] replica {spec} added "
                 f"({self._routable_count()} routable)")
        return True

    def remove_replica(self, spec: str) -> bool:
        """Remove a replica from the routing set (scale-down, after
        its drain). In-flight shards on it finish or requeue through
        the normal loss path; nothing new routes there."""
        with self._state_lock:
            before = len(self.replicas)
            self.replicas = [r for r in self.replicas
                             if r.spec != spec]
            removed = len(self.replicas) != before
        if not removed:
            return False
        self.fleet.remove_endpoint(spec)
        if self.journal is not None:
            self.journal.record("replica-removed", replica=spec)
        log_info(f"[racon_tpu::router] replica {spec} removed")
        return True

    def _pick_replica(self, exclude: set,
                      max_inflight: int | None = None
                      ) -> ReplicaState | None:
        """Least-inflight routable replica, preferring ones the shard
        has not failed on yet; claims an inflight slot under the lock.
        With `max_inflight`, only replicas strictly below that load
        qualify — the autoscale hold uses this to insist on an idle
        replica while the fleet can still grow."""
        with self._state_lock:
            cands = [r for r in self.replicas
                     if r.routable and r.spec not in exclude]
            if not cands:
                cands = [r for r in self.replicas if r.routable]
            if max_inflight is not None:
                cands = [r for r in cands if r.inflight < max_inflight]
            if not cands:
                return None
            best = min(cands, key=lambda r: r.inflight)
            best.inflight += 1
            return best

    def _scaleup_headroom(self) -> bool:
        """True while an armed autoscaler could still add a replica —
        the only condition under which a shard holds for idle capacity
        instead of committing to a busy queue."""
        asc = self.autoscaler
        if asc is None:
            return False
        with self._state_lock:
            total = len(self.replicas)
        return total < asc.config.max_replicas

    def _release_replica(self, r: ReplicaState) -> None:
        with self._state_lock:
            r.inflight = max(0, r.inflight - 1)

    # -------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._draining.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            with self._conn_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="racon-tpu-router-conn",
                                 daemon=True)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while True:
                try:
                    req = recv_frame(conn, self.config.max_frame)
                except ProtocolError as exc:
                    with contextlib.suppress(OSError):
                        send_frame(conn,
                                   error_response(exc.code, str(exc)))
                    if not exc.resync:
                        return
                    continue
                except OSError:
                    return
                if req is None:
                    return
                try:
                    resp = self._dispatch(req, conn, send_lock)
                except Exception as exc:  # noqa: BLE001 — typed answer
                    resp = error_response(
                        "internal", f"{type(exc).__name__}: {exc}")
                try:
                    with send_lock:
                        send_frame(conn, resp)
                except ProtocolError as exc:
                    with contextlib.suppress(OSError):
                        send_frame(conn,
                                   error_response(exc.code, str(exc)))
                except OSError:
                    return
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            with contextlib.suppress(OSError):
                conn.close()

    def _dispatch(self, req: dict, conn: socket.socket,
                  send_lock: threading.Lock) -> dict:
        rtype = req.get("type")
        if rtype == "submit":
            return self._submit(req, conn, send_lock)
        if rtype == "ping":
            return {"type": "pong", "router": True,
                    "replicas": len(self.replicas),
                    "routable": self._routable_count(),
                    "uptime_s": round(
                        time.perf_counter() - self._t_start, 3),
                    "mono_s": time.perf_counter()}
        if rtype == "healthz":
            return dict(self.healthz_snapshot(), type="healthz")
        if rtype == "stats":
            return dict(self.stats_snapshot(), type="stats")
        if rtype == "scrape":
            return {"type": "metrics",
                    "content_type": obs_prom.CONTENT_TYPE,
                    "text": self.prometheus_text()}
        if rtype == "cancel":
            return self._cancel_parent(req)
        if rtype == "shutdown":
            threading.Thread(target=self.drain,
                             name="racon-tpu-router-drain",
                             daemon=True).start()
            return {"type": "ok", "message": "draining"}
        return error_response("bad-request",
                              f"unknown request type {rtype!r}")

    def healthz_snapshot(self) -> dict:
        with self._state_lock:
            routable = sum(1 for r in self.replicas if r.routable)
            draining = sum(1 for r in self.replicas if r.draining)
            down = sum(1 for r in self.replicas
                       if not r.ok or r.down_forced)
            outstanding = self._requeued_outstanding
            inflight = self._inflight_jobs
        self_draining = self._draining.is_set()
        return {"ok": routable > 0 and not self_draining,
                "draining": self_draining,
                "router": True,
                "replicas": len(self.replicas),
                "routable": routable,
                "replicas_draining": draining,
                "replicas_down": down,
                "requeued_outstanding": outstanding,
                "inflight": inflight,
                "uptime_s": round(
                    time.perf_counter() - self._t_start, 3),
                **({"autoscale": self.autoscaler.snapshot()}
                   if self.autoscaler is not None else {})}

    def stats_snapshot(self) -> dict:
        with self._state_lock:
            replicas = [{"endpoint": r.spec, "ok": r.ok,
                         "draining": r.draining,
                         "down_forced": r.down_forced,
                         "inflight": r.inflight, "error": r.error}
                        for r in self.replicas]
            counters = dict(self.counters)
            counters["requeued_outstanding"] = self._requeued_outstanding
        return {"router": dict(counters,
                               inflight_jobs=self._inflight_jobs,
                               uptime_s=round(
                                   time.perf_counter() - self._t_start,
                                   3)),
                "replicas": replicas}

    def prometheus_text(self) -> str:
        """The router's /metrics body: the replicas' scrapes federated
        through the fleet aggregator (counters/gauges summed, histogram
        buckets pooled — the PR-12 merge), plus the router's own
        ``racon_tpu_router_*`` families."""
        body = ""
        with contextlib.suppress(Exception):
            body = self.fleet.prometheus_text()
        with self._state_lock:
            counters = {
                "router.jobs.submitted": self.counters["jobs_submitted"],
                "router.jobs.completed": self.counters["jobs_completed"],
                "router.jobs.failed": self.counters["jobs_failed"],
                "router.shards_dispatched": (
                    self.counters["shards_dispatched"],
                    "child jobs sent to replicas (requeues re-count)"),
                "router.parts_routed": (
                    self.counters["parts_routed"],
                    "contigs forwarded to clients exactly once (the "
                    "requeue dedupe ledger's routed count)"),
                "router.requeues": (
                    self.counters["requeues"],
                    "shards re-dispatched after a replica loss"),
            }
            gauges = {
                "router.replicas": (
                    len(self.replicas), "configured replicas"),
                "router.replicas_routable": (
                    sum(1 for r in self.replicas if r.routable),
                    "replicas accepting new shards at the last probe"),
                "router.replicas_draining": sum(
                    1 for r in self.replicas if r.draining),
                "router.requeued_outstanding": (
                    self._requeued_outstanding,
                    "requeued shards not yet re-completed"),
                "router.inflight_jobs": self._inflight_jobs,
                "router.uptime_seconds": round(
                    time.perf_counter() - self._t_start, 3),
            }
        if self.autoscaler is not None:
            # armed-only families: exposition without --autoscale stays
            # byte-identical (the serve-plane scrape discipline)
            snap = self.autoscaler.snapshot()
            counters["router.autoscale.scale_ups"] = (
                snap["scale_ups"], "replicas spawned on pressure")
            counters["router.autoscale.scale_downs"] = (
                snap["scale_downs"], "replicas drained on idle")
            gauges["router.autoscale.spawned"] = (
                snap["spawned"], "autoscaler-owned replicas alive")
            gauges["router.autoscale.pressure"] = (
                snap["pressure"], "queued+inflight jobs per routable "
                "replica at the last poll")
        return body + obs_prom.render(counters, gauges)

    def _start_metrics_http(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        router = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path in ("/metrics", "/"):
                        body = router.prometheus_text().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         obs_prom.CONTENT_TYPE)
                    elif path == "/healthz":
                        doc = router.healthz_snapshot()
                        body = (json.dumps(doc, sort_keys=True)
                                + "\n").encode()
                        self.send_response(200 if doc["ok"] else 503)
                        self.send_header("Content-Type",
                                         "application/json")
                    else:
                        self.send_error(404)
                        return
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as exc:  # noqa: BLE001
                    with contextlib.suppress(Exception):
                        self.send_error(
                            500, f"{type(exc).__name__}: {exc}")

            def log_message(self, *args):
                pass

        httpd = ThreadingHTTPServer(
            ("127.0.0.1", max(0, self.config.metrics_port)), _Handler)
        httpd.daemon_threads = True
        self.config.metrics_port = httpd.server_address[1]
        self._http = httpd
        t = threading.Thread(target=httpd.serve_forever,
                             name="racon-tpu-router-metrics-http",
                             daemon=True)
        t.start()

    # ------------------------------------------------------------------ qos
    def _cancel_parent(self, req: dict) -> dict:
        """Parent-level cancel: mark the fan-out failed (first-wins, so
        a later shard failure cannot overwrite the typed `cancelled`)
        and fan `cancel` frames out to every in-flight child shard by
        child trace id — the shard threads unblock within one replica
        iteration with typed `cancelled` responses."""
        job_id = req.get("job_id")
        trace_id = req.get("trace_id")
        if not job_id and not trace_id:
            return error_response(
                "bad-request", "cancel needs job_id or trace_id")
        with self._state_lock:
            entry = self._active.get(job_id or "")
            if entry is None and trace_id:
                for jid, (tid, m) in self._active.items():
                    if tid == trace_id:
                        job_id, entry = jid, (tid, m)
                        break
        if entry is None:
            return error_response(
                "unknown-job", "no active router job matches",
                job_id=job_id, trace_id=trace_id)
        tid, merge = entry
        merge.fail(_ShardFailure(
            "cancelled", f"job {job_id} cancelled by client"))
        if self.journal is not None:
            self.journal.record("cancelled", job=job_id, trace=tid)
        n = self._cancel_siblings(merge, job_id, tid,
                                  cause_shard=None, code="cancelled")
        return {"type": "ok", "cancelled": "running",
                "job_id": job_id, "shards_cancelled": n}

    def _cancel_siblings(self, merge: _JobMerge, job_id: str,
                         trace_id: str | None,
                         cause_shard: int | None, code: str) -> int:
        """Best-effort cancel RPC to every OTHER in-flight shard's
        replica (by child trace id): a parent doomed by one shard's
        deadline-abort or by a client cancel must stop burning device
        time on its siblings within one iteration, not at their natural
        end."""
        with merge.lock:
            targets = [(k, rep, ctid)
                       for k, (rep, ctid) in merge.dispatched.items()
                       if k != cause_shard]
        tc0 = time.perf_counter()
        for k, replica, child_trace in targets:
            try:
                replica.client(
                    timeout=self.config.probe_timeout_s).cancel(
                    trace_id=child_trace)
            except (ServeError, ProtocolError, OSError):
                continue  # already finished, or the replica is gone
        if targets:
            self.recorder.complete(
                "router.cancel", tc0, time.perf_counter(),
                {"job": job_id, "trace_id": trace_id or job_id,
                 "by_shard": cause_shard, "code": code,
                 "cancelled": len(targets)})
            if self.journal is not None:
                self.journal.record(
                    "siblings-cancelled", job=job_id, trace=trace_id,
                    by_shard=cause_shard, code=code,
                    cancelled=len(targets))
        return len(targets)

    # --------------------------------------------------------------- submit
    def _read_target_contigs(self, path: str) -> list:
        from ..io.parsers import create_sequence_parser

        parser = create_sequence_parser(path, "router")
        contigs: list = []
        parser.parse(contigs, -1)
        return contigs

    @staticmethod
    def _write_shard_targets(contigs: list, n_shards: int,
                             workdir: str) -> list[str]:
        """The wrapper's lo/hi contiguous-block partition over whole
        contigs (wrapper.py — shard outputs concatenated in shard order
        are byte-identical to the unsharded run)."""
        fastq = any(getattr(c, "quality", b"") for c in contigs)
        paths = []
        for k in range(n_shards):
            lo = k * len(contigs) // n_shards
            hi = (k + 1) * len(contigs) // n_shards
            ext = "fastq" if fastq else "fasta"
            path = os.path.join(workdir, f"shard_{k}.{ext}")
            with open(path, "wb") as fh:
                for c in contigs[lo:hi]:
                    if fastq:
                        qual = getattr(c, "quality", b"") \
                            or b"!" * len(c.data)
                        fh.write(b"@" + c.name.encode() + b"\n"
                                 + c.data + b"\n+\n" + qual + b"\n")
                    else:
                        fh.write(b">" + c.name.encode() + b"\n"
                                 + c.data + b"\n")
            paths.append(path)
        return paths

    @staticmethod
    def _write_contig_targets(contigs: list, workdir: str) -> list[str]:
        """Range mode: one FULL-contig target file per contig, shared
        by every range shard of that contig (the child polishes only
        its window slice; ranks and per-window output stay those of
        the whole contig)."""
        fastq = any(getattr(c, "quality", b"") for c in contigs)
        ext = "fastq" if fastq else "fasta"
        paths = []
        for ci, c in enumerate(contigs):
            path = os.path.join(workdir, f"contig_{ci}.{ext}")
            with open(path, "wb") as fh:
                if fastq:
                    qual = getattr(c, "quality", b"") \
                        or b"!" * len(c.data)
                    fh.write(b"@" + c.name.encode() + b"\n"
                             + c.data + b"\n+\n" + qual + b"\n")
                else:
                    fh.write(b">" + c.name.encode() + b"\n"
                             + c.data + b"\n")
            paths.append(path)
        return paths

    @staticmethod
    def _plan_ranges(contigs: list, cap: int,
                     wl: int) -> list[tuple[int, int, int]]:
        """Sub-contig shard plan: split contigs by target-coordinate
        range at window-grid boundaries (the grid is deterministic from
        `window_length`, so split points are exact and every window is
        owned by exactly one shard). Each contig gets >= 1 shard; the
        remaining budget goes greedily to the contig with the most
        windows per shard, and a contig never splits into more shards
        than it has windows. Returns [(contig_index, lo, hi), ...] in
        contig order, lo ascending within a contig."""
        W = [max(1, (len(c.data) + wl - 1) // wl) for c in contigs]
        budget = min(cap, sum(W))
        s = [1] * len(W)
        for _ in range(max(0, budget - len(W))):
            cands = [i for i in range(len(W)) if s[i] < W[i]]
            if not cands:
                break
            i = max(cands, key=lambda i: W[i] / s[i])
            s[i] += 1
        plan: list[tuple[int, int, int]] = []
        for ci, (w_c, s_c) in enumerate(zip(W, s)):
            for j in range(s_c):
                lo = (j * w_c // s_c) * wl
                hi = ((j + 1) * w_c // s_c) * wl
                plan.append((ci, lo, hi))
        return plan

    def _submit(self, req: dict, conn: socket.socket,
                send_lock: threading.Lock) -> dict:
        for key in ("sequences", "overlaps", "target"):
            path = req.get(key)
            if not isinstance(path, str) or not path:
                return error_response("bad-request",
                                      f"missing input path {key!r}")
            if not os.path.isfile(path):
                return error_response(
                    "bad-request", f"{key} file not found: {path}")
        trace_id = req.get("trace_id")
        if trace_id is not None and (
                not isinstance(trace_id, str)
                or not 0 < len(trace_id) <= 64
                or not set(trace_id) <= _TRACE_ID_OK):
            return error_response(
                "bad-request",
                "trace_id must be 1-64 chars of [A-Za-z0-9._-]")
        if self._draining.is_set():
            return error_response("draining", "router is draining")
        with self._state_lock:
            self._job_seq += 1
            job_id = f"r{self._job_seq}"
            self.counters["jobs_submitted"] += 1
            self._inflight_jobs += 1
        want_stream = bool(req.get("stream"))
        want_progress = bool(req.get("progress"))
        t0 = time.perf_counter()
        # the parent's deadline is pinned ABSOLUTE here: every shard
        # dispatch (first or requeued) derives its child deadline_s
        # from what REMAINS of this instant's budget, never a reset one
        deadline_t = None
        if req.get("deadline_s") is not None:
            try:
                deadline_t = t0 + float(req["deadline_s"])
            except (TypeError, ValueError):
                deadline_t = None
        if self.journal is not None:
            self.journal.record("received", job=job_id, trace=trace_id,
                                tenant=req.get("tenant"),
                                target=req.get("target"))
            # "started" immediately: parsing the target IS the router's
            # work, and any failure from here on must legally pair
            # started -> failed under the journal consistency checker
            self.journal.record("started", job=job_id, trace=trace_id)
        workdir = None
        try:
            try:
                contigs = self._read_target_contigs(req["target"])
            except (RaconError, OSError) as exc:
                if self.journal is not None:
                    self.journal.record("failed", job=job_id,
                                        trace=trace_id,
                                        code="bad-request",
                                        message="unreadable target")
                with self._state_lock:
                    self.counters["jobs_failed"] += 1
                return error_response(
                    "bad-request", f"cannot parse target: {exc}",
                    job_id=job_id)
            n_routable = self._routable_count()
            cap = n_routable
            if self.config.max_shards > 0:
                cap = min(cap, self.config.max_shards)
            # sub-contig window-range sharding: when routable replicas
            # exceed the contig count, split the largest contigs by
            # coordinate range at window-grid boundaries — the one-
            # mega-contig job scales past a single replica. Rounds fall
            # back to contig sharding (round 2 would re-map reads onto
            # a segment, which is not what solo rounds compute).
            groups: list[dict] | None = None
            shard_ranges: list[tuple[int, int] | None]
            # fragment read-range sharding (the third planner): a
            # fragment job's targets are its READS — many small
            # records, so the contig planner's whole-record partition
            # would rewrite a multi-GiB read file per shard. Instead
            # every child shares the ORIGINAL target path and carries a
            # [frag_lo, frag_hi) target-INDEX slice at read boundaries
            # (protocol.py "Fragment child jobs"); slices are
            # contiguous and ascending, so shard-order concatenation
            # IS global read order and the classic merge ledger's
            # part-granularity dedupe = read-GROUP granularity.
            fragment = req.get("mode") == "fragment"
            frag_ranges: list[tuple[int, int]] | None = None
            if fragment:
                n_reads = len(contigs)
                n_shards = max(1, min(cap, n_reads))
                shard_ranges = [None] * n_shards
                shard_targets = [req["target"]] * n_shards
                if n_shards > 1:
                    frag_ranges = [(k * n_reads // n_shards,
                                    (k + 1) * n_reads // n_shards)
                                   for k in range(n_shards)]
                    if self.journal is not None:
                        self.journal.record(
                            "frag-plan", job=job_id, trace=trace_id,
                            shards=n_shards, reads=n_reads)
            elif cap > len(contigs) and req.get("rounds") is None:
                wl = 500
                opts_in = req.get("options")
                if isinstance(opts_in, dict):
                    try:
                        wl = max(1, int(opts_in.get(
                            "window_length", 500)))
                    except (TypeError, ValueError):
                        wl = 500
                plan = self._plan_ranges(contigs, cap, wl)
                n_shards = len(plan)
                workdir = tempfile.mkdtemp(
                    prefix=f"racon_tpu_router_{job_id}_")
                contig_paths = self._write_contig_targets(
                    contigs, workdir)
                shard_targets = [contig_paths[ci] for ci, _, _ in plan]
                shard_ranges = [(lo, hi) for _, lo, hi in plan]
                groups = []
                for k, (ci, _lo, _hi) in enumerate(plan):
                    if not groups or groups[-1]["ci"] != ci:
                        groups.append({"ci": ci,
                                       "name": contigs[ci].name,
                                       "shards": []})
                    groups[-1]["shards"].append(k)
                if self.journal is not None:
                    self.journal.record(
                        "range-plan", job=job_id, trace=trace_id,
                        shards=n_shards, contigs=len(contigs),
                        window_length=wl)
            else:
                n_shards = max(1, min(n_routable, len(contigs)))
                if self.config.max_shards > 0:
                    n_shards = min(n_shards, self.config.max_shards)
                shard_ranges = [None] * n_shards
                if n_shards > 1:
                    workdir = tempfile.mkdtemp(
                        prefix=f"racon_tpu_router_{job_id}_")
                    shard_targets = self._write_shard_targets(
                        contigs, n_shards, workdir)
                else:
                    shard_targets = [req["target"]]
            opts_in = req.get("options") or {}
            if not isinstance(opts_in, dict):
                opts_in = {}
            n_contigs = len(contigs)
            del contigs  # the shard files own the bytes now
            # plan span: target parse + shard planning + shard-target
            # writes, from the submit's t0 — the first hop of the
            # routed job's distributed trace
            self.recorder.complete(
                "router.plan", t0, time.perf_counter(),
                {"job": job_id, "trace_id": trace_id or job_id,
                 "mode": ("fragment" if fragment
                          else "range" if groups is not None
                          else "contig"),
                 "shards": n_shards, "contigs": n_contigs})
            requeues_before = self.counters["requeues"]
            emit_part = None
            if want_stream:
                def emit_part(k, part_index, name, fasta):
                    frame = {"type": "result_part", "job_id": job_id,
                             "part": part_index, "name": name,
                             "fasta": fasta, "shard": k}
                    if trace_id:
                        frame["trace_id"] = trace_id
                    try:
                        with send_lock:
                            send_frame(conn, frame)
                    except (ProtocolError, OSError):
                        pass  # client gone: shards still finish

            def on_routed(k, part_index, name, nbytes, **extra):
                with self._state_lock:
                    self.counters["parts_routed"] += 1
                self.recorder.instant(
                    "router.stream",
                    {"job": job_id, "trace_id": trace_id or job_id,
                     "shard": k, "part": part_index, "bytes": nbytes})
                if self.journal is not None:
                    # range mode adds lo/hi: one `part-routed` line per
                    # accepted SEGMENT (post-dedupe), which is what
                    # obsreport's segment-receipt check tiles per contig
                    self.journal.record("part-routed", job=job_id,
                                        trace=trace_id, shard=k,
                                        part=part_index, name=name,
                                        bytes=nbytes, **extra)

            merge = _JobMerge(
                n_shards, emit_part=emit_part, on_routed=on_routed,
                groups=groups,
                fragment_correction=bool(
                    opts_in.get("fragment_correction")),
                drop_unpolished=not opts_in.get(
                    "include_unpolished", False))
            with self._state_lock:
                self._active[job_id] = (trace_id, merge)
            threads = []
            for k in range(n_shards):
                t = threading.Thread(
                    target=self._run_shard,
                    args=(req, job_id, trace_id, k, n_shards,
                          shard_targets[k], merge, conn, send_lock,
                          want_progress, deadline_t, shard_ranges[k],
                          frag_ranges[k] if frag_ranges is not None
                          else None),
                    name=f"racon-tpu-router-{job_id}-s{k}", daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join()

            if merge.failure is not None:
                f = merge.failure
                if self.journal is not None:
                    self.journal.record("failed", job=job_id,
                                        trace=trace_id, code=f.code,
                                        message=str(f))
                with self._state_lock:
                    self.counters["jobs_failed"] += 1
                return error_response(f.code, str(f), job_id=job_id,
                                      **f.extra)

            wall_s = time.perf_counter() - t0
            tm0 = time.perf_counter()
            job_requeues = self.counters["requeues"] - requeues_before
            queue_wait = 0.0
            exec_max = 0.0
            metrics: dict = {}
            rounds_req = rounds_comp = 0
            cache_hits = cache_misses = 0
            rounds_cached = False
            for resp in merge.results:
                serve = (resp or {}).get("serve") or {}
                queue_wait = max(queue_wait,
                                 float(serve.get("queue_wait_s", 0.0)))
                exec_max = max(exec_max,
                               float(serve.get("exec_s", 0.0)))
                for mk, mv in ((resp or {}).get("metrics") or {}).items():
                    if isinstance(mv, (int, float)):
                        metrics[mk] = metrics.get(mk, 0) + mv
                # each shard ran its own rounds over its contig subset:
                # requested/completed agree across shards (max keeps a
                # partial pre-rounds replica from zeroing the block),
                # cache hit/miss totals sum
                rb = (resp or {}).get("rounds") or {}
                if rb:
                    rounds_req = max(rounds_req,
                                     int(rb.get("requested", 0)))
                    rounds_comp = max(rounds_comp,
                                      int(rb.get("completed", 0)))
                    cache = rb.get("cache")
                    if cache:
                        rounds_cached = True
                        cache_hits += int(cache.get("hits", 0))
                        cache_misses += int(cache.get("misses", 0))
            out = {"type": "result", "job_id": job_id,
                   "serve": {"queue_wait_s": round(queue_wait, 4),
                             "exec_s": round(exec_max, 4)},
                   "router": {"shards": n_shards,
                              "replicas": n_routable,
                              "requeues": job_requeues,
                              "parts": merge.total_routed,
                              "wall_s": round(wall_s, 4),
                              "shard_exec_max_s": round(exec_max, 4)}}
            if groups is not None:
                out["router"]["range"] = True
                out["router"]["range_shards"] = n_shards
                out["router"]["segments"] = merge.segments_routed
            if fragment:
                out["router"]["fragment"] = True
                out["router"]["frag_shards"] = n_shards
                out["router"]["reads"] = merge.reads_routed
            if trace_id:
                out["trace_id"] = trace_id
            if metrics:
                out["metrics"] = metrics
            if rounds_req:
                # no merged per_round: shard walls overlap in time, so
                # per-round walls only mean something per replica
                out["rounds"] = {"requested": rounds_req,
                                 "completed": rounds_comp}
                if rounds_cached:
                    out["rounds"]["cache"] = {"hits": cache_hits,
                                              "misses": cache_misses}
            if want_stream:
                out["streamed"] = True
                out["parts"] = merge.total_routed
            else:
                out["fasta"] = merge.fasta()
            # merge span: stats aggregation + group assembly/concat +
            # result-frame build — the final hop before the reply
            self.recorder.complete(
                "router.merge", tm0, time.perf_counter(),
                {"job": job_id, "trace_id": trace_id or job_id,
                 "shards": n_shards, "parts": merge.total_routed})
            if req.get("trace"):
                self._attach_trace(out, merge, job_id, trace_id)
            if self.journal is not None:
                self.journal.record(
                    "finished", job=job_id,
                    trace=trace_id, shards=n_shards,
                    parts=merge.total_routed,
                    segments=(merge.segments_routed
                              if groups is not None else None),
                    requeues=job_requeues,
                    wall_s=round(wall_s, 4))
            with self._state_lock:
                self.counters["jobs_completed"] += 1
            return out
        finally:
            with self._state_lock:
                self._active.pop(job_id, None)
                self._inflight_jobs = max(0, self._inflight_jobs - 1)
            if workdir is not None:
                shutil.rmtree(workdir, ignore_errors=True)

    def _attach_trace(self, out: dict, merge: _JobMerge, job_id: str,
                      trace_id: str | None) -> None:
        """Trace collection for a routed `--trace-out` job: clock-sync
        and `trace_pull` every replica that completed a shard, then
        embed router spans + per-replica span sets in the result frame
        so the CLIENT can merge everything onto its own timeline
        (client.merge_trace — one process track per replica).

        The child submits deliberately do NOT carry `trace: true`:
        obs.trace.scoped serializes on a module lock, so a traced child
        would serialize same-replica shards. The replica's ALWAYS-ON
        flight ring supplies the spans instead — serve.queue_wait /
        serve.job / serve.iteration all carry the child trace ids —
        and the pull costs the replica nothing it was not already
        paying. Each replica is pulled for EXACTLY the child trace ids
        it finished (merge.shard_owner), never the whole parent prefix:
        a lost attempt's partial spans on a doomed replica would skew
        the critical-path sums, and in-process replica fixtures share
        one flight ring, where a prefix pull would return every
        sibling's spans on every track. Best-effort per replica: one
        that died after a requeue simply contributes no track.
        `offset_s` is the replica clock relative to the ROUTER; the
        client chains it with its own router handshake offset."""
        tid = trace_id or job_id
        pulls = []
        tp0 = time.perf_counter()
        with merge.lock:
            owners = dict(merge.shard_owner)
            seen = dict(merge.replicas_seen)
        per_rep: dict[str, list[str]] = {}
        for k in sorted(owners):
            spec, ctid = owners[k]
            per_rep.setdefault(spec, []).append(ctid)
        for spec in sorted(per_rep):
            replica = seen.get(spec)
            if replica is None:
                continue
            try:
                cl = replica.client(timeout=self.config.probe_timeout_s)
                sync = cl.clock_sync()
                resp = cl.request({"type": "trace_pull",
                                   "trace_id": tid,
                                   "trace_ids": per_rep[spec]})
            except (ServeError, ProtocolError, OSError):
                continue
            if resp.get("base_mono") is None:
                continue  # flight ring disabled on that replica
            pulls.append({"replica": spec,
                          "events": resp.get("events") or [],
                          "base_mono": resp["base_mono"],
                          "offset_s": round(float(sync["offset_s"]), 6),
                          "rtt_s": round(float(sync["rtt_s"]), 6)})
        self.recorder.complete(
            "router.trace_pull", tp0, time.perf_counter(),
            {"job": job_id, "trace_id": tid, "replicas": len(pulls)})
        out["trace"] = obs_flight.trace_events(self.recorder, tid)
        out["trace_base_mono"] = self.recorder._base
        if pulls:
            out["trace_replicas"] = pulls
        # per-shard serve stats ride along (traced jobs only — the
        # flagless frame is pinned byte-identical): tracereport's
        # span-sums-vs-stage_stats consistency check needs each
        # shard's device_s/queue_wait next to the spans
        detail = []
        for kk, resp in enumerate(merge.results):
            serve = (resp or {}).get("serve") or {}
            detail.append({"shard": kk,
                           "queue_wait_s": serve.get("queue_wait_s"),
                           "exec_s": serve.get("exec_s"),
                           "batch": serve.get("batch")})
        out["router"]["shards_detail"] = detail

    def _run_shard(self, req: dict, job_id: str, trace_id: str | None,
                   k: int, n_shards: int, shard_target: str,
                   merge: _JobMerge, conn: socket.socket,
                   send_lock: threading.Lock, want_progress: bool,
                   deadline_t: float | None = None,
                   rng: tuple[int, int] | None = None,
                   frng: tuple[int, int] | None = None) -> None:
        """One shard's dispatch loop: submit to the least-loaded
        routable replica, stream parts into the merge, and on replica
        loss requeue to a healthy one (journal-backed, dedupe by the
        merge ledger) up to `shard_retries` times. QoS rides every
        attempt: `deadline_t` is the parent's ABSOLUTE deadline, so the
        child's `deadline_s` is recomputed to the REMAINING budget at
        each dispatch (a requeued shard inherits what is left, never a
        reset deadline), and a typed `cancelled`/`deadline-doomed`
        child failure fans cancels out to the sibling shards."""
        child: dict = {"type": "submit",
                       "sequences": req["sequences"],
                       "overlaps": req["overlaps"],
                       "target": shard_target,
                       "stream": True,
                       "parent": job_id, "shard": k, "shards": n_shards,
                       "trace_id": f"{trace_id or job_id}.s{k}"}
        for key in ("options", "priority", "fault_plan",
                    "strict", "tenant", "rounds", "mode",
                    "ingest", "subsample", "normalize"):
            if req.get(key) is not None:
                child[key] = req[key]
        if rng is not None:
            # window-range shard: the child polishes only the target
            # windows whose grid start falls in [lo, hi) and streams
            # raw segments with stitch accounting (protocol.py
            # "Child-job fields"); never combined with rounds (range
            # plans are only built for round-less submits)
            child["range_lo"], child["range_hi"] = rng
        if frng is not None:
            # fragment read-range shard: the child shares the parent's
            # target file and corrects only the reads whose file index
            # falls in [frag_lo, frag_hi) — group frames come back
            # with GLOBAL `frag` receipts (the server rebases by
            # frag_lo), so the merge ledger tiles the read axis
            child["frag_lo"], child["frag_hi"] = frng
        if want_progress:
            child["progress"] = True

        def on_progress(frame):
            fwd = dict(frame, job_id=job_id, shard=k)
            try:
                with send_lock:
                    send_frame(conn, fwd)
            except (ProtocolError, OSError):
                pass

        losses = 0
        busy_waits = 0
        requeued_pending = False
        exclude: set[str] = set()
        wait_deadline = time.monotonic() + self.config.replica_wait_s
        # autoscale hold: while the fleet can still grow, insist on an
        # idle replica for up to hold_s before settling for a busy one
        # — the held shard counts as backlog (autoscale._signals), so
        # holding is what summons the scale-up it waits for. A busy
        # replica serializes device phases anyway, so the hold costs
        # nothing when no capacity arrives: the first replica to go
        # idle (old or new) is taken within one 0.1s poll.
        asc = self.autoscaler
        hold_deadline = (
            time.monotonic() + asc.config.hold_s
            if asc is not None and asc.config.hold_s > 0 else None)
        waiting_flagged = False

        def _set_waiting(on: bool):
            nonlocal waiting_flagged
            if on == waiting_flagged:
                return
            with self._state_lock:
                self._dispatch_waiting = max(
                    0, self._dispatch_waiting + (1 if on else -1))
            waiting_flagged = on

        def settle():
            _set_waiting(False)
            if requeued_pending:
                with self._state_lock:
                    self._requeued_outstanding = max(
                        0, self._requeued_outstanding - 1)

        #: dispatch-span clock: each attempt's `router.dispatch` span
        #: runs from here to the moment a replica is picked, so the
        #: busy-wait AND the autoscale hold both show up as span width
        attempt_t0 = time.perf_counter()
        held = False  # the autoscale hold actually engaged this attempt
        while True:
            if merge.failure is not None:
                # another shard (or a parent-level cancel) already
                # doomed the job: do not dispatch more device work
                settle()
                return
            if deadline_t is not None:
                remaining = deadline_t - time.perf_counter()
                if remaining <= 0:
                    merge.fail(_ShardFailure(
                        "deadline-doomed",
                        f"shard {k}: parent deadline budget exhausted "
                        f"before dispatch",
                        remaining_s=round(remaining, 3)))
                    self._cancel_siblings(merge, job_id, trace_id, k,
                                          "deadline-doomed")
                    settle()
                    return
                # requeued shards inherit the REMAINING parent budget
                child["deadline_s"] = round(remaining, 4)
            hold = (hold_deadline is not None
                    and time.monotonic() < hold_deadline
                    and not self._draining.is_set()
                    and self._scaleup_headroom())
            replica = self._pick_replica(
                exclude, max_inflight=1 if hold else None)
            if replica is None:
                if hold or (time.monotonic() < wait_deadline
                            and not self._draining.is_set()):
                    held = held or hold
                    _set_waiting(True)
                    time.sleep(0.1)
                    continue
                merge.fail(_ShardFailure(
                    "no-replica",
                    f"shard {k}: no routable replica within "
                    f"{self.config.replica_wait_s:g}s"))
                settle()
                return
            _set_waiting(False)
            picked_t = time.perf_counter()
            held_s = picked_t - attempt_t0
            # dispatch span: replica acquisition for this attempt —
            # width IS the wait (busy-wait + autoscale hold); `held`
            # says the PR-18 idle-hold specifically engaged
            self.recorder.complete(
                "router.dispatch", attempt_t0, picked_t,
                {"job": job_id, "trace_id": child["trace_id"],
                 "shard": k, "replica": replica.spec,
                 "held_s": round(held_s, 4), "held": held,
                 "attempt": losses + busy_waits})
            with self._state_lock:
                self.counters["shards_dispatched"] += 1
            if self.journal is not None:
                self.journal.record("shard-dispatched", job=job_id,
                                    trace=trace_id, shard=k,
                                    replica=replica.spec,
                                    attempt=losses + busy_waits)
                if held:
                    # annotation twin of the span: obsreport timelines
                    # and the autoscale balance check read this
                    self.journal.record("hold", job=job_id,
                                        trace=trace_id, shard=k,
                                        held_s=round(held_s, 4))
            with merge.lock:
                merge.dispatched[k] = (replica, child["trace_id"])
                merge.replicas_seen[replica.spec] = replica
            lost = False
            try:
                resp = replica.client().request(
                    child,
                    on_part=lambda f: merge.on_part(k, f),
                    on_progress=on_progress if want_progress else None)
                # shard span: the child request's full wall on the
                # replica — the critical-path unit tracereport walks
                self.recorder.complete(
                    "router.shard", picked_t, time.perf_counter(),
                    {"job": job_id, "trace_id": child["trace_id"],
                     "shard": k, "replica": replica.spec,
                     "outcome": "ok",
                     "parts": len(resp.get("_parts") or ())})
                with merge.lock:
                    merge.shard_owner[k] = (replica.spec,
                                            child["trace_id"])
                merge.shard_done(k, resp)
                if self.journal is not None:
                    self.journal.record(
                        "shard-finished", job=job_id, trace=trace_id,
                        shard=k, replica=replica.spec,
                        parts=len(resp.get("_parts") or ()))
                settle()
                return
            except JobFailed as exc:
                merge.fail(_ShardFailure(
                    "job-failed", f"shard {k}: {exc}",
                    error_type=exc.error_type))
                settle()
                return
            except ServerDraining:
                # rolling restart in progress: this replica stopped
                # admitting — route the shard elsewhere, no loss
                exclude.add(replica.spec)
                attempt_t0 = time.perf_counter()
                held = False
                continue
            except QueueFull as exc:
                busy_waits += 1
                if busy_waits > 50:
                    merge.fail(_ShardFailure(
                        "queue-full",
                        f"shard {k}: replicas stayed full"))
                    settle()
                    return
                # the backoff is capacity wait: charge it to the NEXT
                # attempt's dispatch span
                attempt_t0 = time.perf_counter()
                held = False
                time.sleep(_retry_delay(exc.retry_after))
                continue
            except ServeError as exc:
                if exc.code == "closed":
                    lost = True
                else:
                    merge.fail(_ShardFailure(
                        exc.code, f"shard {k}: {exc}"))
                    if exc.code in ("cancelled", "deadline-doomed"):
                        # a doomed or cancelled child dooms the parent:
                        # stop the sibling shards within one iteration
                        self._cancel_siblings(merge, job_id, trace_id,
                                              k, exc.code)
                    settle()
                    return
            except (ProtocolError, OSError):
                lost = True
            finally:
                with merge.lock:
                    merge.dispatched.pop(k, None)
                self._release_replica(replica)
            if not lost:
                return  # unreachable, but keeps the loop shape honest
            # ---- replica loss: mark down, requeue with ledger dedupe
            self.recorder.complete(
                "router.shard", picked_t, time.perf_counter(),
                {"job": job_id, "trace_id": child["trace_id"],
                 "shard": k, "replica": replica.spec,
                 "outcome": "lost"})
            with self._state_lock:
                replica.down_forced = True
            if self.journal is not None:
                self.journal.record("replica-down", replica=replica.spec,
                                    job=job_id, shard=k)
            log_info(f"[racon_tpu::router] replica {replica.spec} lost "
                     f"mid-shard ({job_id} shard {k})")
            losses += 1
            if losses > self.config.shard_retries:
                merge.fail(_ShardFailure(
                    "replica-lost",
                    f"shard {k}: lost {losses} replicas "
                    f"(retry limit {self.config.shard_retries})"))
                settle()
                return
            with self._state_lock:
                self.counters["requeues"] += 1
                if not requeued_pending:
                    self._requeued_outstanding += 1
                    requeued_pending = True
            if self.journal is not None:
                self.journal.record("requeued", job=job_id,
                                    trace=trace_id, shard=k,
                                    from_replica=replica.spec)
            merge.requeue(k)
            self.recorder.instant(
                "router.requeue",
                {"job": job_id, "trace_id": child["trace_id"],
                 "shard": k, "from": replica.spec, "losses": losses})
            exclude.add(replica.spec)
            wait_deadline = time.monotonic() + self.config.replica_wait_s
            attempt_t0 = time.perf_counter()
            held = False


# ------------------------------------------------------------------ CLI
def router_main(argv: list[str]) -> int:
    """`racon_tpu router` entry point: run a PolishRouter until
    SIGTERM / SIGINT, then drain."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="racon_tpu router",
        description="shard-aware front-end over N warm `racon_tpu "
                    "serve` replicas: contig-sharded fan-out, "
                    "journal-backed requeue on replica loss, rolling "
                    "restarts without job loss (README 'Serving')")
    ap.add_argument("--replicas", default=None,
                    help="comma-separated replica RPC endpoints — unix "
                         "socket paths or localhost host:port "
                         "(RACON_TPU_ROUTER_REPLICAS)")
    ap.add_argument("--socket", default=None,
                    help=f"router unix socket (RACON_TPU_ROUTER_SOCKET, "
                         f"default {DEFAULT_ROUTER_SOCKET})")
    ap.add_argument("--port", type=int, default=None,
                    help="listen on localhost TCP instead "
                         "(RACON_TPU_ROUTER_PORT; 0 = ephemeral)")
    ap.add_argument("--journal", default=None,
                    help="durable JSONL retry ledger + lifecycle "
                         "journal (RACON_TPU_ROUTER_JOURNAL; pair with "
                         "RACON_TPU_JOURNAL_FSYNC=1 for per-record "
                         "fsync; an unwritable path fails the start)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="federated /metrics + /healthz over the "
                         "replicas plus racon_tpu_router_* families "
                         "(RACON_TPU_ROUTER_METRICS_PORT; 0 = "
                         "ephemeral)")
    ap.add_argument("--health-interval", type=float, default=None,
                    help="replica healthz/scrape poll seconds "
                         "(RACON_TPU_ROUTER_HEALTH_INTERVAL, default "
                         "2)")
    ap.add_argument("--max-shards", type=int, default=None,
                    help="cap shards per job "
                         "(RACON_TPU_ROUTER_MAX_SHARDS, default 0 = "
                         "one per routable replica)")
    ap.add_argument("--shard-retries", type=int, default=None,
                    help="replica losses tolerated per shard before "
                         "the job fails (RACON_TPU_ROUTER_RETRIES, "
                         "default 3)")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="dump the router's own flight ring (plan/"
                         "dispatch/stream/merge/requeue spans per "
                         "routed job) as Chrome-trace JSON at stop "
                         "(RACON_TPU_ROUTER_TRACE)")
    ap.add_argument("--autoscale", action="store_true",
                    help="arm the elastic-fleet loop: spawn warm "
                         "replicas on sustained backlog pressure or a "
                         "firing deadline burn-rate alert, drain the "
                         "newest spawned replica after sustained idle "
                         "(RACON_TPU_ROUTER_AUTOSCALE_* knobs, README "
                         "'Elastic fleet')")
    ap.add_argument("--autoscale-min", type=int, default=None,
                    help="autoscaler fleet floor "
                         "(RACON_TPU_ROUTER_AUTOSCALE_MIN, default 1)")
    ap.add_argument("--autoscale-max", type=int, default=None,
                    help="autoscaler fleet ceiling "
                         "(RACON_TPU_ROUTER_AUTOSCALE_MAX, default 4)")
    args = ap.parse_args(argv)

    kw: dict = {}
    if args.replicas is not None:
        kw["replicas"] = args.replicas
    if args.socket is not None:
        kw["socket_path"] = args.socket
    if args.port is not None:
        kw["port"] = args.port
    if args.journal is not None:
        kw["journal"] = args.journal
    if args.metrics_port is not None:
        kw["metrics_port"] = args.metrics_port
    if args.health_interval is not None:
        kw["health_interval_s"] = args.health_interval
    if args.max_shards is not None:
        kw["max_shards"] = args.max_shards
    if args.shard_retries is not None:
        kw["shard_retries"] = args.shard_retries
    if args.trace is not None:
        kw["trace_path"] = args.trace

    try:
        router = PolishRouter(**kw).start()
    except (RaconError, OSError, ValueError) as exc:
        print(f"[racon_tpu::router] error: {exc}", file=sys.stderr)
        return 1

    scaler = None
    if args.autoscale:
        from .autoscale import Autoscaler

        as_kw: dict = {}
        if args.autoscale_min is not None:
            as_kw["min_replicas"] = args.autoscale_min
        if args.autoscale_max is not None:
            as_kw["max_replicas"] = args.autoscale_max
        try:
            scaler = Autoscaler(router, **as_kw).start()
        except RaconError as exc:
            print(f"[racon_tpu::router] error: {exc}", file=sys.stderr)
            router.drain()
            return 1

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    while not stop.is_set() and not router._stopped.is_set():
        stop.wait(0.2)
    if scaler is not None:
        scaler.close()
    router.drain()
    return 0
