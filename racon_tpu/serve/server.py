"""PolishServer: a long-lived, warm polishing job server.

The one-shot CLI pays engine construction, XLA compilation and ladder
warmup on EVERY run — the cost profile a high-traffic service cannot
afford (PR 3 measured warm-vs-cold precompile at 0.67 s vs 1.79 s, and
that is before interpreter + jax import). `PolishServer` keeps one
process alive and multiplexes many polish requests through it:

  - ONE warm engine set: the persistent compile cache and the adaptive-
    ladder posture are armed at startup, a synthetic warmup job runs the
    full path once, and every later job reuses the process-level jit
    caches — the warm submit path compiles nothing (asserted via the
    sched compile telemetry in tools/servebench.py).
  - requests flow through a bounded `JobQueue` (admission control with
    retry-after, FIFO-within-priority, per-job deadlines) to a small
    worker pool;
  - concurrent jobs' windows pool into the continuous `WindowBatcher`:
    a persistent device feeder packs bounded shape-homogeneous
    iterations, so late arrivals join the next dispatch instead of a
    round barrier (byte-identical per-job output), finished contigs
    stitch immediately and can stream to the client as `result_part`
    frames before the job completes;
  - per-tenant weighted fair scheduling on the queue (submit frames
    carry a `tenant` id; RACON_TPU_SERVE_TENANT_WEIGHTS) keeps one
    heavy client from monopolizing the feeder;
  - SIGTERM (or a `shutdown` request) triggers graceful drain: stop
    admitting, finish in-flight jobs, flush metrics/trace, exit;
  - per-job failure isolation: a job's `DeviceError` / quarantine storm
    (fault-injectable per job via its OWN fault plan) produces one typed
    error response; the server, its warm engines and concurrent jobs
    survive.

What is NOT isolated: jobs share one process, one device, one host
thread pool and one jit cache — a hard process crash (OOM, native
segfault) takes every in-flight job down. The serve layer trades that
blast radius for warmth; run several servers for fault domains.

Transport: a unix socket (default) or localhost TCP, length-prefixed
JSON frames (serve/protocol.py). `racon_tpu.cli serve` is the CLI
surface; `serve.client.PolishClient` the Python one.
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
import random
import socket
import sys
import tempfile
import threading
import time

from collections import deque

from ..errors import RaconError
from ..obs import fleet as obs_fleet
from ..obs import flight as obs_flight
from ..obs import prom as obs_prom
from ..obs import trace as obs_trace
from ..obs.hist import HistogramSet
from ..obs.journal import Journal
from ..resilience import strict_scope
from ..utils.logger import log_info
from .batcher import WindowBatcher
from .protocol import (ProtocolError, error_response, max_frame_bytes,
                       recv_frame, send_frame)
from .queue import (DeadlineDoomed, Draining, Job, JobCancelledError,
                    JobQueue, QueueFull, TenantQuotaExceeded)

#: request option keys a submit may carry; anything else is rejected
#: with `bad-request` (a typo'd knob must not silently polish with
#: defaults)
ALLOWED_OPTIONS = frozenset((
    "window_length", "quality_threshold", "error_threshold", "trim",
    "match", "mismatch", "gap", "fragment_correction",
    "include_unpolished", "tpu_poa_batches", "tpu_banded_alignment",
    "tpu_aligner_batches", "tpu_aligner_band_width", "tpu_engine",
    "tpu_pipeline_depth", "tpu_device_timeout"))

DEFAULT_SOCKET = "/tmp/racon_tpu_serve.sock"

#: hard cap on `rounds=N` per submit — polishing converges in 2-4
#: rounds in practice; a runaway N must not pin a worker forever
MAX_ROUNDS = 64


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _deprecated_knob(name: str, what: str) -> None:
    """Round-barrier-era knobs are deprecated loudly, never silently
    ignored: a Python warning for programmatic users plus a stderr line
    for operators."""
    import warnings

    warnings.warn(f"{name} is deprecated since the continuous-batching "
                  f"rework: {what}", DeprecationWarning, stacklevel=3)
    log_info(f"[racon_tpu::serve] warning: {name} is deprecated "
             f"({what})")


def _parse_tenant_weights(raw) -> dict:
    """Tenant weight table from a dict or a "a=4,b=1,default=1" string.
    Strict: malformed entries fail ServeConfig (startup), mirroring the
    --metrics-port discipline."""
    if not raw:
        return {}
    if isinstance(raw, dict):
        items = raw.items()
    else:
        items = []
        for part in str(raw).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise RaconError(
                    "ServeConfig",
                    f"invalid tenant weight entry {part!r} "
                    "(expected tenant=weight)")
            items.append(part.split("=", 1))
    out: dict = {}
    for tenant, weight in items:
        try:
            w = float(weight)
        except (TypeError, ValueError):
            raise RaconError(
                "ServeConfig",
                f"invalid tenant weight {weight!r} for tenant "
                f"{tenant!r} (expected a number)") from None
        if w <= 0:
            raise RaconError(
                "ServeConfig",
                f"tenant weight for {tenant!r} must be positive, "
                f"got {w}")
        out[str(tenant)] = w
    return out


class ServeConfig:
    """Server posture: transport, capacity, and the polish defaults jobs
    inherit when their request omits an option. Every field defaults
    from its RACON_TPU_SERVE_* env knob so a bare `racon_tpu serve` is
    deployable; constructor kwargs win over the environment."""

    def __init__(self, **kw):
        env = os.environ.get
        self.socket_path = kw.pop(
            "socket_path", env("RACON_TPU_SERVE_SOCKET") or DEFAULT_SOCKET)
        # None = unix socket; an int (including 0 = ephemeral, the real
        # port is published back into the config) = localhost TCP
        self.port = kw.pop(
            "port", _env_int("RACON_TPU_SERVE_PORT", -1)
            if env("RACON_TPU_SERVE_PORT") else None)
        self.workers = max(1, kw.pop(
            "workers", _env_int("RACON_TPU_SERVE_WORKERS", 2)))
        self.queue_depth = max(1, kw.pop(
            "queue_depth", _env_int("RACON_TPU_SERVE_QUEUE_DEPTH", 16)))
        self.drain_timeout_s = kw.pop(
            "drain_timeout_s", _env_float("RACON_TPU_SERVE_DRAIN_S", 30.0))
        # continuous-batching feeder knobs (serve/batcher.py):
        # iteration_windows bounds one device iteration's batch,
        # max_wait_s optionally lets a sparse pool coalesce briefly
        # before a short iteration (0 = dispatch the moment work is
        # pending — the default; there is no round gather anymore)
        self.iteration_windows = max(1, kw.pop(
            "iteration_windows",
            _env_int("RACON_TPU_SERVE_ITERATION_WINDOWS", 256)))
        # sub-mesh worker lanes (serve/batcher.py): partition the device
        # list into K independent sub-meshes, each with its own feeder
        # thread + exec lock, so iterations run concurrently across the
        # slice; 1 (the default) keeps the single full-mesh feeder
        self.worker_lanes = max(1, kw.pop(
            "worker_lanes", _env_int("RACON_TPU_WORKER_LANES", 1)))
        # hard per-tenant admission quota (queue.py): cap on QUEUED jobs
        # per tenant, rejected typed with retry_after; 0 = off. Weights
        # shape service order; the quota is the only thing stopping one
        # tenant from filling the whole queue depth
        self.tenant_quota = max(0, kw.pop(
            "tenant_quota",
            _env_int("RACON_TPU_SERVE_TENANT_QUOTA", 0)))
        # QoS layer (queue.py + batcher.py + the cancel RPC), all off
        # by default — with none of the three configured, every serve
        # surface is byte-identical to the pre-QoS server (test-
        # pinned). Strict env parsing throughout, mirroring the
        # --metrics-port / RACON_TPU_WINCACHE discipline: a typo'd
        # operator value fails the start, never silently serves with
        # QoS half-armed.
        # preempt: a newly admitted higher-priority job may preempt a
        # running lower-priority one (its not-yet-dispatched windows
        # park between iterations; it resumes byte-identically when
        # capacity frees)
        if "preempt" in kw:
            self.preempt = bool(kw.pop("preempt"))
        else:
            raw = env("RACON_TPU_SERVE_PREEMPT")
            if raw:
                try:
                    self.preempt = bool(int(raw))
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        f"invalid RACON_TPU_SERVE_PREEMPT value "
                        f"{raw!r} (expected an integer)") from None
            else:
                self.preempt = False
        # abort_margin: speculative deadline-abort margin in seconds
        # (None = off) — both at admission (queue EMA) and mid-run
        # (batcher iteration-boundary estimate)
        if "abort_margin" in kw:
            raw_am = kw.pop("abort_margin")
            self.abort_margin = (None if raw_am is None
                                 else max(0.0, float(raw_am)))
        else:
            raw = env("RACON_TPU_SERVE_ABORT_MARGIN")
            if raw:
                try:
                    self.abort_margin = max(0.0, float(raw))
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        "invalid RACON_TPU_SERVE_ABORT_MARGIN value "
                        f"{raw!r} (expected a number of seconds)") \
                        from None
            else:
                self.abort_margin = None
        # tenant_burst: token-bucket capacity letting a tenant briefly
        # exceed its hard quota, refilled at its DRR weight per second
        if "tenant_burst" in kw:
            self.tenant_burst = max(0, int(kw.pop("tenant_burst")))
        else:
            raw = env("RACON_TPU_SERVE_TENANT_BURST")
            if raw:
                try:
                    self.tenant_burst = max(0, int(raw))
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        "invalid RACON_TPU_SERVE_TENANT_BURST value "
                        f"{raw!r} (expected an integer)") from None
            else:
                self.tenant_burst = 0
        explicit_max_wait = "max_wait_s" in kw
        self.max_wait_s = max(0.0, kw.pop(
            "max_wait_s",
            _env_float("RACON_TPU_SERVE_MAX_WAIT_MS", 0.0) / 1000.0))
        # deprecated round-barrier knobs: the gather window aliases to
        # the feeder's coalescing wait, min_gather has no continuous
        # equivalent — both warn, neither is silently ignored
        explicit_gather = "gather_window_s" in kw
        if explicit_gather:
            _deprecated_knob(
                "gather_window_s",
                "aliased to max_wait_s (the feeder's coalescing wait); "
                "use max_wait_s / --max-wait-ms")
            val = float(kw.pop("gather_window_s"))
            # the deprecated alias must never beat the explicit NEW knob
            if not explicit_max_wait:
                self.max_wait_s = max(0.0, val)
        if "min_gather" in kw:
            _deprecated_knob(
                "min_gather",
                "the continuous feeder has no round to fill — the knob "
                "is ignored")
            kw.pop("min_gather")
        # env fallback only when NO explicit knob (new or deprecated)
        # was passed — an explicit argument must never lose to the
        # environment
        if env("RACON_TPU_SERVE_GATHER_MS") \
                and not env("RACON_TPU_SERVE_MAX_WAIT_MS") \
                and not explicit_max_wait and not explicit_gather:
            _deprecated_knob(
                "RACON_TPU_SERVE_GATHER_MS",
                "aliased to the feeder's max wait; set "
                "RACON_TPU_SERVE_MAX_WAIT_MS")
            self.max_wait_s = max(
                0.0, _env_float("RACON_TPU_SERVE_GATHER_MS", 0.0)
                / 1000.0)
        # per-tenant fair-scheduling weights: "gold=4,free=1,default=1"
        # (queue.py weighted deficit round-robin); strict parse — a
        # typo'd weights string fails the start, not the fairness
        self.tenant_weights = _parse_tenant_weights(kw.pop(
            "tenant_weights",
            env("RACON_TPU_SERVE_TENANT_WEIGHTS") or None))
        # identity-audit sentinel (obs/audit.py): the fraction of
        # production windows deterministically sampled for shadow
        # re-execution through the oracle path. 0 (the default) keeps
        # every serve surface byte-identical to the pre-audit code;
        # the companion knobs gate the mismatch consequences (online
        # winner-table demotion, lane quarantine/re-probe)
        self.audit_rate = min(1.0, max(0.0, kw.pop(
            "audit_rate", _env_float("RACON_TPU_AUDIT_RATE", 0.0))))
        self.audit_demote = bool(kw.pop(
            "audit_demote",
            (env("RACON_TPU_AUDIT_DEMOTE") or "1") != "0"))
        self.lane_quarantine = bool(kw.pop(
            "lane_quarantine",
            (env("RACON_TPU_LANE_QUARANTINE") or "1") != "0"))
        # content-addressed window consensus cache (serve/wincache.py):
        # off by default; armed, the batcher consults it before a
        # window enters the pooled stream (a hit skips device dispatch)
        # and populates it on iteration completion. Strict env parsing,
        # mirroring the --metrics-port discipline: a typo'd value fails
        # the start, never silently serves uncached
        if "wincache" in kw:
            self.wincache = bool(kw.pop("wincache"))
        else:
            raw = env("RACON_TPU_WINCACHE")
            if raw:
                try:
                    self.wincache = bool(int(raw))
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        f"invalid RACON_TPU_WINCACHE value {raw!r} "
                        "(expected an integer)") from None
            else:
                self.wincache = False
        from .wincache import DEFAULT_MAX_BYTES as _WINCACHE_DEFAULT

        if "wincache_max_bytes" in kw:
            self.wincache_max_bytes = int(kw.pop("wincache_max_bytes"))
        else:
            raw = env("RACON_TPU_WINCACHE_MAX_BYTES")
            if raw:
                try:
                    self.wincache_max_bytes = int(raw)
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        "invalid RACON_TPU_WINCACHE_MAX_BYTES value "
                        f"{raw!r} (expected an integer)") from None
            else:
                self.wincache_max_bytes = _WINCACHE_DEFAULT
        if self.wincache_max_bytes <= 0:
            raise RaconError(
                "ServeConfig",
                f"invalid wincache_max_bytes "
                f"{self.wincache_max_bytes} (expected a positive "
                "integer)")
        # fragment streaming group size (core/polisher.FragmentStreamer):
        # corrected reads of a fragment job ship in bounded groups of
        # this many targets per result_part frame — one frame per read
        # would mean millions of tiny frames on a real read set. Strict
        # env parsing like every other serve knob.
        if "frag_group" in kw:
            self.frag_group = int(kw.pop("frag_group"))
        else:
            raw = env("RACON_TPU_FRAG_GROUP")
            if raw:
                try:
                    self.frag_group = int(raw)
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        f"invalid RACON_TPU_FRAG_GROUP value {raw!r} "
                        "(expected an integer)") from None
            else:
                self.frag_group = 64
        if self.frag_group <= 0:
            raise RaconError(
                "ServeConfig",
                f"invalid frag_group {self.frag_group} (expected a "
                "positive integer)")
        self.warmup = kw.pop("warmup", True)
        self.max_frame = kw.pop("max_frame", max_frame_bytes())
        # telemetry exposition: None = no HTTP endpoint (the scrape RPC
        # is always available); an int (0 = ephemeral, published back)
        # serves Prometheus text on localhost HTTP. The env value is
        # parsed STRICTLY: a typo'd port must fail at startup, not
        # silently bind an ephemeral one Prometheus will never find
        if "metrics_port" in kw:
            self.metrics_port = kw.pop("metrics_port")
        else:
            raw = env("RACON_TPU_SERVE_METRICS_PORT")
            if raw:
                try:
                    self.metrics_port = int(raw)
                except ValueError:
                    raise RaconError(
                        "ServeConfig",
                        f"invalid RACON_TPU_SERVE_METRICS_PORT {raw!r} "
                        "(expected an integer)") from None
            else:
                self.metrics_port = None
        if self.metrics_port is not None and self.metrics_port < 0:
            raise RaconError(
                "ServeConfig",
                f"invalid metrics port {self.metrics_port} "
                "(expected >= 0; 0 = ephemeral)")
        # flight recorder: directory for automatic per-job dumps when a
        # job fails / times out / misses its deadline; empty string or
        # None disables dumping (the ring itself stays on). Resolution:
        # kwarg > RACON_TPU_SERVE_FLIGHT_DIR > the process-wide
        # RACON_TPU_FLIGHT_DIR (obs/flight.py) > the /tmp default.
        # start() validates the resolved directory STRICTLY — a bad
        # path fails the start, mirroring the --metrics-port discipline
        #: whether the operator CHOSE the flight dir (kwarg or either
        #: env knob): only then is startup validation strict — the
        #: built-in /tmp default keeps PR-6's best-effort-per-dump
        #: posture, so a plain `racon_tpu serve` on a host where
        #: another user owns /tmp/racon_tpu_flight still starts
        self.flight_dir_explicit = (
            "flight_dir" in kw
            or env("RACON_TPU_SERVE_FLIGHT_DIR") is not None
            or obs_flight.default_dump_dir() is not None)
        self.flight_dir = kw.pop(
            "flight_dir", env("RACON_TPU_SERVE_FLIGHT_DIR",
                              obs_flight.default_dump_dir()
                              or "/tmp/racon_tpu_flight"))
        # durable event journal (obs/journal.py): JSONL lifecycle log of
        # every job transition, keyed by job + trace id; None (the
        # default) disables it. Also validated strictly at start()
        self.journal_path = kw.pop(
            "journal", env("RACON_TPU_SERVE_JOURNAL") or None)
        # polish defaults (jobs may override per request, except
        # num_threads: host threads are a server resource)
        self.window_length = kw.pop("window_length", 500)
        self.quality_threshold = kw.pop("quality_threshold", 10.0)
        self.error_threshold = kw.pop("error_threshold", 0.3)
        self.trim = kw.pop("trim", True)
        self.match = kw.pop("match", 3)
        self.mismatch = kw.pop("mismatch", -5)
        self.gap = kw.pop("gap", -4)
        self.job_threads = max(1, kw.pop("job_threads", 2))
        self.tpu_poa_batches = kw.pop("tpu_poa_batches", 0)
        self.tpu_aligner_batches = kw.pop("tpu_aligner_batches", 0)
        self.tpu_aligner_band_width = kw.pop("tpu_aligner_band_width", 0)
        self.tpu_banded_alignment = kw.pop("tpu_banded_alignment", False)
        self.tpu_engine = kw.pop("tpu_engine", None)
        self.tpu_pipeline_depth = kw.pop("tpu_pipeline_depth", 2)
        self.tpu_device_timeout = kw.pop("tpu_device_timeout", 0.0)
        self.tpu_adaptive_buckets = kw.pop("tpu_adaptive_buckets", None)
        self.tpu_compile_cache = kw.pop("tpu_compile_cache", None)
        if kw:
            raise RaconError("ServeConfig",
                             f"unknown option(s): {', '.join(sorted(kw))}")

    @property
    def address(self) -> str:
        return (f"127.0.0.1:{self.port}" if self.port is not None
                else self.socket_path)


def make_synth_dataset(dirname: str, seed: int = 11,
                       genome_len: int = 2000, read_len: int = 400,
                       step: int = 100,
                       contigs: int = 1) -> tuple[str, str, str]:
    """Tiny deterministic ONT-shaped dataset (reads/PAF/draft gz
    triple) — the warmup job's input, also reused by servebench and the
    serve tests. Overlength pairs are included so the device-aligner
    fallback path warms too. `contigs` > 1 emits that many independent
    draft contigs (each with its own reads and PAF rows) for the
    multi-contig streaming / router-sharding tests; `contigs` == 1 is
    byte-identical to what this function always produced (same rng call
    order, same `draft` / `r{k}` names)."""
    rng = random.Random(seed)
    acgt = b"ACGT"

    def mutate(s, rate):
        out = bytearray()
        for c in s:
            r = rng.random()
            if r < rate / 3:
                continue
            if r < 2 * rate / 3:
                out.append(rng.choice(acgt))
                out.append(c)
                continue
            if r < rate:
                out.append(rng.choice(acgt))
                continue
            out.append(c)
        return bytes(out)

    reads, paf, drafts = [], [], []
    for c in range(max(1, contigs)):
        cname = "draft" if contigs <= 1 else f"ctg{c:02d}"
        truth = bytes(rng.choice(acgt) for _ in range(genome_len))
        draft = mutate(truth, 0.04)
        jobs = [(start, read_len)
                for start in range(0, genome_len - read_len, step)]
        jobs += [(0, genome_len - 700), (600, genome_len - 700)]
        for k, (start, length) in enumerate(jobs):
            read = mutate(truth[start:start + length], 0.05)
            rname = f"r{k}" if contigs <= 1 else f"r{c:02d}_{k}"
            reads.append((rname, read))
            t_end = min(start + length, len(draft))
            paf.append(f"{rname}\t{len(read)}\t0\t{len(read)}\t+\t"
                       f"{cname}\t{len(draft)}\t{start}\t{t_end}\t"
                       f"{length}\t{length}\t60")
        drafts.append((cname, draft))
    paths = (os.path.join(dirname, "reads.fasta.gz"),
             os.path.join(dirname, "ovl.paf.gz"),
             os.path.join(dirname, "draft.fasta.gz"))
    with gzip.open(paths[0], "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    with gzip.open(paths[2], "wb") as f:
        for cname, draft in drafts:
            f.write(b">" + cname.encode() + b"\n" + draft + b"\n")
    return paths


def make_fragment_dataset(dirname: str, seed: int = 13,
                          genome_len: int = 2000, read_len: int = 400,
                          step: int = 100) -> tuple[str, str, str]:
    """Tiny deterministic reads-correcting-reads dataset for the
    fragment traffic class: staggered noisy reads off one truth genome
    plus their all-vs-all overlaps (PAF rows between position-adjacent
    read pairs). Returns (sequences, overlaps, target) where sequences
    and target are the SAME reads file — the one-shot CLI's
    `racon_tpu -f reads.fasta.gz ava.paf.gz reads.fasta.gz` shape —
    used by the serve fragment tests, servebench --fragment and
    faultcheck."""
    rng = random.Random(seed)
    acgt = b"ACGT"

    def mutate(s, rate):
        out = bytearray()
        for c in s:
            r = rng.random()
            if r < rate / 3:
                continue
            if r < 2 * rate / 3:
                out.append(rng.choice(acgt))
                out.append(c)
                continue
            if r < rate:
                out.append(rng.choice(acgt))
                continue
            out.append(c)
        return bytes(out)

    truth = bytes(rng.choice(acgt) for _ in range(genome_len))
    reads: list[tuple[str, bytes, int, int]] = []
    for k, start in enumerate(range(0, genome_len - read_len + 1,
                                    step)):
        end = min(start + read_len, genome_len)
        reads.append((f"f{k}", mutate(truth[start:end], 0.05),
                      start, end))
    paf = []
    for qn, qd, qs0, qe0 in reads:
        for tn, td, ts0, te0 in reads:
            if qn == tn:
                continue
            ov0, ov1 = max(qs0, ts0), min(qe0, te0)
            if ov1 - ov0 < read_len // 4:
                continue  # only meaningfully overlapping pairs
            # truth-coordinate overlap mapped onto each noisy read,
            # clamped to its (indel-shifted) actual length
            qlo = min(max(0, ov0 - qs0), len(qd))
            qhi = min(ov1 - qs0, len(qd))
            tlo = min(max(0, ov0 - ts0), len(td))
            thi = min(ov1 - ts0, len(td))
            if qhi <= qlo or thi <= tlo:
                continue
            paf.append(f"{qn}\t{len(qd)}\t{qlo}\t{qhi}\t+\t"
                       f"{tn}\t{len(td)}\t{tlo}\t{thi}\t"
                       f"{qhi - qlo}\t{qhi - qlo}\t60")
    reads_path = os.path.join(dirname, "frags.fasta.gz")
    ovl_path = os.path.join(dirname, "frags_ava.paf.gz")
    with gzip.open(reads_path, "wb") as f:
        for name, data, _s, _e in reads:
            f.write(b">" + name.encode() + b"\n" + data + b"\n")
    with gzip.open(ovl_path, "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    return reads_path, ovl_path, reads_path


class PolishServer:
    def __init__(self, config: ServeConfig | None = None, **overrides):
        self.config = config if config is not None \
            else ServeConfig(**overrides)
        cfg = self.config
        if cfg.tpu_compile_cache:
            from ..sched import enable_compile_cache

            enable_compile_cache(cfg.tpu_compile_cache)
        #: server-lifetime latency histograms (obs/hist.py): job
        #: end-to-end / queue wait / device iterations / pipeline
        #: stages / compiles — the scrape RPC's distribution view
        self.hists = HistogramSet()
        self.queue = JobQueue(cfg.queue_depth, workers=cfg.workers,
                              hists=self.hists,
                              tenant_weights=cfg.tenant_weights,
                              tenant_quota=cfg.tenant_quota,
                              tenant_burst=cfg.tenant_burst,
                              abort_margin=cfg.abort_margin)
        self.batcher = WindowBatcher(
            iteration_windows=cfg.iteration_windows,
            max_wait_s=cfg.max_wait_s,
            worker_lanes=cfg.worker_lanes)
        #: iteration-boundary speculative abort rides the batcher's
        #: consume loop (None keeps that check compiled out entirely)
        self.batcher.abort_margin = cfg.abort_margin
        #: QoS runtime state (all under `_qos_lock`): every RUNNING
        #: job by id (the cancel RPC's running-job lookup), the jobs
        #: currently parked by preemption, and the lifetime QoS
        #: counters. Counters live here (not in queue.counters) so the
        #: scrape can render them armed-only — queue counters render
        #: unconditionally and would break byte-identity when off.
        self._qos_lock = threading.Lock()
        self._running_jobs: dict[str, Job] = {}
        self._preempted: dict[str, Job] = {}
        self.qos = {"preemptions": 0, "aborted_doomed": 0,
                    "cancelled": 0}
        self.batcher.hists = self.hists
        self.batcher.pipeline_stats.hists = self.hists
        self.batcher.scheduler.stats.hists = self.hists
        #: identity-audit sentinel (obs/audit.py): armed only when the
        #: sampled fraction is nonzero — with it off, the scrape, the
        #: journal and the FASTA are byte-identical to the pre-audit
        #: server (test-pinned)
        self.auditor = None
        if cfg.audit_rate > 0.0:
            from ..obs.audit import WindowAuditor

            self.auditor = WindowAuditor(
                rate=cfg.audit_rate, demote=cfg.audit_demote,
                quarantine=cfg.lane_quarantine, hists=self.hists,
                flight_dir=cfg.flight_dir or None,
                on_alert=self._on_audit_alert)
            self.batcher.auditor = self.auditor
        #: content-addressed window consensus cache (serve/wincache.py)
        #: — armed only when configured; with it off the batcher path,
        #: the snapshot and the scrape are byte-identical to the
        #: pre-cache server (test-pinned)
        if cfg.wincache:
            from .wincache import WindowCache

            self.batcher.wincache = WindowCache(
                max_bytes=cfg.wincache_max_bytes)
        #: serve-native polishing rounds telemetry: jobs that requested
        #: rounds, rounds completed, live in-flight gauge. The scrape
        #: renders the families only once a rounds job has been seen
        self._rounds_lock = threading.Lock()
        self._rounds = {"jobs": 0, "completed": 0, "inflight": 0}
        #: flight recorder (obs/flight.py): installed at start() unless
        #: a full trace is already armed (then that recorder serves as
        #: the flight source too)
        self._flight: obs_trace.TraceRecorder | None = None
        self._flight_installed = False
        self._dumps: deque = deque(maxlen=8)
        self._http = None
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conn_lock = threading.Lock()
        self._job_seq = 0
        self._job_seq_lock = threading.Lock()
        self._inflight = 0
        self._idle = threading.Condition()
        self._stop_workers = threading.Event()
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._t_start = time.perf_counter()
        #: wall-clock start time: exposed as the
        #: racon_tpu_serve_start_time_seconds gauge so a dashboard can
        #: tell a restarted server from a quiet one
        self._t_wall_start = time.time()
        self.journal: Journal | None = None
        self._warm: dict | None = None
        #: SLO burn-rate tracker (obs/fleet.py): sampled on every
        #: deadline-carrying job via the queue's on_slo hook; state
        #: transitions journal typed `alert` events and flip the
        #: racon_tpu_slo_burn_alert gauge. seed_zero: this process's
        #: counters were born with the tracker, so the very first miss
        #: counts against a zero baseline.
        self.burn = obs_fleet.BurnRateTracker(seed_zero=True)
        self.queue.on_slo = self._on_slo
        #: latency exemplars (obs/hist.py): on by default, disabled by
        #: RACON_TPU_SERVE_EXEMPLARS=0 — the byte-identity A/B knob
        self.exemplars_enabled = (
            os.environ.get("RACON_TPU_SERVE_EXEMPLARS", "1") != "0")
        #: self-metered exposition cost: seconds this process spent
        #: RENDERING scrape bodies (not wire or aggregator time) — the
        #: number servebench --fleet holds to the <2% budget
        self._scrape_count = 0
        self._scrape_render_s = 0.0
        self._scrape_lock = threading.Lock()
        #: admit-time ingest workdir (serve/ingest.py): lazily created
        #: server-lifetime scratch directory holding subsampled /
        #: pair-normalized inputs; removed on close()
        self._ingest_dir: str | None = None
        self._ingest_lock = threading.Lock()

    def _ingest_workdir(self) -> str:
        """Lazily created server-lifetime scratch directory for the
        ingest plane's rewritten inputs (subsample-on-admit, pair
        normalization). One directory per server so close() can remove
        every rewritten file in one sweep."""
        with self._ingest_lock:
            if self._ingest_dir is None:
                import tempfile

                self._ingest_dir = tempfile.mkdtemp(
                    prefix="racon-tpu-ingest-")
            return self._ingest_dir

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "PolishServer":
        """Warm up (unless disabled), bind the transport, spawn the
        worker pool and the accept loop. Returns self; the server is
        accepting when this returns."""
        cfg = self.config
        # strict startup validation (the --metrics-port discipline): an
        # operator who configured a flight-dump directory or an audit
        # journal must find out NOW that the path is unusable, not at
        # the first failed job / first lifecycle line
        if cfg.flight_dir and cfg.flight_dir_explicit:
            try:
                os.makedirs(cfg.flight_dir, exist_ok=True)
                probe = os.path.join(cfg.flight_dir,
                                     f".probe_{os.getpid()}")
                with open(probe, "w"):
                    pass
                os.unlink(probe)
            except OSError as exc:
                raise RaconError(
                    "PolishServer.start",
                    f"flight dump directory {cfg.flight_dir!r} is not "
                    f"writable ({exc}); point --flight-dir / "
                    "RACON_TPU_SERVE_FLIGHT_DIR / RACON_TPU_FLIGHT_DIR "
                    "at a writable directory, or '' to disable "
                    "dumping") from None
        if cfg.journal_path:
            try:
                self.journal = Journal(cfg.journal_path)
            except OSError as exc:
                raise RaconError(
                    "PolishServer.start",
                    f"cannot open serve journal {cfg.journal_path!r} "
                    f"({exc}); point --journal / "
                    "RACON_TPU_SERVE_JOURNAL at a writable path") \
                    from None
        # queue-side lifecycle transitions (started / expired) feed the
        # journal and the live progress relay
        self.queue.on_event = self._on_queue_event
        if self.auditor is not None:
            # the sentinel journals its annotation events (audit-
            # mismatch / audit-lane / alert) into the same lifecycle
            # journal, keyed by the owning job
            self.auditor.journal = self.journal
        # always-on flight recorder: when no full trace is armed,
        # install the bounded ring as the process tracer so every span
        # hook feeds it (<2% overhead, synthbench --flight A/Bs it);
        # an armed RACON_TPU_TRACE recorder doubles as the flight source
        tr = obs_trace.get_tracer()
        if tr is None:
            self._flight = obs_trace.install(obs_flight.FlightRecorder())
            self._flight_installed = True
        else:
            self._flight = tr
        if cfg.warmup:
            self.warmup()
        if cfg.metrics_port is not None:
            self._start_metrics_http()
        if cfg.port is not None:
            lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lst.bind(("127.0.0.1", max(0, cfg.port)))
            if cfg.port <= 0:  # ephemeral: publish the real port
                cfg.port = lst.getsockname()[1]
        else:
            with contextlib.suppress(OSError):
                os.unlink(cfg.socket_path)
            lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            lst.bind(cfg.socket_path)
        lst.listen(64)
        lst.settimeout(0.2)
        self._listener = lst
        for i in range(cfg.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"racon-tpu-serve-worker-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._accept_loop,
                             name="racon-tpu-serve-accept", daemon=True)
        t.start()
        self._threads.append(t)
        if self.journal is not None:
            self.journal.record("serve-start", address=cfg.address,
                                pid=os.getpid(), workers=cfg.workers,
                                queue_depth=cfg.queue_depth)
        log_info(f"[racon_tpu::serve] listening on {cfg.address} "
                 f"({cfg.workers} workers, queue depth "
                 f"{cfg.queue_depth}"
                 + (f", {cfg.worker_lanes} worker lanes"
                    if cfg.worker_lanes > 1 else "")
                 + (f", warm in {self._warm['warmup_s']:.2f}s"
                    if self._warm else "")
                 + (f", metrics on 127.0.0.1:{cfg.metrics_port}"
                    if self._http is not None else "")
                 + (f", journal {cfg.journal_path}"
                    if self.journal is not None else "") + ")")
        return self

    def _on_queue_event(self, event: str, job: Job, **fields) -> None:
        """JobQueue.on_event sink: journal the transition and, for a
        progress-streaming job, announce the queue->worker handoff.
        `admitted`/`expired` arrive UNDER the queue mutex, so they are
        STAGED (memory-only, order-preserving) rather than written — a
        stalled journal disk must not serialize every submit/pop/scrape
        behind it; the handler flushes once its job resolves."""
        if event == "started" and job.want_progress:
            job.notify_progress(
                {"phase": "start",
                 "queue_wait_s": fields.get("queue_wait_s")})
        if self.journal is not None:
            if event == "cancelled":
                # fired UNDER the queue mutex by queue.cancel: stage
                # the typed annotation AND the legal terminal (the job
                # never started, so it leaves as an expiry with the
                # reason pinned — `failed` would trip the journal's
                # ran-without-started check)
                self.journal.stage(event, job=job.id,
                                   trace=job.trace_id, **fields)
                self.journal.stage("expired", job=job.id,
                                   trace=job.trace_id,
                                   reason="cancelled")
            elif event in ("admitted", "expired"):
                self.journal.stage(event, job=job.id,
                                   trace=job.trace_id, **fields)
            else:
                self.journal.record(event, job=job.id,
                                    trace=job.trace_id, **fields)

    def _on_slo(self, job: Job, hit: int, miss: int) -> None:
        """JobQueue.on_slo sink: sample the burn-rate tracker with the
        cumulative deadline counters; a state transition journals a
        typed `alert` event carrying the job that tripped (or cleared)
        it, so obsreport's per-job timeline shows the alert next to
        the deadline-miss that caused it."""
        res = self.burn.sample(hit, miss)
        if res["changed"] and self.journal is not None:
            self.journal.record(
                "alert", job=job.id, trace=job.trace_id,
                kind="slo-burn",
                state="firing" if res["firing"] else "clear",
                burn_fast=res["fast"], burn_slow=res["slow"],
                threshold=res["threshold"],
                deadline_hit=hit, deadline_miss=miss)
        if res["changed"]:
            log_info(
                f"[racon_tpu::serve] SLO burn alert "
                f"{'FIRING' if res['firing'] else 'clear'}: "
                f"fast {res['fast']:g}x / slow {res['slow']:g}x of "
                f"budget (threshold {res['threshold']:g}x, "
                f"{miss} deadline misses)")

    def _on_audit_alert(self, state: str, detail: dict) -> None:
        """WindowAuditor.on_alert sink: a nonzero mismatch count flips
        the racon_tpu_audit_alert gauge (rendered from the auditor's
        live state) and journals a typed alert; the operator clears it
        with the debug RPC's `audit_ack`."""
        if self.journal is not None:
            self.journal.record(
                "alert", kind="audit-mismatch", state=state,
                mismatches=detail.get("mismatches"),
                acked=detail.get("acked"))
        log_info(f"[racon_tpu::serve] audit alert "
                 f"{'FIRING' if state == 'firing' else 'clear'}: "
                 f"{detail.get('mismatches', 0)} identity mismatches "
                 f"({detail.get('acked', 0)} acknowledged)")

    def healthz_snapshot(self) -> dict:
        """The health body both transports serve (`/healthz` HTTP —
        503 while draining — and the `healthz` RPC): ok + draining +
        enough context for a fleet view's per-replica detail."""
        draining = self._draining.is_set()
        return {"ok": not draining,
                "draining": draining,
                "warm": self._warm is not None,
                "uptime_s": round(
                    time.perf_counter() - self._t_start, 3),
                "queue_depth": len(self.queue),
                "inflight": self._inflight_count()}

    def _start_metrics_http(self) -> None:
        """Serve Prometheus text on localhost HTTP (stdlib only). Bind
        failure raises at start() — an operator asked for a port they
        cannot have — but once up, NO handler error ever propagates:
        a scrape bug answers 500 and the polish server keeps serving."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        polish_server = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path in ("/metrics", "/"):
                        body = polish_server.prometheus_text().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         obs_prom.CONTENT_TYPE)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    elif path == "/healthz":
                        # a draining replica answers 503 so a load
                        # balancer stops routing to it — the JSON body
                        # says WHY, for the operator behind the LB
                        doc = polish_server.healthz_snapshot()
                        body = (json.dumps(doc, sort_keys=True)
                                + "\n").encode()
                        self.send_response(200 if doc["ok"] else 503)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self.send_error(404)
                except Exception as exc:  # noqa: BLE001 — see docstring
                    with contextlib.suppress(Exception):
                        self.send_error(
                            500, f"{type(exc).__name__}: {exc}")

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.config.metrics_port), _Handler)
        httpd.daemon_threads = True
        self.config.metrics_port = httpd.server_address[1]
        self._http = httpd
        t = threading.Thread(target=httpd.serve_forever,
                             name="racon-tpu-serve-metrics-http",
                             daemon=True)
        t.start()

    def warmup(self, paths: tuple[str, str, str] | None = None) -> dict:
        """Run one job end to end (synthetic by default, or the caller's
        input triple — servebench passes its own so warmup shapes equal
        job shapes) so every engine the configured posture uses is jit-
        built before the first real request."""
        from ..core.polisher import PolisherType, create_polisher

        cfg = self.config
        t0 = time.perf_counter()
        with contextlib.ExitStack() as stack:
            if paths is None:
                tmp = stack.enter_context(
                    tempfile.TemporaryDirectory(prefix="racon_serve_warm_"))
                paths = make_synth_dataset(tmp)
            polisher = create_polisher(
                *paths, PolisherType.kC, cfg.window_length,
                cfg.quality_threshold, cfg.error_threshold, cfg.trim,
                cfg.match, cfg.mismatch, cfg.gap,
                num_threads=cfg.job_threads,
                tpu_poa_batches=cfg.tpu_poa_batches,
                tpu_banded_alignment=cfg.tpu_banded_alignment,
                tpu_aligner_batches=cfg.tpu_aligner_batches,
                tpu_aligner_band_width=cfg.tpu_aligner_band_width,
                tpu_engine=cfg.tpu_engine,
                tpu_pipeline_depth=cfg.tpu_pipeline_depth,
                tpu_device_timeout=cfg.tpu_device_timeout,
                tpu_adaptive_buckets=cfg.tpu_adaptive_buckets)
            polisher.initialize()
            polisher.polish(True, batcher=self.batcher)
        compiles, compile_s = self.batcher._compile_totals()
        self._warm = {"warmup_s": round(time.perf_counter() - t0, 3),
                      "compiles": compiles,
                      "compile_s": round(compile_s, 3)}
        return self._warm

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: stop admitting, finish queued + in-flight
        jobs (bounded by `timeout`, default config.drain_timeout_s),
        flush observability, close the transport. True when everything
        finished inside the budget."""
        if self._draining.is_set():
            self._stopped.wait()
            return True
        self._draining.set()
        budget = (timeout if timeout is not None
                  else self.config.drain_timeout_s)
        if self.journal is not None:
            self.journal.record("drain", queued=len(self.queue),
                                inflight=self._inflight,
                                budget_s=round(budget, 1))
        log_info(f"[racon_tpu::serve] draining: {len(self.queue)} queued, "
                 f"{self._inflight} in flight (budget {budget:.0f}s)")
        self.queue.drain()
        if self._listener is not None:
            with contextlib.suppress(OSError):
                self._listener.close()
        deadline = time.monotonic() + budget
        clean = True
        with self._idle:
            while len(self.queue) or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    clean = False
                    break
                self._idle.wait(min(left, 0.2))
        self._stop_workers.set()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)
        # in-flight jobs are done (or over budget): stop the device
        # feeder so the process can exit without a straggler iteration
        self.batcher.close()
        if self.auditor is not None:
            self.auditor.close()
        # flush observability BEFORE dropping connections: an armed
        # trace/metrics artifact must survive the shutdown
        self._flush_observability()
        if self._http is not None:
            with contextlib.suppress(Exception):
                self._http.shutdown()
                self._http.server_close()
            self._http = None
        # uninstall the flight ring (only if still ours): later runs in
        # this process must re-resolve tracing from their own environment
        if self._flight_installed \
                and obs_trace.get_tracer() is self._flight:
            obs_trace.reset()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            with contextlib.suppress(OSError):
                c.shutdown(socket.SHUT_RDWR)
            with contextlib.suppress(OSError):
                c.close()
        if self.config.port is None:
            with contextlib.suppress(OSError):
                os.unlink(self.config.socket_path)
        if self._ingest_dir is not None:
            import shutil

            with contextlib.suppress(OSError):
                shutil.rmtree(self._ingest_dir)
            self._ingest_dir = None
        if self.journal is not None:
            self.journal.record(
                "serve-stop", clean=clean,
                completed=self.queue.counters["completed"],
                failed=self.queue.counters["failed"])
            self.journal.close()
        log_info(f"[racon_tpu::serve] drained "
                 f"{'cleanly' if clean else 'OVER BUDGET'}: "
                 f"{self.queue.counters['completed']} jobs completed, "
                 f"{self.queue.counters['failed']} failed")
        self._stopped.set()
        return clean

    def _flush_observability(self) -> None:
        snap = self.stats_snapshot()
        q, b = snap["queue"], snap["batcher"]
        log_info(f"[racon_tpu::serve] lifetime: {q['admitted']} admitted "
                 f"({q['rejected_full']} full-queue rejects, "
                 f"{q['expired']} expired), {b['iterations']} device "
                 f"iterations ({b['shared_iterations']} cross-job), "
                 f"{b['compiles']} compiles {b['compile_s']:.2f}s")
        metrics_path = os.environ.get("RACON_TPU_METRICS")
        if metrics_path:
            try:
                with open(metrics_path, "w") as fh:
                    json.dump(snap, fh, indent=2, sort_keys=True)
                log_info(f"[racon_tpu::serve] metrics written to "
                         f"{metrics_path}")
            except OSError as exc:
                log_info(f"[racon_tpu::serve] warning: could not write "
                         f"metrics ({exc})")
        try:
            saved = obs_trace.save()
        except OSError as exc:
            saved = None
            log_info(f"[racon_tpu::serve] warning: could not write trace "
                     f"({exc})")
        if saved:
            log_info(f"[racon_tpu::serve] trace written to {saved}")

    # ----------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._draining.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            with self._conn_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 name="racon-tpu-serve-conn", daemon=True)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                try:
                    req = recv_frame(conn, self.config.max_frame)
                except ProtocolError as exc:
                    with contextlib.suppress(OSError):
                        send_frame(conn,
                                   error_response(exc.code, str(exc)))
                    if not exc.resync:
                        return
                    continue
                except OSError:
                    return
                if req is None:
                    return
                try:
                    resp = self._dispatch(req, conn)
                except Exception as exc:
                    # a handler bug answers typed and keeps serving;
                    # it never takes the process down
                    resp = error_response(
                        "internal", f"{type(exc).__name__}: {exc}")
                try:
                    send_frame(conn, resp)
                except ProtocolError as exc:
                    # response too big for the wire: answer typed
                    # rather than dying mid-send with a desynced peer
                    with contextlib.suppress(OSError):
                        send_frame(conn,
                                   error_response(exc.code, str(exc)))
                except OSError:
                    return
        finally:
            with self._conn_lock:
                self._conns.discard(conn)
            with contextlib.suppress(OSError):
                conn.close()

    def _dispatch(self, req: dict, conn: socket.socket) -> dict:
        rtype = req.get("type")
        if rtype == "submit":
            return self._submit(req, conn)
        if rtype == "ping":
            # mono_s is the clock-handshake sample: a tracing client
            # RTT-brackets it to estimate this process's perf_counter
            # offset, so merged client+server traces share one timeline
            return {"type": "pong", "warm": self._warm is not None,
                    "uptime_s": round(
                        time.perf_counter() - self._t_start, 3),
                    "mono_s": time.perf_counter()}
        if rtype == "stats":
            return dict(self.stats_snapshot(), type="stats")
        if rtype == "healthz":
            # the RPC twin of the HTTP /healthz: same body, same
            # draining semantics, for unix/TCP-only deployments and
            # the fleet aggregator's replica probe
            return dict(self.healthz_snapshot(), type="healthz")
        if rtype == "scrape":
            return {"type": "metrics",
                    "content_type": obs_prom.CONTENT_TYPE,
                    "text": self.prometheus_text()}
        if rtype == "debug":
            resp = self.debug_snapshot(
                max_events=int(req.get("max_events", 5000)))
            if self.auditor is not None:
                # operator acknowledgement: clears the audit alert
                # (gauge + journal) until the next mismatch
                if req.get("audit_ack"):
                    resp["audit_ack"] = self.auditor.ack()
                resp["audit"] = self.auditor.snapshot()
            return resp
        if rtype == "trace_pull":
            return self._trace_pull(req)
        if rtype == "cancel":
            return self._cancel(req)
        if rtype == "shutdown":
            threading.Thread(target=self.drain,
                             name="racon-tpu-serve-drain",
                             daemon=True).start()
            return {"type": "ok", "message": "draining"}
        return error_response("bad-request",
                              f"unknown request type {rtype!r}")

    #: trace ids come from untrusted clients and ride journal lines,
    #: file-adjacent artifacts and Prometheus-adjacent text — constrain
    #: them to a boring charset instead of sanitizing at every sink
    _TRACE_ID_OK = frozenset(
        "abcdefghijklmnopqrstuvwxyz"
        "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.")

    def _submit(self, req: dict, conn: socket.socket) -> dict:
        for key in ("sequences", "overlaps", "target"):
            path = req.get(key)
            if not isinstance(path, str) or not path:
                return error_response("bad-request",
                                      f"missing input path {key!r}")
            if not os.path.isfile(path):
                return error_response(
                    "bad-request", f"{key} file not found: {path}")
        options = req.get("options") or {}
        if not isinstance(options, dict):
            return error_response("bad-request", "options must be an object")
        unknown = set(options) - ALLOWED_OPTIONS
        if unknown:
            return error_response(
                "bad-request",
                f"unknown option(s): {', '.join(sorted(unknown))}")
        trace_id = req.get("trace_id")
        if trace_id is not None and (
                not isinstance(trace_id, str)
                or not 0 < len(trace_id) <= 64
                or not set(trace_id) <= self._TRACE_ID_OK):
            return error_response(
                "bad-request",
                "trace_id must be 1-64 chars of [A-Za-z0-9._-]")
        # tenant ids ride journal lines and Prometheus-adjacent metric
        # names — same boring charset as trace ids
        tenant = req.get("tenant")
        if tenant is not None and (
                not isinstance(tenant, str)
                or not 0 < len(tenant) <= 64
                or not set(tenant) <= self._TRACE_ID_OK):
            return error_response(
                "bad-request",
                "tenant must be 1-64 chars of [A-Za-z0-9._-]")
        fault_plan = req.get("fault_plan")
        if fault_plan:
            from ..resilience import FaultPlan

            try:
                FaultPlan.parse(fault_plan)
            except RaconError as exc:
                return error_response("bad-request", str(exc))
        # serve-native polishing rounds: validated here so a typo'd
        # request fails typed instead of silently polishing once
        rounds = req.get("rounds")
        if rounds is not None and (
                isinstance(rounds, bool) or not isinstance(rounds, int)
                or not 1 <= rounds <= MAX_ROUNDS):
            return error_response(
                "bad-request",
                f"rounds must be an integer in [1, {MAX_ROUNDS}]")
        # sub-contig window-range shard slice (router fan-out,
        # protocol.py "Child-job fields"): validated here so a typo'd
        # range fails typed instead of silently polishing the whole
        # target — the one unknown-key family a range-aware replica
        # must NOT ignore
        range_lo = req.get("range_lo")
        range_hi = req.get("range_hi")
        if range_lo is not None or range_hi is not None:
            if (isinstance(range_lo, bool) or isinstance(range_hi, bool)
                    or not isinstance(range_lo, int)
                    or not isinstance(range_hi, int)
                    or range_lo < 0 or range_hi <= range_lo):
                return error_response(
                    "bad-request",
                    "range_lo/range_hi must be integers with "
                    "0 <= range_lo < range_hi")
            if rounds is not None:
                # round 2 would re-map reads onto a SEGMENT, which is
                # not what solo rounds on the full contig compute —
                # the router falls back to contig sharding instead
                return error_response(
                    "bad-request",
                    "rounds cannot be combined with range_lo/range_hi")
        # fragment traffic class (reference `-f`, PolisherType.kF): an
        # explicit `mode` field rather than a bare option so the
        # router, journal, and streaming shape can tell the traffic
        # classes apart. Absent mode keeps every surface byte-identical
        # — including legacy `options.fragment_correction` jobs, which
        # keep their per-contig streaming shape.
        mode = req.get("mode")
        if mode is not None and mode not in ("contig", "fragment"):
            return error_response(
                "bad-request", 'mode must be "contig" or "fragment"')
        fragment = mode == "fragment"
        if fragment:
            if range_lo is not None or range_hi is not None:
                # the window-range planner slices ONE target's
                # coordinate axis; fragment jobs shard across the
                # target INDEX axis instead (frag_lo/frag_hi)
                return error_response(
                    "bad-request",
                    'mode "fragment" cannot be combined with '
                    "range_lo/range_hi")
            if rounds is not None and rounds > 1:
                # rounds re-polish a DRAFT assembly; corrected reads
                # are terminal outputs with nothing to re-map onto
                return error_response(
                    "bad-request",
                    'rounds > 1 cannot be combined with mode '
                    '"fragment"')
            # mode implies the kF polisher; normalize here so _run_job
            # and the audit config keep a single source of truth
            options = dict(options)
            options["fragment_correction"] = True
        # fragment child-job shard slice (router fan-out, protocol.py
        # "Fragment child jobs"): [frag_lo, frag_hi) target-INDEX
        # bounds, mirroring the range_lo/range_hi discipline
        frag_lo = req.get("frag_lo")
        frag_hi = req.get("frag_hi")
        if frag_lo is not None or frag_hi is not None:
            if (isinstance(frag_lo, bool) or isinstance(frag_hi, bool)
                    or not isinstance(frag_lo, int)
                    or not isinstance(frag_hi, int)
                    or frag_lo < 0 or frag_hi <= frag_lo):
                return error_response(
                    "bad-request",
                    "frag_lo/frag_hi must be integers with "
                    "0 <= frag_lo < frag_hi")
            if not fragment:
                return error_response(
                    "bad-request",
                    'frag_lo/frag_hi require mode "fragment"')
            if rounds is not None:
                return error_response(
                    "bad-request",
                    "rounds cannot be combined with frag_lo/frag_hi")
        # streaming ingest plane (serve/ingest.py): opt-in via any of
        # `ingest: true` (validate-only), `subsample: {...}`, or
        # `normalize: true`. Shapes are validated HERE so a typo'd
        # request fails typed before a job id is minted; the actual
        # (possibly slow) streaming parse runs after `received` below.
        ingest_spec = None
        if (req.get("ingest") is not None or req.get("subsample")
                is not None or req.get("normalize") is not None):
            from . import ingest as ingest_mod

            try:
                ingest_spec = ingest_mod.IngestSpec.from_request(req)
            except ingest_mod.IngestError as exc:
                return error_response("bad-request", str(exc))
            if not (req.get("ingest") or ingest_spec.subsample
                    or ingest_spec.normalize):
                # `ingest: false` with no other opt-in: shapes were
                # still validated above, but nothing to run
                ingest_spec = None
        with self._job_seq_lock:
            self._job_seq += 1
            job_id = f"j{self._job_seq}"
        job = Job(job_id, req["sequences"], req["overlaps"], req["target"],
                  options, priority=int(req.get("priority", 0)),
                  deadline_s=req.get("deadline_s"),
                  fault_plan=fault_plan, strict=req.get("strict"),
                  want_trace=bool(req.get("trace")),
                  trace_id=trace_id,
                  want_progress=bool(req.get("progress")),
                  want_stream=bool(req.get("stream")),
                  tenant=tenant or "", rounds=rounds,
                  range_lo=range_lo, range_hi=range_hi,
                  fragment=fragment, frag_lo=frag_lo, frag_hi=frag_hi)
        # child-job fields from a serve router (router.py): `parent` is
        # the router-side parent job id, `shard`/`shards` this child's
        # slot in the contig fan-out. Purely observational replica-side
        # — journaled so the replica's journal lines correlate with the
        # router's ledger — and ignored (like any unknown key) when
        # absent or malformed.
        parent = req.get("parent")
        if not isinstance(parent, str) or not parent \
                or not set(parent) <= self._TRACE_ID_OK:
            parent = None
        shard = req.get("shard") if isinstance(req.get("shard"), int) \
            else None
        shards = req.get("shards") if isinstance(req.get("shards"), int) \
            else None
        if self.journal is not None:
            self.journal.record("received", job=job.id, trace=trace_id,
                                priority=job.priority or None,
                                tenant=job.tenant or None,
                                deadline_s=req.get("deadline_s"),
                                rounds=job.rounds,
                                parent=parent, shard=shard,
                                shards=shards,
                                range_lo=job.range_lo,
                                range_hi=job.range_hi,
                                mode="fragment" if job.fragment
                                else None,
                                frag_lo=job.frag_lo,
                                frag_hi=job.frag_hi)
        if ingest_spec is not None:
            # admit-time ingest: streaming-validate (and optionally
            # subsample / pair-normalize) the raw inputs. A parse error
            # fails THIS job typed — `rejected-ingest` terminal, no
            # queue time, never the server — and rewritten paths land
            # on the job before it is queued.
            from . import ingest as ingest_mod

            try:
                done = ingest_mod.prepare(
                    job.sequences, job.overlaps, job.target,
                    ingest_spec, workdir=self._ingest_workdir(),
                    job_id=job.id, trace_id=trace_id,
                    journal=self.journal)
            except ingest_mod.IngestError as exc:
                if self.journal is not None:
                    self.journal.record("rejected-ingest", job=job.id,
                                        trace=trace_id,
                                        error=exc.stage,
                                        detail=str(exc))
                return error_response("bad-request", str(exc),
                                      job_id=job_id)
            job.sequences, job.overlaps, job.target = done
        try:
            self.queue.submit(job)
        except QueueFull as exc:
            if self.journal is not None:
                self.journal.record("rejected-full", job=job.id,
                                    trace=trace_id,
                                    retry_after=round(exc.retry_after, 3))
            return error_response("queue-full", str(exc),
                                  retry_after=round(exc.retry_after, 3),
                                  job_id=job_id)
        except TenantQuotaExceeded as exc:
            if self.journal is not None:
                self.journal.record("rejected-quota", job=job.id,
                                    trace=trace_id,
                                    tenant=job.tenant or None,
                                    retry_after=round(exc.retry_after, 3))
            return error_response("tenant-quota", str(exc),
                                  retry_after=round(exc.retry_after, 3),
                                  tenant=job.tenant, job_id=job_id)
        except DeadlineDoomed as exc:
            # speculative abort at the door: the EMA says this job
            # cannot finish inside its own deadline — fail fast, typed,
            # before it costs queue time or device time. Terminal is
            # `expired` (the job never ran), the typed annotation pins
            # the why.
            with self._qos_lock:
                self.qos["aborted_doomed"] += 1
            if self.journal is not None:
                self.journal.record(
                    "deadline-doomed", job=job.id, trace=trace_id,
                    phase="admission",
                    predicted_s=round(exc.predicted_s, 3),
                    remaining_s=round(exc.remaining_s, 3))
                self.journal.record("expired", job=job.id,
                                    trace=trace_id,
                                    reason="deadline-doomed")
            return error_response(
                "deadline-doomed", str(exc), job_id=job_id,
                predicted_s=round(exc.predicted_s, 3),
                remaining_s=round(exc.remaining_s, 3))
        except Draining as exc:
            if self.journal is not None:
                self.journal.record("rejected-draining", job=job.id,
                                    trace=trace_id)
            return error_response("draining", str(exc), job_id=job_id)
        self._maybe_preempt(job)
        # `admitted` is STAGED by the queue's on_event hook under the
        # submit lock (ordering vs `started` fixed at stage time, no
        # disk I/O behind the queue mutex); flushed below once the job
        # resolves, covering the expired-in-queue path too
        if not job.relaying:
            job.event.wait()
        else:
            self._stream_frames(job, conn)
        if self.journal is not None:
            self.journal.flush_staged()
        return job.response

    def _stream_frames(self, job: Job, conn: socket.socket) -> dict:
        """Forward the job's outbox — `progress` events and streamed
        `result_part` frames — as interleaved frames on the submitting
        connection while waiting for the result, including
        queue-position updates while the job is still pending. Returns
        the final response for the handler to send LAST, so the wire
        order is (progress|result_part)*, result. A client that stops
        reading only loses its interleaved frames (the first send error
        stops forwarding); the job itself runs to completion and is
        accounted normally either way — a mid-stream disconnect never
        touches the feeder or any other job."""
        seq = 0
        last_pos = None
        send_ok = True

        def push(ev: dict) -> None:
            nonlocal seq, send_ok
            if not send_ok:
                return
            if ev.get("type") == "result_part":
                # worker-built, ready to send (carries its own `part`
                # ordinal); only the trace context is stamped here
                frame = ev
            else:
                seq += 1
                frame = {"type": "progress", "job_id": job.id,
                         "seq": seq}
                frame.update(ev)
            if job.trace_id:
                frame.setdefault("trace_id", job.trace_id)
            try:
                send_frame(conn, frame)
            except (OSError, ProtocolError):
                send_ok = False

        last_version = None
        while True:
            ev = job.next_frame(timeout=0.05)
            if ev is not None:
                push(ev)
                continue
            if job.event.is_set():
                break
            # position recomputes (an O(depth) DRR simulation under the
            # queue mutex) only when the queue actually moved, and not
            # at all once the client stopped reading
            if job.started_t is None and send_ok and job.want_progress:
                version = self.queue.version
                if version != last_version:
                    last_version = version
                    pos = self.queue.position(job)
                    if pos is not None and pos != last_pos:
                        last_pos = pos
                        push({"phase": "queued", "position": pos,
                              "depth": len(self.queue)})
        # the worker set the event after its last notify: drain the tail
        while True:
            ev = job.next_frame()
            if ev is None:
                break
            push(ev)
        return job.response

    # ------------------------------------------------------------ workers
    def _worker(self) -> None:
        while True:
            job = self.queue.pop(timeout=0.2)
            if job is None:
                if self._stop_workers.is_set() and not len(self.queue):
                    return
                continue
            self._process_one(job)

    def _surge_worker(self) -> None:
        """One-shot worker spawned by a preemption: the victim's
        worker thread stays blocked in its consensus consume loop (its
        windows are parked, not failed), so the capacity the preemption
        freed needs a thread to spend it on the high-priority job —
        which the queue's priority-first pop hands over next."""
        job = self.queue.pop(timeout=1.0)
        if job is not None:
            self._process_one(job)

    def _process_one(self, job: Job) -> None:
        with self._idle:
            self._inflight += 1
        with self._qos_lock:
            self._running_jobs[job.id] = job
        t0 = time.perf_counter()
        try:
            resp = self._run_job(job)
            ok = True
        except JobCancelledError as exc:
            # typed terminal for the cancel RPC's running-job path: the
            # batcher's withdrawal seam (or the round-boundary flag)
            # raised this through the job's own thread
            if self.journal is not None:
                self.journal.record("cancelled", job=job.id,
                                    trace=job.trace_id,
                                    state="running")
            resp = error_response(
                "cancelled", str(exc), job_id=job.id,
                error_type=type(exc).__name__,
                queue_wait_s=round(job.queue_wait_s, 4))
            ok = False
        except DeadlineDoomed as exc:
            # mid-run speculative abort: the iteration-boundary
            # estimate said the deadline is provably lost — the job
            # fails typed within one iteration instead of at the end
            with self._qos_lock:
                self.qos["aborted_doomed"] += 1
            if self.journal is not None:
                self.journal.record(
                    "deadline-doomed", job=job.id, trace=job.trace_id,
                    phase=exc.phase,
                    predicted_s=round(exc.predicted_s, 3),
                    remaining_s=round(exc.remaining_s, 3))
            resp = error_response(
                "deadline-doomed", str(exc), job_id=job.id,
                error_type=type(exc).__name__,
                predicted_s=round(exc.predicted_s, 3),
                remaining_s=round(exc.remaining_s, 3),
                queue_wait_s=round(job.queue_wait_s, 4))
            ok = False
        except Exception as exc:
            # per-job failure isolation: the job answers typed, the
            # server and its warm engines survive
            resp = error_response(
                "job-failed", str(exc), job_id=job.id,
                error_type=type(exc).__name__,
                queue_wait_s=round(job.queue_wait_s, 4))
            ok = False
        job.response = resp
        try:
            # fold the job's own latency histograms (align phase,
            # solo rounds, polisher phases, compiles) into the
            # lifetime scrape view — on FAILURE too: the
            # pathological jobs are exactly the ones the p99s must
            # not exclude. (Shared batch rounds already observe
            # into the server set directly.)
            if job.stats_ref is not None \
                    and job.stats_ref.hists is not None:
                self.hists.merge(job.stats_ref.hists)
            service_s = time.perf_counter() - t0
            # latency exemplar: the job-latency bucket this job
            # lands in remembers WHO it was (trace id) and — for a
            # failed / deadline-missed job — the flight dump the
            # worker is about to write, so a fleet p99 bucket
            # clicks through to the exact job's Chrome trace. The
            # dump path is deterministic (_flight_dump names it
            # identically below).
            exemplar = None
            if self.exemplars_enabled:
                exemplar = {"trace_id": job.trace_id or job.id,
                            "job": job.id}
                will_miss = (job.deadline is not None
                             and time.perf_counter() > job.deadline)
                if (not ok or will_miss) and self.config.flight_dir:
                    reason = ("job-failed" if not ok
                              else "deadline-miss")
                    exemplar["flight"] = os.path.join(
                        self.config.flight_dir,
                        f"flight_{job.id}_{reason}.json")
            missed = self.queue.task_done(job, ok, service_s,
                                          exemplar=exemplar)
            if self.journal is not None:
                batch = ((resp.get("serve") or {}).get("batch")
                         if ok else None) or {}
                if batch:
                    self.journal.record(
                        "iterations", job=job.id,
                        trace=job.trace_id,
                        iterations=batch.get("iterations"),
                        shared=batch.get("shared_iterations"),
                        windows=batch.get("windows"))
                if missed:
                    self.journal.record("deadline-miss", job=job.id,
                                        trace=job.trace_id)
                self.journal.record(
                    "finished" if ok else "failed",
                    job=job.id, trace=job.trace_id,
                    service_s=round(service_s, 4),
                    sequences=resp.get("sequences"),
                    error_type=resp.get("error_type"))
            if not ok or missed:
                # post-mortem artifact: the flight ring windowed to
                # this job, with its stage stats riding along
                # (obs/flight.py). Written BEFORE the waiter is
                # unblocked, so a client reacting to its error
                # response finds the dump already listed by `debug`
                self._flight_dump(
                    job,
                    "job-failed" if not ok else "deadline-miss",
                    resp)
        except Exception as exc:  # noqa: BLE001
            # telemetry accounting must never kill the worker or
            # strand the waiter blocked on job.event
            log_info(f"[racon_tpu::serve] warning: post-job "
                     f"telemetry failed ({type(exc).__name__}: "
                     f"{exc})")
        finally:
            job.finish()
        self._qos_job_done(job)
        with self._idle:
            self._inflight -= 1
            self._idle.notify_all()

    # ---------------------------------------------------------------- qos
    def _qos_job_done(self, job: Job) -> None:
        """Post-terminal QoS bookkeeping: drop the job from the
        running set, clean any parked state it left in the batcher
        (a job can terminate WHILE preempted — cancelled, or finished
        because all of its windows were already in flight when the
        withdrawal landed), then hand freed capacity to the
        highest-priority parked job."""
        with self._qos_lock:
            self._running_jobs.pop(job.id, None)
            if not self.config.preempt:
                return
            was_parked = self._preempted.pop(job.id, None) is not None
        if was_parked:
            # releases the withdrawn mark and any still-parked entries
            # so the pools never leak a dead job's windows
            self.batcher.resume_job(job.id)
            if self.journal is not None:
                self.journal.record("resumed", job=job.id,
                                    trace=job.trace_id,
                                    reason="terminal")
        self._maybe_resume()

    def _maybe_preempt(self, job: Job) -> None:
        """A newly admitted job preempts the lowest-priority running
        job of a strictly lower class: the victim's not-yet-dispatched
        pooled windows are parked between iterations (completed
        windows stay — ContigStreamer tolerates the gap) and a surge
        worker thread spends the freed capacity on the new job.
        Fault-injected and strict jobs run the solo path and are
        never victims."""
        if not self.config.preempt:
            return
        with self._qos_lock:
            active = [j for jid, j in self._running_jobs.items()
                      if jid not in self._preempted]
            if len(active) < self.config.workers:
                return  # free capacity: no need to take any back
            victims = [j for j in active
                       if j.priority < job.priority
                       and j.fault_plan is None and not j.strict]
            if not victims:
                return
            victim = min(victims, key=lambda j: j.priority)
            self._preempted[victim.id] = victim
            self.qos["preemptions"] += 1
        parked = self.batcher.withdraw_job(victim.id)
        if self.journal is not None:
            self.journal.record(
                "preempted", job=victim.id, trace=victim.trace_id,
                by=job.id, priority=victim.priority,
                by_priority=job.priority, windows=parked)
        log_info(f"[racon_tpu::serve] preempted job {victim.id} "
                 f"(class {victim.priority}) for {job.id} "
                 f"(class {job.priority}): {parked} windows parked")
        threading.Thread(target=self._surge_worker,
                         name="racon-tpu-serve-surge",
                         daemon=True).start()

    def _maybe_resume(self) -> None:
        """Resume the highest-priority parked job once capacity frees
        — unless a strictly higher class is still waiting in the
        queue, which keeps its claim on the freed slot."""
        if not self.config.preempt:
            return
        top = self.queue.highest_queued_priority()
        with self._qos_lock:
            if not self._preempted:
                return
            active = len(self._running_jobs) - len(self._preempted)
            if active >= self.config.workers:
                return
            cand = max(self._preempted.values(),
                       key=lambda j: j.priority)
            if top is not None and top > cand.priority:
                return
            del self._preempted[cand.id]
        n = self.batcher.resume_job(cand.id)
        if self.journal is not None:
            self.journal.record("resumed", job=cand.id,
                                trace=cand.trace_id, windows=n)
        log_info(f"[racon_tpu::serve] resumed job {cand.id}: "
                 f"{n} windows back in pool")

    def _cancel(self, req: dict) -> dict:
        """Cancel RPC: dequeue a pending job (typed `cancelled`
        response delivered through its queue slot) or withdraw a
        running one (the batcher fails its tickets; solo/isolated
        jobs see the round-boundary flag instead)."""
        job_id = req.get("job_id")
        trace_id = req.get("trace_id")
        if not job_id and not trace_id:
            return error_response(
                "bad-request", "cancel needs job_id or trace_id")
        job = self.queue.cancel(job_id=job_id, trace_id=trace_id)
        if job is not None:
            with self._qos_lock:
                self.qos["cancelled"] += 1
            if self.journal is not None:
                self.journal.flush_staged()
            return {"type": "ok", "cancelled": "queued",
                    "job_id": job.id}
        with self._qos_lock:
            running = self._running_jobs.get(job_id or "")
            if running is None and trace_id:
                for j in self._running_jobs.values():
                    if j.trace_id == trace_id:
                        running = j
                        break
            if running is not None:
                self.qos["cancelled"] += 1
        if running is None:
            return error_response(
                "unknown-job", "no queued or running job matches",
                job_id=job_id, trace_id=trace_id)
        # round-boundary fallback for solo/isolated jobs the pools
        # never see; the pooled path fails the tickets directly
        running.cancelled = True
        pooled = self.batcher.cancel_job(running.id)
        return {"type": "ok", "cancelled": "running",
                "job_id": running.id, "pooled": pooled}

    def _run_job(self, job: Job) -> dict:
        from ..core.polisher import PolisherType, create_polisher

        opts, cfg = job.options, self.config
        t0 = time.perf_counter()
        trace_ctx = (obs_trace.scoped() if job.want_trace
                     else contextlib.nullcontext())
        with strict_scope(job.strict), trace_ctx as rec:
            if job.want_trace:
                # the job's timeline starts at ENQUEUE, not at this
                # worker pop: rebase the fresh per-job recorder so the
                # queue-wait span keeps its real offset, then record it
                # tagged with the client's trace context
                rec.rebase(job.enqueued_t)
                rec.complete("serve.queue_wait", job.enqueued_t,
                             job.started_t or t0,
                             {"job": job.id, "trace_id": job.trace_id})
            elif self._flight is not None:
                # untraced jobs (the router's child shards deliberately
                # run without a scoped trace — obs/trace.scoped
                # serializes on a module lock, which would serialize
                # same-replica shards) still leave their queue-wait in
                # the always-on flight ring, tagged with the trace id,
                # so a later `trace_pull` can window them out
                self._flight.complete(
                    "serve.queue_wait", job.enqueued_t,
                    job.started_t or t0,
                    {"job": job.id, "trace_id": job.trace_id})
            polisher = create_polisher(
                job.sequences, job.overlaps, job.target,
                PolisherType.kF if opts.get("fragment_correction")
                else PolisherType.kC,
                int(opts.get("window_length", cfg.window_length)),
                float(opts.get("quality_threshold",
                               cfg.quality_threshold)),
                float(opts.get("error_threshold", cfg.error_threshold)),
                bool(opts.get("trim", cfg.trim)),
                int(opts.get("match", cfg.match)),
                int(opts.get("mismatch", cfg.mismatch)),
                int(opts.get("gap", cfg.gap)),
                num_threads=cfg.job_threads,
                tpu_poa_batches=int(
                    opts.get("tpu_poa_batches", cfg.tpu_poa_batches)),
                tpu_banded_alignment=bool(
                    opts.get("tpu_banded_alignment",
                             cfg.tpu_banded_alignment)),
                tpu_aligner_batches=int(
                    opts.get("tpu_aligner_batches",
                             cfg.tpu_aligner_batches)),
                tpu_aligner_band_width=int(
                    opts.get("tpu_aligner_band_width",
                             cfg.tpu_aligner_band_width)),
                tpu_engine=opts.get("tpu_engine", cfg.tpu_engine),
                tpu_pipeline_depth=int(
                    opts.get("tpu_pipeline_depth",
                             cfg.tpu_pipeline_depth)),
                tpu_device_timeout=float(
                    opts.get("tpu_device_timeout",
                             cfg.tpu_device_timeout)),
                tpu_adaptive_buckets=cfg.tpu_adaptive_buckets,
                tpu_fault_plan=job.fault_plan)
            # live ref for the flight dump: a job that dies mid-phase
            # still gets its partial stage stats into the artifact
            job.stats_ref = polisher.pipeline_stats
            # trace context + live progress ride the polisher: the
            # batcher tags shared-round spans with serve_trace_id, and
            # progress events relay through the job to the handler;
            # the job id lets the audit sentinel journal a mismatch
            # into the OWNING job's timeline
            polisher.serve_trace_id = job.trace_id
            polisher.serve_job_id = job.id
            # tenant identity rides the polisher too: the batcher
            # prorates each lane iteration's device seconds onto the
            # tenants whose windows shared it (per-tenant device-cost
            # accounting, serve.tenant_device_seconds)
            polisher.serve_tenant = job.tenant
            # the absolute deadline rides the polisher so the batcher's
            # iteration-boundary doomed check can see it (mid-run
            # speculative abort, RACON_TPU_SERVE_ABORT_MARGIN)
            polisher.serve_deadline = job.deadline
            if job.cancelled:
                raise JobCancelledError("running")
            if job.want_progress:
                polisher.progress_hook = job.notify_progress
            if job.range_lo is not None:
                # sub-contig range shard: polish only the target
                # windows whose grid start falls in [lo, hi) — the
                # polisher emits bare-named segments and records the
                # stitch accounting in segment_meta (core/polisher.py)
                polisher.window_range = (job.range_lo, job.range_hi)
            if job.frag_lo is not None:
                # fragment child shard: correct only the reads whose
                # target-file index falls in [frag_lo, frag_hi) — the
                # read-axis twin of window_range (core/polisher.py
                # target_range)
                polisher.target_range = (job.frag_lo, job.frag_hi)
            polisher.initialize()
            # per-contig sink: every serve job stitches incrementally
            # through the continuous batcher, so each finished contig is
            # journaled (`part-streamed`, the obsreport --check receipt)
            # and — when the client asked to stream — shipped as a
            # `result_part` frame BEFORE the job completes. The
            # concatenation of parts is the job's full FASTA by
            # construction (ContigStreamer emits in contig order).
            parts: list[bytes] = []

            def on_part(seq) -> None:
                part = (b">" + seq.name.encode() + b"\n" + seq.data
                        + b"\n")
                parts.append(part)
                if self.journal is not None:
                    self.journal.record(
                        "part-streamed", job=job.id, trace=job.trace_id,
                        contig=seq.name.split(" ", 1)[0],
                        part=len(parts), bytes=len(part))
                frame = {"type": "result_part",
                         "job_id": job.id, "part": len(parts),
                         "name": seq.name,
                         "fasta": part.decode("latin-1")}
                if job.range_lo is not None:
                    # range shard: the frame carries the RAW segment
                    # body (no FASTA header/newline — Sequence.data has
                    # no newlines) plus the stitch accounting the
                    # router needs to re-derive the solo tags; the
                    # classic "parts' concatenation IS the body"
                    # contract deliberately does NOT apply here
                    # (protocol.py "Child-job fields")
                    frame["fasta"] = seq.data.decode("latin-1")
                    frame["seg"] = polisher.segment_meta.get(seq.name)
                job.notify_part(frame)

            def on_group(seqs, lo, hi) -> None:
                # fragment traffic class: targets are many small reads,
                # so corrected reads ship one result_part frame per
                # BOUNDED GROUP (cfg.frag_group consecutive targets,
                # core/polisher.FragmentStreamer), never one frame per
                # read. `lo`/`hi` are this polisher's local target
                # indices; the frame's `frag` receipt is rebased to the
                # GLOBAL read axis so a router's dedupe ledger can tile
                # [0, n_reads) across child shards. Dropped
                # (unpolished) reads still advance the receipt range,
                # so a group may carry fewer reads than indices — or
                # none at all.
                body = b"".join(b">" + s.name.encode() + b"\n" + s.data
                                + b"\n" for s in seqs)
                parts.append(body)
                if self.journal is not None:
                    self.journal.record(
                        "part-streamed", job=job.id, trace=job.trace_id,
                        part=len(parts), bytes=len(body),
                        reads=len(seqs))
                base = job.frag_lo or 0
                job.notify_part({"type": "result_part",
                                 "job_id": job.id, "part": len(parts),
                                 "reads": len(seqs),
                                 "frag": [base + lo, base + hi],
                                 "fasta": body.decode("latin-1")})

            drop = not opts.get("include_unpolished", False)
            per_round: list[dict] = []
            if job.rounds is None:
                # no rounds requested: the pre-rounds single-pass path,
                # byte-identical in output, journal and scrape
                if job.fragment:
                    polished = polisher.polish(
                        drop, batcher=self.batcher, on_group=on_group,
                        group_size=cfg.frag_group)
                else:
                    polished = polisher.polish(
                        drop, batcher=self.batcher, on_part=on_part)
            else:
                # serve-native polishing rounds: round k's stitched
                # contigs loop back as round k+1's draft WITHOUT
                # leaving the warm process — in-process re-overlap +
                # re-window (Polisher.redraft -> core/remap.py), warm
                # engines/jit caches/autotune posture carried across.
                # Only the FINAL round streams parts: the result_part
                # contract (and obsreport's parts-streamed receipt)
                # covers the job's authoritative output, not drafts.
                rounds = job.rounds
                with self._rounds_lock:
                    self._rounds["jobs"] += 1
                    self._rounds["inflight"] += 1
                try:
                    with tempfile.TemporaryDirectory(
                            prefix=f"racon_serve_rounds_{job.id}_") \
                            as workdir:
                        for rnd in range(1, rounds + 1):
                            final = rnd == rounds
                            if job.cancelled:
                                # round-boundary cancel fallback for
                                # solo/isolated jobs the pools miss
                                raise JobCancelledError("running")
                            if self.journal is not None:
                                self.journal.record(
                                    "round-started", job=job.id,
                                    trace=job.trace_id, round=rnd,
                                    of=rounds)
                            rt0 = time.perf_counter()
                            if job.fragment:
                                # only rounds == 1 reaches here (the
                                # submit validation rejects more), so
                                # `final` is always true — but keep the
                                # guard shape symmetric
                                polished = polisher.polish(
                                    drop, batcher=self.batcher,
                                    on_group=on_group if final
                                    else None,
                                    group_size=cfg.frag_group)
                            else:
                                polished = polisher.polish(
                                    drop, batcher=self.batcher,
                                    on_part=on_part if final else None)
                            wall = time.perf_counter() - rt0
                            batch = getattr(polisher, "serve_batch",
                                            None) or {}
                            info = {"round": rnd,
                                    "wall_s": round(wall, 4),
                                    "windows": batch.get("windows"),
                                    "iterations": batch.get(
                                        "iterations"),
                                    "sequences": len(polished)}
                            cache = getattr(polisher, "serve_cache",
                                            None)
                            if cache is not None:
                                info["cache"] = dict(cache)
                            per_round.append(info)
                            self.hists.observe(f"serve.round_{rnd}",
                                               wall)
                            if self.journal is not None:
                                self.journal.record(
                                    "round-finished", job=job.id,
                                    trace=job.trace_id, round=rnd,
                                    of=rounds, wall_s=round(wall, 4),
                                    sequences=len(polished),
                                    cache_hits=(cache or {}).get(
                                        "hits"))
                            with self._rounds_lock:
                                self._rounds["completed"] += 1
                            if not final:
                                polisher.redraft(polished, workdir,
                                                 tag=f"r{rnd}")
                                polisher.initialize()
                finally:
                    with self._rounds_lock:
                        self._rounds["inflight"] -= 1
        if job.cancelled:
            # a cancel that landed mid-run on a solo/isolated job has
            # no pooled tickets for the batcher to fail — honour it
            # here, before the completed work ships: cancel means the
            # bytes are unwanted, not that the run must have crashed
            raise JobCancelledError("running")
        # the response body comes from `polished`, NOT from the parts
        # collected in the callback: ContigStreamer swallows on_part
        # exceptions (streaming is decoration), so a callback bug may
        # lose a part — it must never truncate the authoritative body
        fasta = b"".join(b">" + s.name.encode() + b"\n" + s.data
                         + b"\n" for s in polished)
        resp = {"type": "result", "job_id": job.id,
                "sequences": len(polished),
                "metrics": polisher.metrics.snapshot(),
                "serve": {"queue_wait_s": round(job.queue_wait_s, 4),
                          "exec_s": round(time.perf_counter() - t0, 4),
                          "batch": getattr(polisher, "serve_batch",
                                           None)}}
        if job.rounds is not None:
            # rounds accounting block — present ONLY when the request
            # asked for rounds (a plain submit's response shape is
            # unchanged). Cache totals summed across rounds when the
            # window cache is armed.
            block = {"requested": job.rounds,
                     "completed": len(per_round),
                     "per_round": per_round}
            caches = [i["cache"] for i in per_round if i.get("cache")]
            if caches:
                block["cache"] = {
                    "hits": sum(c["hits"] for c in caches),
                    "misses": sum(c["misses"] for c in caches)}
            resp["rounds"] = block
        if job.want_stream:
            # the bytes already streamed as result_part frames; the
            # final frame carries the stats, not a second copy of the
            # assembly
            resp["streamed"] = True
            resp["parts"] = len(parts)
        else:
            resp["fasta"] = fasta.decode("latin-1")
        if job.want_trace:
            rec.complete("serve.job", t0, time.perf_counter(),
                         {"job": job.id, "trace_id": job.trace_id})
            resp["trace"] = rec.events()
            # the recorder's time zero in SERVER perf_counter terms:
            # with the ping handshake's clock offset, the client maps
            # every server span onto its own timeline (client.py)
            resp["trace_base_mono"] = rec._base
        elif self._flight is not None:
            # untraced twin of the span above, into the always-on ring,
            # for trace_pull (see the queue-wait comment)
            self._flight.complete(
                "serve.job", t0, time.perf_counter(),
                {"job": job.id, "trace_id": job.trace_id})
        return resp

    # -------------------------------------------------- flight recorder
    def _flight_dump(self, job: Job, reason: str,
                     resp: dict | None) -> None:
        """Write the flight ring, windowed to `job`, as a Chrome-trace
        artifact named for the job. Best-effort by design: a full disk
        or unwritable directory loses the artifact, never the server."""
        dirpath = self.config.flight_dir
        if not dirpath or self._flight is None:
            return
        try:
            os.makedirs(dirpath, exist_ok=True)
            path = os.path.join(dirpath,
                                f"flight_{job.id}_{reason}.json")
            info = {"job_id": job.id, "reason": reason,
                    "queue_wait_s": round(job.queue_wait_s, 4),
                    "error_type": (resp or {}).get("error_type"),
                    "message": (resp or {}).get("message"),
                    "stage_stats": (job.stats_ref.snapshot()
                                    if job.stats_ref is not None
                                    else None)}
            obs_flight.dump(self._flight, path,
                            since=job.started_t, flight=info)
            self._dumps.append(path)
            log_info(f"[racon_tpu::serve] flight recorder dumped to "
                     f"{path} ({reason})")
        except Exception as exc:  # noqa: BLE001 — full disk, an
            # unserializable span arg, anything: the artifact is lost,
            # never the job response or the server
            log_info(f"[racon_tpu::serve] warning: could not write "
                     f"flight dump ({type(exc).__name__}: {exc})")

    def debug_snapshot(self, max_events: int = 5000) -> dict:
        """The `debug` RPC body: the flight ring's most recent events
        (bounded so the response frame stays small) plus the automatic
        dump artifacts written so far."""
        events: list = []
        if self._flight is not None:
            events = obs_flight.window_events(self._flight)
            if max_events > 0 and len(events) > max_events:
                # keep thread metadata, trim the oldest spans
                meta = [e for e in events if e.get("ph") == "M"]
                rest = [e for e in events if e.get("ph") != "M"]
                events = meta + rest[-max_events:]
        return {"type": "debug", "events": events,
                "dumps": list(self._dumps),
                "flight_installed": self._flight_installed}

    def _trace_pull(self, req: dict) -> dict:
        """The `trace_pull` RPC body: flight-ring spans windowed to ONE
        distributed trace id (exact or dotted `<trace>.s<k>` child
        match — obs/flight.trace_events), with this process's recorder
        base and a fresh mono sample so the router can rebase the
        events onto its own timeline after a `clock_sync()`. An
        optional `trace_ids` list narrows the window to exactly those
        ids (union) — the router pulls each replica for only the child
        traces that completed there. Always-on: it reads the ring that
        is already recording, so pulling a trace costs the replica
        nothing beyond the reply frame."""
        trace_id = req.get("trace_id")
        if (not isinstance(trace_id, str) or not trace_id
                or len(trace_id) > 64
                or not set(trace_id) <= self._TRACE_ID_OK):
            return error_response(
                "bad-request", "trace_pull needs a trace_id of "
                "[A-Za-z0-9._-], at most 64 chars")
        want = trace_id
        tids = req.get("trace_ids")
        if tids is not None:
            if (not isinstance(tids, list) or not tids
                    or not all(isinstance(t, str) and t
                               and len(t) <= 64
                               and set(t) <= self._TRACE_ID_OK
                               for t in tids)):
                return error_response(
                    "bad-request", "trace_pull trace_ids must be a "
                    "non-empty list of [A-Za-z0-9._-] ids")
            want = tids
        events: list = []
        base = None
        if self._flight is not None:
            cap = req.get("max_events")
            events = obs_flight.trace_events(
                self._flight, want,
                max_events=int(cap) if cap is not None else None)
            base = self._flight._base
        return {"type": "trace", "trace_id": trace_id,
                "events": events, "base_mono": base,
                "mono_s": time.perf_counter()}

    # --------------------------------------------------------- exposition
    def prometheus_text(self) -> str:
        """One Prometheus scrape body (obs/prom.py): lifetime counters,
        live gauges and every latency histogram — refreshed at call
        time, safe to call at any lifecycle point including drain."""
        t_render = time.perf_counter()
        q = self.queue.snapshot()
        b = self.batcher.snapshot()
        counters = {f"serve.jobs.{k}": q[k] for k in (
            "submitted", "admitted", "rejected_full",
            "rejected_draining", "rejected_quota", "expired",
            "completed", "failed", "deadline_hit", "deadline_miss")}
        counters["serve.batch.iterations"] = b["iterations"]
        counters["serve.batch.shared_iterations"] = \
            b["shared_iterations"]
        counters["serve.batch.windows"] = b["windows"]
        # measured per-iteration host overhead (iteration wall minus
        # device-stage seconds), cumulative — the dispatch-loop number
        counters["serve.batch.host_seconds"] = round(
            b.get("host_s", 0.0), 4)
        counters["serve.compiles"] = b["compiles"]
        for lane in b.get("lanes") or ():
            counters[f"serve.lane.{lane['lane']}.iterations"] = \
                lane["iterations"]
        # per-tenant fairness receipts. Tenant ids embed in the metric
        # NAME, so only ids that survive Prometheus sanitization
        # unchanged ([A-Za-z0-9_]) are exported — 'team.a' and
        # 'team-a' would otherwise collide into one duplicated series
        # and invalidate the whole scrape. Skipped tenants (and the
        # anonymous "" tenant) remain fully visible in the `stats`
        # response's tenants view.
        for tenant, tc in (q.get("tenants") or {}).items():
            if tenant and all(c.isalnum() or c == "_" for c in tenant):
                counters[f"serve.tenant.{tenant}.admitted"] = \
                    tc["admitted"]
                counters[f"serve.tenant.{tenant}.completed"] = \
                    tc["completed"]
        if self.journal is not None:
            counters["serve.journal.events"] = self.journal.events
            counters["serve.journal.dropped"] = self.journal.dropped
        # autotuner decision receipts: which (engine, kernel, dtype)
        # decision the persisted winner tables handed each dispatcher —
        # the fleet view of which buckets run which kernel plane
        from ..sched.autotune import get_autotuner

        consults = get_autotuner().consult_counts()
        if consults:
            counters["sched.autotune.consults"] = obs_prom.Labeled(
                consults, "winner-table consults by decision "
                "(decision 'none' = cold bucket, XLA default)")
        gauges = {
            "serve.uptime_seconds": (
                round(time.perf_counter() - self._t_start, 3),
                "seconds since this server process started serving"),
            "serve.start_time_seconds": (
                round(self._t_wall_start, 3),
                "unix time the server started (restart detector: a "
                "counter reset with an unchanged start_time is a bug, "
                "with a changed one a restart)"),
            "serve.queue_depth": q["depth"],
            "serve.queue_capacity": q["maxsize"],
            "serve.queue_oldest_wait_seconds": q.get("oldest_wait_s",
                                                     0.0),
            "serve.inflight": self._inflight_count(),
            "serve.draining": self._draining.is_set(),
            "serve.service_time_ema_seconds": q["ema_service_s"],
            "serve.worker_lanes": b.get("worker_lanes", 1),
        }
        for lane in b.get("lanes") or ():
            gauges[f"serve.lane.{lane['lane']}.busy"] = (
                lane["busy"],
                "1 while this worker lane is executing a device "
                "iteration (sub-mesh occupancy view)")
        for engine, e in (b.get("occupancy") or {}).items():
            if "occupancy_pct" in e:
                gauges[f"sched.{engine}.occupancy_pct"] = \
                    e["occupancy_pct"]
        # per-tenant live view as PROPERLY LABELED series (tenant ids
        # are label VALUES here, escaped — any validated id survives,
        # unlike the name-embedded lifetime counters above): queue
        # depth per tenant is what makes the fleet per-tenant view
        # possible at all, credit is the live DRR fairness dial
        tenants = q.get("tenants") or {}
        if tenants:
            gauges["serve.tenant_queue_depth"] = obs_prom.Labeled(
                [({"tenant": t}, tc.get("queued", 0))
                 for t, tc in sorted(tenants.items())],
                "live queued jobs per tenant")
            gauges["serve.tenant_credit"] = obs_prom.Labeled(
                [({"tenant": t}, tc.get("credit", 0.0))
                 for t, tc in sorted(tenants.items())],
                "accrued DRR credit per tenant (spent one per pop)")
        # per-tenant device-cost accounting (batcher proration of lane
        # iteration wall by window share). Armed-only like the views
        # above: appears once a NAMED tenant has accrued device time;
        # the "" bucket then rides along so the series sum stays equal
        # to total lane device seconds (test-pinned)
        tdev = b.get("tenant_device_s")
        if tdev:
            counters["serve.tenant_device_seconds"] = obs_prom.Labeled(
                [({"tenant": t}, v) for t, v in sorted(tdev.items())],
                "device-seconds charged per tenant (lane iteration "
                "wall prorated by window share; empty tenant label = "
                "untenanted traffic)")
        # identity-audit families (obs/audit.py) — rendered ONLY when
        # the sentinel is armed, so an audit-off scrape stays
        # byte-identical to the pre-audit exposition (test-pinned)
        if self.auditor is not None:
            a = self.auditor.snapshot()
            counters["audit.windows"] = (
                a["windows"], "windows that passed through audited "
                "iterations (the sampling denominator)")
            counters["audit.sampled"] = (
                a["sampled"], "windows selected by the content-keyed "
                "sample at the armed rate")
            counters["audit.shadow_seconds"] = round(a["shadow_s"], 4)
            counters["audit.repaired"] = a["repaired"]
            counters["audit.demotions"] = (
                a["demotions"], "autotuner winner entries online-"
                "demoted to the oracle candidate after a mismatch")
            counters["audit.shadow_launches"] = a["shadow"]["launches"]
            counters["audit.shadow_compiles"] = a["shadow"]["compiles"]
            mism = self.auditor.mismatch_samples()
            if mism:
                counters["audit.mismatches"] = obs_prom.Labeled(
                    mism, "confirmed silent-data-corruption events by "
                    "(engine, kernel, dtype, bucket, lane)")
            gauges["audit.rate"] = (
                a["rate"], "deterministic content-keyed sample "
                "fraction the sentinel audits at")
            gauges["audit.alert"] = (
                a["alert_firing"],
                "1 while unacknowledged identity mismatches exist "
                "(clear via the debug RPC's audit_ack)")
            lane_rows = b.get("lanes") or ()
            if lane_rows:
                gauges["lane_health"] = obs_prom.Labeled(
                    [({"lane": str(l["lane"])}, l["health"])
                     for l in lane_rows],
                    "audit-sentinel lane health: 1 healthy, 0 "
                    "quarantined, 0.5 degraded (failed re-probe, "
                    "last serving lane)")
        # content-addressed window cache families (serve/wincache.py)
        # — rendered ONLY when the cache is armed, so a cache-off
        # scrape stays byte-identical to the pre-cache exposition
        # (test-pinned). The labeled ops family federates through
        # FleetAggregator like any labeled series.
        wc = self.batcher.wincache
        if wc is not None:
            c = wc.snapshot()
            counters["serve.wincache.ops"] = obs_prom.Labeled(
                [({"op": "eviction"}, c["evictions"]),
                 ({"op": "hit"}, c["hits"]),
                 ({"op": "invalidation"}, c["invalidations"]),
                 ({"op": "miss"}, c["misses"]),
                 ({"op": "put"}, c["puts"]),
                 ({"op": "quarantined"}, c["quarantined"])],
                "window consensus cache operations by outcome (a hit "
                "skips device dispatch entirely)")
            counters["serve.wincache.hit_bytes"] = (
                c["hit_bytes"], "consensus bytes served straight from "
                "the cache instead of a device iteration")
            gauges["serve.wincache.bytes"] = (
                c["bytes"], "resident cache payload bytes (LRU-bounded "
                "by max_bytes)")
            gauges["serve.wincache.entries"] = c["entries"]
            gauges["serve.wincache.max_bytes"] = c["max_bytes"]
        # serve-native rounds families — rendered only once a rounds
        # job has been seen (same armed-only discipline)
        with self._rounds_lock:
            r = dict(self._rounds)
        if r["jobs"]:
            counters["serve.rounds_jobs"] = (
                r["jobs"], "jobs that requested serve-native "
                "polishing rounds (rounds=N on the submit frame)")
            counters["serve.rounds_completed"] = (
                r["completed"], "polishing rounds completed across "
                "all rounds jobs")
            gauges["serve.rounds_inflight"] = (
                r["inflight"], "rounds jobs currently executing "
                "(each loops drafts in-process between rounds)")
        # QoS families (preemption / doomed-abort / cancel) — rendered
        # ONLY when a QoS knob is armed or an event has fired, so a
        # QoS-off scrape stays byte-identical to the pre-QoS
        # exposition (test-pinned)
        with self._qos_lock:
            qos = dict(self.qos)
            preempted_now = len(self._preempted)
        cfg = self.config
        if (cfg.preempt or cfg.abort_margin is not None
                or cfg.tenant_burst > 0 or any(qos.values())):
            counters["serve.preemptions"] = (
                qos["preemptions"], "running jobs preempted by a "
                "higher priority class (windows parked, resumed "
                "byte-identically when capacity frees)")
            counters["serve.aborted_doomed"] = (
                qos["aborted_doomed"], "jobs failed fast with "
                "deadline-doomed (predicted finish past the deadline "
                "by more than the abort margin)")
            counters["serve.cancelled"] = (
                qos["cancelled"], "jobs cancelled via the cancel RPC "
                "(queued or running)")
            gauges["serve.preempted_inflight"] = (
                preempted_now, "jobs currently parked by preemption "
                "(their completed windows are kept)")
            if cfg.tenant_burst > 0:
                counters["serve.burst_admits"] = (
                    q.get("burst_admits", 0), "admissions over the "
                    "hard tenant quota paid for by burst tokens")
        # SLO burn-rate view (obs/fleet.py tracker, fed by the queue's
        # on_slo hook)
        burn = self.burn.state()
        gauges["slo.burn_rate"] = (
            burn["fast"], "fast-window SLO burn rate: deadline-miss "
            "rate over the window as a multiple of the error budget")
        gauges["slo.burn_rate_slow"] = burn["slow"]
        gauges["slo.burn_alert"] = (
            burn["firing"],
            "1 while both burn windows exceed the threshold")
        # self-metered scrape cost (PRIOR renders — this body reports
        # the totals as they stood when it started rendering)
        with self._scrape_lock:
            counters["serve.scrapes"] = self._scrape_count
            counters["serve.scrape_seconds"] = round(
                self._scrape_render_s, 6)
        body = obs_prom.render(counters, gauges, self.hists)
        with self._scrape_lock:
            self._scrape_count += 1
            self._scrape_render_s += time.perf_counter() - t_render
        return body

    # -------------------------------------------------------------- misc
    def _inflight_count(self) -> int:
        with self._idle:
            return self._inflight

    def stats_snapshot(self) -> dict:
        with self._idle:
            inflight = self._inflight
        q = self.queue.snapshot()
        latency = self.hists.get("job.latency")
        deadlined = q["deadline_hit"] + q["deadline_miss"]
        # QoS view — present only when armed or an event fired (the
        # same discipline as the scrape families), so a QoS-off stats
        # body is byte-identical to pre-QoS output
        with self._qos_lock:
            qos = dict(self.qos)
            qos["preempted_inflight"] = len(self._preempted)
        cfg = self.config
        qos_armed = (cfg.preempt or cfg.abort_margin is not None
                     or cfg.tenant_burst > 0
                     or any(v for k, v in qos.items()))
        out = {"uptime_s": round(time.perf_counter() - self._t_start, 3),
                "warm": self._warm,
                "inflight": inflight,
                "draining": self._draining.is_set(),
                "queue": q,
                "batcher": self.batcher.snapshot(),
                # the SLO view: deadline hit/miss plus the rolling
                # latency window — the SAME service-time stream the
                # admission retry-after EMA is computed from
                "slo": {"deadline_hit": q["deadline_hit"],
                        "deadline_miss": q["deadline_miss"],
                        "expired": q["expired"],
                        "burn": self.burn.state(),
                        "miss_rate": round(
                            q["deadline_miss"] / deadlined, 4)
                        if deadlined else 0.0,
                        "recent": q.get("recent"),
                        "latency": (latency.snapshot()
                                    if latency is not None else None)},
                "audit": (self.auditor.snapshot()
                          if self.auditor is not None else None),
                "flight": {"dumps": list(self._dumps),
                           "installed": self._flight_installed},
                "journal": ({"path": self.config.journal_path,
                             "events": self.journal.events,
                             "dropped": self.journal.dropped}
                            if self.journal is not None else None)}
        if qos_armed:
            qos["preempt"] = cfg.preempt
            out["qos"] = qos
        return out

    @property
    def address(self) -> str:
        return self.config.address


# ------------------------------------------------------------------ CLI
def serve_main(argv: list[str]) -> int:
    """`racon_tpu serve` entry point: run a PolishServer until SIGTERM /
    SIGINT, then drain gracefully."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        prog="racon_tpu serve",
        description="warm polishing job server (unix socket or "
                    "localhost TCP; see README 'Serving')")
    ap.add_argument("--socket", default=None,
                    help=f"unix socket path (default "
                         f"RACON_TPU_SERVE_SOCKET or {DEFAULT_SOCKET})")
    ap.add_argument("--port", type=int, default=None,
                    help="listen on localhost TCP instead of the unix "
                         "socket (0 = ephemeral)")
    ap.add_argument("--workers", type=int, default=None,
                    help="job worker threads (RACON_TPU_SERVE_WORKERS, "
                         "default 2)")
    ap.add_argument("--queue-depth", type=int, default=None,
                    help="admission-control queue bound "
                         "(RACON_TPU_SERVE_QUEUE_DEPTH, default 16)")
    ap.add_argument("--drain-timeout", type=float, default=None,
                    help="graceful-drain budget in seconds "
                         "(RACON_TPU_SERVE_DRAIN_S, default 30)")
    ap.add_argument("--max-wait-ms", type=float, default=None,
                    help="continuous feeder: let a sparse window pool "
                         "coalesce up to this long before a short "
                         "device iteration (RACON_TPU_SERVE_MAX_WAIT_MS"
                         ", default 0 — dispatch immediately)")
    ap.add_argument("--iteration-windows", type=int, default=None,
                    help="continuous feeder: max windows per device "
                         "iteration — the latency quantum under load "
                         "(RACON_TPU_SERVE_ITERATION_WINDOWS, default "
                         "256)")
    ap.add_argument("--tenant-weights", default=None,
                    help="per-tenant fair-scheduling weights, e.g. "
                         "'gold=4,free=1,default=1' "
                         "(RACON_TPU_SERVE_TENANT_WEIGHTS)")
    ap.add_argument("--tenant-quota", type=int, default=None,
                    help="hard per-tenant admission quota: max QUEUED "
                         "jobs per tenant, excess submits rejected "
                         "typed with retry_after "
                         "(RACON_TPU_SERVE_TENANT_QUOTA, default 0 = "
                         "off)")
    ap.add_argument("--worker-lanes", type=int, default=None,
                    help="partition the device mesh into this many "
                         "sub-mesh worker lanes, each with its own "
                         "feeder thread + engines, so device "
                         "iterations run concurrently across the "
                         "slice (RACON_TPU_WORKER_LANES, default 1; "
                         "clamps to the device count; output stays "
                         "byte-identical at any lane count)")
    ap.add_argument("--gather-ms", type=float, default=None,
                    help="DEPRECATED (round-barrier era): aliased to "
                         "--max-wait-ms with a deprecation warning")
    ap.add_argument("--audit-rate", type=float, default=None,
                    help="identity-audit sentinel: deterministically "
                         "sample this fraction of production windows "
                         "(content-keyed hash, no RNG) and shadow "
                         "re-execute them through the oracle path, "
                         "byte-comparing consensus output "
                         "(RACON_TPU_AUDIT_RATE, default 0 = off; "
                         "companions RACON_TPU_AUDIT_DEMOTE / "
                         "RACON_TPU_LANE_QUARANTINE gate the mismatch "
                         "consequences)")
    ap.add_argument("--wincache", action="store_true", default=None,
                    help="arm the content-addressed window cache: "
                         "windows whose (content, engine parameters, "
                         "kernel posture) key was already polished "
                         "skip device dispatch entirely and reuse the "
                         "stored consensus (RACON_TPU_WINCACHE; "
                         "biggest win with rounds=N where later "
                         "rounds converge; output stays "
                         "byte-identical, audit-compatible)")
    ap.add_argument("--wincache-max-bytes", type=int, default=None,
                    help="window-cache capacity bound in bytes, "
                         "LRU-evicted (RACON_TPU_WINCACHE_MAX_BYTES, "
                         "default 64 MiB)")
    ap.add_argument("--frag-group", type=int, default=None,
                    help="reads per streamed result_part frame on "
                         "fragment-correction jobs "
                         "(RACON_TPU_FRAG_GROUP, default 64; keep "
                         "homogeneous across a routed fleet — the "
                         "router's requeue dedupe assumes replicas "
                         "decompose a shard into the same read groups)")
    ap.add_argument("--preempt", action="store_true", default=None,
                    help="arm priority preemption: a newly admitted "
                         "higher-priority job parks the pooled windows "
                         "of a running lower-class job between device "
                         "iterations, resuming it byte-identically "
                         "when capacity frees (RACON_TPU_SERVE_PREEMPT"
                         ", default off)")
    ap.add_argument("--abort-margin", type=float, default=None,
                    help="speculative deadline-abort margin in "
                         "seconds: fail a job fast with "
                         "deadline-doomed when its predicted finish "
                         "exceeds the deadline by more than this, at "
                         "admission and at iteration boundaries "
                         "(RACON_TPU_SERVE_ABORT_MARGIN, default off)")
    ap.add_argument("--tenant-burst", type=int, default=None,
                    help="per-tenant burst tokens on top of the hard "
                         "quota: a tenant may exceed --tenant-quota by "
                         "up to this many queued jobs, tokens refilled "
                         "at its DRR weight per second "
                         "(RACON_TPU_SERVE_TENANT_BURST, default 0 = "
                         "off)")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the synthetic warmup job (first real "
                         "request pays the compiles)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus text metrics on this "
                         "localhost HTTP port (0 = ephemeral; "
                         "RACON_TPU_SERVE_METRICS_PORT; the `scrape` "
                         "RPC works regardless)")
    ap.add_argument("--flight-dir", default=None,
                    help="directory for automatic flight-recorder "
                         "dumps of failed / deadline-missed jobs "
                         "(RACON_TPU_SERVE_FLIGHT_DIR, falling back to "
                         "RACON_TPU_FLIGHT_DIR, default "
                         "/tmp/racon_tpu_flight; '' disables; an "
                         "unwritable path fails the start)")
    ap.add_argument("--journal", default=None,
                    help="durable JSONL event journal of every job "
                         "lifecycle transition, keyed by job and trace "
                         "id (RACON_TPU_SERVE_JOURNAL; size-bounded "
                         "via RACON_TPU_JOURNAL_MAX_BYTES; render with "
                         "tools/obsreport.py; an unwritable path fails "
                         "the start)")
    ap.add_argument("-w", "--window-length", type=int, default=500)
    ap.add_argument("-q", "--quality-threshold", type=float, default=10.0)
    ap.add_argument("-e", "--error-threshold", type=float, default=0.3)
    ap.add_argument("-m", "--match", type=int, default=3)
    ap.add_argument("-x", "--mismatch", type=int, default=-5)
    ap.add_argument("-g", "--gap", type=int, default=-4)
    ap.add_argument("-t", "--threads", type=int, default=2,
                    help="host threads per job")
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--tpualigner-band-width", type=int, default=0)
    ap.add_argument("--tpu-engine", choices=("session", "fused"),
                    default=None)
    ap.add_argument("--tpu-pipeline-depth", type=int, default=2)
    ap.add_argument("--tpu-adaptive-buckets", action="store_true")
    ap.add_argument("--tpu-compile-cache", default=None)
    args = ap.parse_args(argv)

    kw: dict = {
        "warmup": not args.no_warmup,
        "window_length": args.window_length,
        "quality_threshold": args.quality_threshold,
        "error_threshold": args.error_threshold,
        "match": args.match, "mismatch": args.mismatch, "gap": args.gap,
        "job_threads": args.threads,
        "tpu_poa_batches": args.tpupoa_batches,
        "tpu_aligner_batches": args.tpualigner_batches,
        "tpu_aligner_band_width": args.tpualigner_band_width,
        "tpu_engine": args.tpu_engine,
        "tpu_pipeline_depth": args.tpu_pipeline_depth,
        "tpu_adaptive_buckets": args.tpu_adaptive_buckets or None,
        "tpu_compile_cache": args.tpu_compile_cache,
    }
    if args.socket is not None:
        kw["socket_path"] = args.socket
    if args.port is not None:
        kw["port"] = args.port
    if args.metrics_port is not None:
        kw["metrics_port"] = args.metrics_port
    if args.flight_dir is not None:
        kw["flight_dir"] = args.flight_dir
    if args.journal is not None:
        kw["journal"] = args.journal
    if args.workers is not None:
        kw["workers"] = args.workers
    if args.queue_depth is not None:
        kw["queue_depth"] = args.queue_depth
    if args.drain_timeout is not None:
        kw["drain_timeout_s"] = args.drain_timeout
    if args.max_wait_ms is not None:
        kw["max_wait_s"] = args.max_wait_ms / 1000.0
    if args.iteration_windows is not None:
        kw["iteration_windows"] = args.iteration_windows
    if args.tenant_weights is not None:
        kw["tenant_weights"] = args.tenant_weights
    if args.tenant_quota is not None:
        kw["tenant_quota"] = args.tenant_quota
    if args.worker_lanes is not None:
        kw["worker_lanes"] = args.worker_lanes
    if args.audit_rate is not None:
        kw["audit_rate"] = args.audit_rate
    if args.frag_group is not None:
        kw["frag_group"] = args.frag_group
    if args.wincache:
        kw["wincache"] = True
    if args.wincache_max_bytes is not None:
        kw["wincache_max_bytes"] = args.wincache_max_bytes
    if args.preempt:
        kw["preempt"] = True
    if args.abort_margin is not None:
        kw["abort_margin"] = args.abort_margin
    if args.tenant_burst is not None:
        kw["tenant_burst"] = args.tenant_burst
    if args.gather_ms is not None:
        # deprecated alias: ServeConfig warns and maps it to max_wait_s
        kw["gather_window_s"] = args.gather_ms / 1000.0

    try:
        server = PolishServer(**kw).start()
    except (RaconError, OSError) as exc:
        print(f"[racon_tpu::serve] error: {exc}", file=sys.stderr)
        return 1

    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    while not stop.is_set() and not server._stopped.is_set():
        stop.wait(0.2)
    server.drain()
    return 0
