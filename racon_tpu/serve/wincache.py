"""Content-addressed window consensus cache: skip device dispatch on hit.

Later polishing rounds converge — most windows are byte-identical to
the previous round's — and the repo's core invariant says a window's
consensus bytes are a pure function of (window content, engine
parameters, kernel/dtype posture): independent of batch composition,
lane, mesh width and co-tenant jobs (test-pinned since PR-3, extended
across jobs by serve/batcher.py). That purity is exactly what makes a
consensus result CACHEABLE: `WindowCache` keys stored
consensus+polished bytes by

    (sha256 over the window content — the same bytes the audit
     sentinel's `obs/audit.py:window_sample_fraction` hashes, plus the
     window type,
     the batcher's engine-parameter key (`serve/batcher._engine_key`),
     the process kernel/dtype posture (`sched/autotune.posture_key`))

so a hit can ONLY return bytes some earlier dispatch of the same
content under the same engine identity produced. The batcher consults
the cache before a window enters the pooled iteration stream; a hit
returns the stored bytes and skips device dispatch entirely, a miss
populates on iteration completion (AFTER the audit pass, so repaired
bytes are what gets cached). Isolation jobs (own fault plan / strict
posture) neither consult nor populate — their bytes are deliberately
perturbed.

Safety properties:

  - BOUNDED: LRU by payload bytes (`max_bytes`), evicting oldest-used
    entries first; every eviction is counted.
  - THREAD-SAFE: one mutex; lookups/stores are dict operations, never
    device work.
  - INVALIDATED on autotuner demotion and lane quarantine (the batcher
    calls `invalidate_all` from `flush_lane_engines` /
    `quarantine_lane`): a demoted winner table or a suspect lane may
    have populated entries the new posture would not produce.
  - AUDITABLE: the sentinel keeps sampling cache-hit windows; a
    poisoned entry is caught as a mismatch, the production window is
    repaired with oracle bytes, and the ENTRY is quarantined — evicted
    and permanently refused (`quarantine`) — rather than demoting an
    engine or quarantining a lane that never touched it.

Env surface (strict parsing — a typo fails loudly, never silently
disables the cache): RACON_TPU_WINCACHE (integer; nonzero enables,
default off), RACON_TPU_WINCACHE_MAX_BYTES (positive integer, default
64 MiB)."""

from __future__ import annotations

import hashlib
import os
import struct
import threading
from collections import OrderedDict

from ..errors import RaconError

#: default LRU budget: 64 MiB of consensus payload
DEFAULT_MAX_BYTES = 64 * 1024 * 1024

#: accounting overhead charged per entry on top of the payload (key
#: digest + tuple + OrderedDict slot — an estimate, but it keeps a
#: flood of empty-consensus windows from evading the byte bound)
_ENTRY_OVERHEAD = 120


def window_content_digest(w) -> bytes:
    """SHA-256 over the full window content: the identical byte walk
    the audit sentinel samples on (backbone + layers + qualities +
    layer positions; obs/audit.py:window_sample_fraction), extended
    with the window type (kNGS/kTGS trim differently — same layers,
    different consensus bytes)."""
    h = hashlib.sha256()
    h.update(struct.pack("<i", int(w.type.value)))
    for seq, qual, (begin, end) in zip(w.sequences, w.qualities,
                                       w.positions):
        h.update(struct.pack("<Iii", len(seq), begin, end))
        h.update(seq)
        if qual:
            h.update(qual)
    return h.digest()


class WindowCache:
    """Bounded, thread-safe, content-addressed consensus cache (module
    docstring). One per PolishServer, wired onto the WindowBatcher."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES):
        self.max_bytes = max(1, int(max_bytes))
        self._lock = threading.Lock()
        #: key -> (consensus bytes, polished flag); OrderedDict order
        #: IS the LRU order (lookup moves to end)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        #: keys the audit sentinel condemned: evicted and refused
        #: forever (a poisoned producer may try to re-populate)
        self._quarantined: set[tuple] = set()
        self.counters = {"hits": 0, "misses": 0, "puts": 0,
                         "evictions": 0, "quarantined": 0,
                         "invalidations": 0, "hit_bytes": 0}
        self._bytes = 0

    # ------------------------------------------------------------ keying
    @staticmethod
    def key(w, engine_key: tuple, posture: tuple | None = None) -> tuple:
        """The full cache identity of one window under one engine
        configuration. Callers batching many windows should resolve
        `posture` once (sched/autotune.posture_key) and pass it in."""
        if posture is None:
            from ..sched.autotune import posture_key

            posture = posture_key()
        return (window_content_digest(w), engine_key, posture)

    # ----------------------------------------------------------- access
    def lookup(self, key: tuple):
        """(consensus, polished) for a hit, None for a miss (counted)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None or key in self._quarantined:
                self.counters["misses"] += 1
                return None
            self._entries.move_to_end(key)
            self.counters["hits"] += 1
            self.counters["hit_bytes"] += len(ent[0])
            return ent

    def store(self, key: tuple, consensus: bytes,
              polished: bool) -> None:
        """Populate one entry (no-op for quarantined keys), evicting
        LRU entries past the byte budget."""
        size = len(consensus) + _ENTRY_OVERHEAD
        with self._lock:
            if key in self._quarantined:
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[0]) + _ENTRY_OVERHEAD
            self._entries[key] = (bytes(consensus), bool(polished))
            self._bytes += size
            self.counters["puts"] += 1
            while self._bytes > self.max_bytes and self._entries:
                _k, (cons, _p) = self._entries.popitem(last=False)
                self._bytes -= len(cons) + _ENTRY_OVERHEAD
                self.counters["evictions"] += 1

    # ------------------------------------------------------ invalidation
    def quarantine(self, key: tuple) -> None:
        """Audit verdict for one entry: evict it and refuse the key
        forever (the sentinel calls this when a cache-hit window's
        bytes diverge from the oracle)."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= len(ent[0]) + _ENTRY_OVERHEAD
                self.counters["evictions"] += 1
            self._quarantined.add(key)
            self.counters["quarantined"] += 1

    def quarantined(self, key: tuple) -> bool:
        with self._lock:
            return key in self._quarantined

    def invalidate_all(self, reason: str = "") -> int:
        """Drop every entry (demotion / posture change / lane
        quarantine — the producer's identity is no longer trusted);
        quarantined keys stay condemned. Returns the entry count."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            self.counters["invalidations"] += 1
        if n:
            from ..utils.logger import log_info

            log_info(f"[racon_tpu::wincache] invalidated {n} entr"
                     f"{'y' if n == 1 else 'ies'}"
                     + (f" ({reason})" if reason else ""))
        return n

    # --------------------------------------------------------- exposure
    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["entries"] = len(self._entries)
            out["bytes"] = self._bytes
            out["max_bytes"] = self.max_bytes
            total = out["hits"] + out["misses"]
            out["hit_rate"] = (out["hits"] / total) if total else 0.0
        return out


def wincache_from_env() -> WindowCache | None:
    """The env-armed cache, or None when off. Strict parsing: a
    malformed value raises (naming the variable) instead of silently
    running uncached."""
    raw = os.environ.get("RACON_TPU_WINCACHE")
    if raw is None or raw == "":
        return None
    try:
        enabled = int(raw)
    except ValueError:
        raise RaconError(
            "WindowCache",
            f"invalid RACON_TPU_WINCACHE value {raw!r} "
            f"(expected an integer)") from None
    if not enabled:
        return None
    max_bytes = DEFAULT_MAX_BYTES
    raw = os.environ.get("RACON_TPU_WINCACHE_MAX_BYTES")
    if raw:
        try:
            max_bytes = int(raw)
        except ValueError:
            raise RaconError(
                "WindowCache",
                f"invalid RACON_TPU_WINCACHE_MAX_BYTES value {raw!r} "
                f"(expected an integer)") from None
        if max_bytes <= 0:
            raise RaconError(
                "WindowCache",
                f"invalid RACON_TPU_WINCACHE_MAX_BYTES value {raw!r} "
                f"(expected a positive integer)")
    return WindowCache(max_bytes=max_bytes)
