"""CIGAR string utilities.

A CIGAR is a run-length encoded alignment path: `<count><op>` pairs where op is
one of M/=/X (match columns), I (insertion to query), D/N (deletion from query),
S/H (clips), P (padding). Parsed once into parallel numpy arrays so downstream
walks (SAM span derivation, breaking-point extraction) are vectorized instead
of per-base loops (reference walks per base: src/overlap.cpp:60-108,244-292).
"""

from __future__ import annotations

import re

import numpy as np

_CIGAR_RE = re.compile(rb"(\d+)([MIDNSHP=X])")

# op codes used internally
OP_TO_CODE = {
    ord("M"): 0, ord("="): 0, ord("X"): 0,  # consume query + target
    ord("I"): 1,                              # consume query
    ord("D"): 2, ord("N"): 2,                 # consume target
    ord("S"): 3, ord("H"): 3,                 # clip (consume neither, here)
    ord("P"): 4,                              # padding
}


def parse_cigar(cigar: bytes | str) -> tuple[np.ndarray, np.ndarray]:
    """Parse CIGAR into (ops, lengths) int arrays. ops are raw ASCII codes."""
    if isinstance(cigar, str):
        cigar = cigar.encode()
    matches = _CIGAR_RE.findall(cigar)
    n = len(matches)
    ops = np.empty(n, dtype=np.uint8)
    lens = np.empty(n, dtype=np.int64)
    for i, (num, op) in enumerate(matches):
        ops[i] = op[0]
        lens[i] = int(num)
    return ops, lens


def cigar_from_ops(ops: "list[tuple[int, str]]") -> str:
    """Build a CIGAR string from (length, op_char) runs, merging adjacent
    runs with the same op."""
    parts: list[str] = []
    last_op: str | None = None
    last_len = 0
    for length, op in ops:
        if length == 0:
            continue
        if op == last_op:
            last_len += length
        else:
            if last_op is not None:
                parts.append(f"{last_len}{last_op}")
            last_op, last_len = op, length
    if last_op is not None:
        parts.append(f"{last_len}{last_op}")
    return "".join(parts)


def match_segments(ops: np.ndarray, lens: np.ndarray, t_start: int, q_start: int):
    """Return (t0, q0, length) arrays — the maximal runs of M/=/X columns —
    plus final (t_end, q_end) pointers, walking the CIGAR from (t_start,
    q_start). Coordinates are 0-based; a segment covers target positions
    [t0, t0+len) paired with query positions [q0, q0+len)."""
    is_m = (ops == ord("M")) | (ops == ord("=")) | (ops == ord("X"))
    is_q = is_m | (ops == ord("I"))
    is_t = is_m | (ops == ord("D")) | (ops == ord("N"))

    dq = np.where(is_q, lens, 0)
    dt = np.where(is_t, lens, 0)
    # coordinate BEFORE each run
    q_at = q_start + np.concatenate(([0], np.cumsum(dq)[:-1]))
    t_at = t_start + np.concatenate(([0], np.cumsum(dt)[:-1]))

    t0 = t_at[is_m]
    q0 = q_at[is_m]
    seg_len = lens[is_m]
    t_end = t_start + int(dt.sum())
    q_end = q_start + int(dq.sum())
    return t0, q0, seg_len, t_end, q_end
