"""Phase timing + progress logging to stderr.

Mirrors the reference Logger (src/logger.cpp:20-54): `log()` opens a timing
section, `log(msg)` closes it printing elapsed seconds, `bar(msg)` renders a
fixed 20-bin progress bar, `total(msg)` prints cumulative elapsed time.
"""

from __future__ import annotations

import sys
import threading
import time


class Logger:
    def __init__(self):
        self._time = 0.0
        self._bar = 0
        self._bar_count = 0
        self._bar_total = 0
        self._total = 0.0
        # bar() is ticked concurrently by the dispatch pipeline's unpack
        # worker and fallback pool (pipeline/__init__.py); the tick
        # read-modify-write needs the lock or progress is lost
        self._bar_lock = threading.Lock()

    def log(self, msg: str | None = None) -> None:
        now = time.perf_counter()
        if msg is None:
            self._time = now
            return
        elapsed = now - self._time
        self._total += elapsed
        print(f"{msg} {elapsed:.5f} s", file=sys.stderr)
        self._time = now

    def bar_total(self, total: int) -> None:
        """Arm the 20-bin progress bar for `total` upcoming bar() calls."""
        with self._bar_lock:
            self._bar_total = max(total, 1)
            self._bar_count = 0
            self._bar = 0

    def bar(self, msg: str) -> None:
        with self._bar_lock:
            self._bar_count += 1
            bins = min(20 * self._bar_count // self._bar_total, 20)
            if bins == self._bar and bins < 20:
                return
            self._bar = bins
            filled = "=" * bins + (">" if bins < 20 else "")
            sys.stderr.write(f"{msg} [{filled:<20}] {bins * 5}%")
            if bins == 20 and self._bar_count >= self._bar_total:
                elapsed = time.perf_counter() - self._time
                self._total += elapsed
                sys.stderr.write(f" {elapsed:.5f} s\n")
                self._bar = 0
                self._bar_count = 0
                self._time = time.perf_counter()
            else:
                sys.stderr.write("\r")
            sys.stderr.flush()

    def total(self, msg: str) -> None:
        elapsed = self._total + (time.perf_counter() - self._time if self._bar else 0)
        print(f"{msg} {elapsed:.5f} s", file=sys.stderr)
