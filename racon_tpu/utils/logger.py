"""Phase timing + progress logging to stderr, plus the leveled logger.

`Logger` mirrors the reference Logger (src/logger.cpp:20-54): `log()`
opens a timing section, `log(msg)` closes it printing elapsed seconds,
`bar(msg)` renders a fixed 20-bin progress bar (interactive \r redraws
only when stderr is a tty; piped/server runs get one completion line
per phase instead), `total(msg)` prints cumulative elapsed time.

The module-level functions are the observability layer's structured,
leveled logging (`RACON_TPU_LOG_LEVEL=quiet|info|debug`, default info):

  - `log_info(msg)` / `log_debug(msg)` — plain leveled stderr lines. At
    the default level every `log_info` prints exactly the text it is
    given, so migrating a raw `print(..., file=sys.stderr)` site onto it
    is byte-identical.
  - `warn_dedup(key, msg)` — once-per-run deduplication for warnings
    that repeat per chunk/window (host-fallback warnings flood stderr on
    large runs): the first occurrence of `key` prints at info, repeats
    are counted silently (every occurrence prints at debug), and
    `flush_dedup()` — called at end of run — reports the suppressed
    totals in one line per key.

Timing/progress prints from `Logger` honor the same level (quiet
silences them; timing ACCUMULATION is level-independent, so a quiet run
still carries correct totals into the metrics snapshot)."""

from __future__ import annotations

import os
import sys
import threading
import time

# ---------------------------------------------------------- leveled logging
QUIET, INFO, DEBUG = 0, 1, 2
_LEVELS = {"quiet": QUIET, "info": INFO, "debug": DEBUG}
#: the valid level names, in severity order — the one source of truth
#: the CLI's --tpu-log-level validation shares
LEVEL_NAMES = tuple(_LEVELS)

#: resolved-once level (RACON_TPU_LOG_LEVEL); None = not yet resolved
_level: int | None = None


def log_level() -> int:
    """The active level, resolved once from RACON_TPU_LOG_LEVEL (default
    info; unknown values fall back to info rather than crashing — a
    typo'd level must not take a run down)."""
    global _level
    if _level is None:
        name = (os.environ.get("RACON_TPU_LOG_LEVEL") or "info").strip()
        _level = _LEVELS.get(name.lower(), INFO)
    return _level


def set_log_level(name: str | None) -> None:
    """Pin the level (`quiet`/`info`/`debug`), or None to re-resolve
    from the environment on next use — tests and tools."""
    global _level
    if name is None:
        _level = None
        return
    if name not in _LEVELS:
        raise ValueError(f"set_log_level: unknown level {name!r} "
                         f"(expected one of {', '.join(_LEVELS)})")
    _level = _LEVELS[name]


def log_info(msg: str) -> None:
    if log_level() >= INFO:
        print(msg, file=sys.stderr)


def log_debug(msg: str) -> None:
    if log_level() >= DEBUG:
        print(msg, file=sys.stderr)


# ------------------------------------------------------- warning dedup
_dedup_lock = threading.Lock()
#: key -> count of suppressed repeats since the first occurrence
_dedup: dict[str, int] = {}


def warn_dedup(key: str, msg: str) -> None:
    """Leveled warning with once-per-run deduplication on `key` (the
    call-site identity, not the formatted text — per-chunk messages
    differ in counts/exception text but are the same warning). First
    occurrence prints at info; repeats are counted for `flush_dedup()`.
    At debug every occurrence prints in full."""
    with _dedup_lock:
        first = key not in _dedup
        _dedup[key] = 0 if first else _dedup[key] + 1
    lvl = log_level()
    if lvl >= DEBUG or (first and lvl >= INFO):
        print(msg, file=sys.stderr)


def flush_dedup() -> None:
    """End-of-run hook: report (and clear) the suppressed-repeat counts.
    Silent when nothing repeated, at debug (everything already printed),
    and at quiet."""
    with _dedup_lock:
        repeated = [(k, c) for k, c in _dedup.items() if c]
        _dedup.clear()
    if log_level() != INFO:
        return
    for key, count in repeated:
        print(f"[racon_tpu::obs] warning '{key}' repeated {count} more "
              f"time{'s' if count != 1 else ''} (suppressed; "
              "RACON_TPU_LOG_LEVEL=debug shows every occurrence)",
              file=sys.stderr)


def reset_dedup() -> None:
    """Drop dedup state without reporting (tests)."""
    with _dedup_lock:
        _dedup.clear()


def _stderr_is_tty() -> bool:
    """Checked per bar redraw (cheap, <= 21 calls per phase) rather than
    cached: tests and the serve layer swap sys.stderr mid-process."""
    try:
        return sys.stderr.isatty()
    except Exception:
        return False


class Logger:
    def __init__(self):
        self._time = 0.0
        self._bar = 0
        self._bar_count = 0
        self._bar_total = 0
        self._total = 0.0
        self._open = False
        # bar() is ticked concurrently by the dispatch pipeline's unpack
        # worker and fallback pool (pipeline/__init__.py); the tick
        # read-modify-write needs the lock or progress is lost
        self._bar_lock = threading.Lock()
        #: optional callable(count, total) fired at the same bin
        #: transitions the bar redraws at (<= 21 calls per phase) —
        #: the polisher's live-progress hook (core/polisher.py). Called
        #: OUTSIDE the bar lock so the callback may take its own locks.
        self.on_bar = None

    def log(self, msg: str | None = None) -> None:
        now = time.perf_counter()
        if msg is None:
            self._time = now
            self._open = True
            return
        elapsed = now - self._time
        self._total += elapsed
        if log_level() >= INFO:
            print(f"{msg} {elapsed:.5f} s", file=sys.stderr)
        self._time = now
        self._open = False

    def bar_total(self, total: int) -> None:
        """Arm the 20-bin progress bar for `total` upcoming bar() calls."""
        with self._bar_lock:
            self._bar_total = max(total, 1)
            self._bar_count = 0
            self._bar = 0

    def bar(self, msg: str) -> None:
        with self._bar_lock:
            self._bar_count += 1
            bins = min(20 * self._bar_count // self._bar_total, 20)
            if bins == self._bar and bins < 20:
                return
            notify = (self._bar_count, self._bar_total)
            self._bar = bins
            quiet = log_level() < INFO
            # the \r redraw protocol is unreadable spam when stderr is a
            # pipe (bench log tails, server mode): without a tty, emit
            # ONLY the phase's completion line — byte-identical to the
            # last line a tty would show. On a tty the classic bar is
            # preserved byte-for-byte.
            tty = not quiet and _stderr_is_tty()
            done = bins == 20 and self._bar_count >= self._bar_total
            if tty:
                filled = "=" * bins + (">" if bins < 20 else "")
                sys.stderr.write(f"{msg} [{filled:<20}] {bins * 5}%")
            if done:
                elapsed = time.perf_counter() - self._time
                self._total += elapsed
                if tty:
                    sys.stderr.write(f" {elapsed:.5f} s\n")
                elif not quiet:
                    sys.stderr.write(f"{msg} [{'=' * 20}] 100% "
                                     f"{elapsed:.5f} s\n")
                self._bar = 0
                self._bar_count = 0
                self._time = time.perf_counter()
            elif tty:
                sys.stderr.write("\r")
            if tty or (done and not quiet):
                sys.stderr.flush()
        if self.on_bar is not None:
            self.on_bar(*notify)

    def total(self, msg: str) -> None:
        # an open log() section counts its elapsed time even with no bar
        # mid-progress (it used to contribute 0 unless a bar was active);
        # after a bar completion or log(msg) close, _time was just reset,
        # so the addition is the genuine still-open remainder
        elapsed = self._total
        if self._open or self._bar:
            elapsed += time.perf_counter() - self._time
        if log_level() >= INFO:
            print(f"{msg} {elapsed:.5f} s", file=sys.stderr)
