"""Wrapper: subsample / split / multi-chunk polishing driver.

The capability of the reference's `racon_wrapper`
(scripts/racon_wrapper.py:57-147): optionally subsample the reads to a
target coverage, optionally split the target sequences into byte-bounded
chunks, then polish chunk by chunk so peak memory stays bounded — the
reference's only scale-out mechanism beyond one process (SURVEY.md §2c-7).

Differences from the reference, both deliberate:
  - rampler is replaced by the in-package racon_tpu.rampler (no external
    binary, gzip-transparent);
  - chunks are polished in-process (create_polisher per chunk) instead of
    shelling out, so device runtimes and compiled kernels are reused
    across chunks.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

from . import rampler
from .errors import RaconError


def run(sequences: str, overlaps: str, target_sequences: str,
        split: int | None = None, subsample: tuple[int, int] | None = None,
        include_unpolished: bool = False, fragment_correction: bool = False,
        window_length: int = 500, quality_threshold: float = 10.0,
        error_threshold: float = 0.3, match: int = 5, mismatch: int = -4,
        gap: int = -8, threads: int = 1, tpu_poa_batches: int = 0,
        tpu_aligner_batches: int = 0, tpu_banded_alignment: bool = False,
        num_shards: int = 1, shard_id: int = 0, out=None) -> None:
    """Polish `target_sequences`, optionally subsampled/split, writing
    FASTA to `out` (default stdout).

    `num_shards`/`shard_id` implement the multi-host scale-out story
    (SURVEY.md §5): the window workload is embarrassingly parallel and
    needs no inter-device communication, so hosts scale by FILE-LEVEL
    scatter/gather over DCN — each host polishes a contiguous block of the
    target chunks (chunks are byte-bounded, so blocks are balanced), and
    concatenating the shard outputs in shard order reproduces the
    unsharded output byte-for-byte. Requires --split so there is more
    than one unit to scatter."""
    from .core.polisher import create_polisher, PolisherType

    if not (0 <= shard_id < num_shards):
        raise RaconError(
            "wrapper", f"shard_id {shard_id} outside [0, {num_shards})")
    out = out if out is not None else sys.stdout.buffer
    work = tempfile.mkdtemp(prefix="racon_tpu_work_")
    try:
        if subsample is not None:
            ref_len, coverage = subsample
            print("[racon_tpu::wrapper] subsampling sequences", file=sys.stderr)
            sequences = rampler.subsample(sequences, ref_len, coverage, work)

        if split is not None:
            print("[racon_tpu::wrapper] splitting target sequences",
                  file=sys.stderr)
            targets = rampler.split(target_sequences, split, work)
            print(f"[racon_tpu::wrapper] total number of splits: "
                  f"{len(targets)}", file=sys.stderr)
        else:
            targets = [target_sequences]

        if num_shards > 1:
            if len(targets) < num_shards:
                # every shard must have work: silently-empty shard output
                # looks like a failed run to gather scripts
                raise RaconError(
                    "wrapper",
                    f"num_shards {num_shards} exceeds the {len(targets)} "
                    "target chunk(s); " +
                    ("use a smaller --split size or fewer shards"
                     if split is not None else
                     "--num-shards needs --split to make chunks to scatter"))
            lo = shard_id * len(targets) // num_shards
            hi = (shard_id + 1) * len(targets) // num_shards
            print(f"[racon_tpu::wrapper] shard {shard_id}/{num_shards}: "
                  f"chunks [{lo}, {hi}) of {len(targets)}", file=sys.stderr)
            targets = targets[lo:hi]

        for part in targets:
            polisher = create_polisher(
                sequences, overlaps, part,
                PolisherType.kF if fragment_correction else PolisherType.kC,
                window_length, quality_threshold, error_threshold, True,
                match, mismatch, gap, threads, tpu_poa_batches,
                tpu_banded_alignment, tpu_aligner_batches)
            polisher.initialize()
            for seq in polisher.polish(not include_unpolished):
                out.write(b">" + seq.name.encode() + b"\n" + seq.data + b"\n")
            out.flush()
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="racon_tpu_wrapper",
        description="racon_tpu wrapper adding sequence subsampling and "
                    "target splitting for bounded memory/runtime")
    parser.add_argument("sequences")
    parser.add_argument("overlaps")
    parser.add_argument("target_sequences")
    parser.add_argument("--split", type=int,
                        help="split target sequences into chunks of given "
                             "size in bytes")
    parser.add_argument("--subsample", nargs=2, type=int,
                        metavar=("REFERENCE_LENGTH", "COVERAGE"),
                        help="subsample sequences to coverage given the "
                             "reference length")
    parser.add_argument("-u", "--include-unpolished", action="store_true")
    parser.add_argument("-f", "--fragment-correction", action="store_true")
    parser.add_argument("-w", "--window-length", type=int, default=500)
    parser.add_argument("-q", "--quality-threshold", type=float, default=10.0)
    parser.add_argument("-e", "--error-threshold", type=float, default=0.3)
    parser.add_argument("-m", "--match", type=int, default=5)
    parser.add_argument("-x", "--mismatch", type=int, default=-4)
    parser.add_argument("-g", "--gap", type=int, default=-8)
    parser.add_argument("-t", "--threads", type=int, default=1)
    parser.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    parser.add_argument("--tpualigner-batches", type=int, default=0)
    parser.add_argument("-b", "--tpu-banded-alignment", action="store_true")
    parser.add_argument("--num-shards", type=int, default=1,
                        help="multi-host scale-out: total hosts polishing "
                             "this workload (file-level scatter over the "
                             "--split chunks; cat shard outputs in shard "
                             "order to gather)")
    parser.add_argument("--shard-id", type=int, default=0,
                        help="this host's shard index in [0, num_shards)")

    args = parser.parse_args(argv)
    try:
        run(args.sequences, args.overlaps, args.target_sequences,
            split=args.split,
            subsample=tuple(args.subsample) if args.subsample else None,
            include_unpolished=args.include_unpolished,
            fragment_correction=args.fragment_correction,
            window_length=args.window_length,
            quality_threshold=args.quality_threshold,
            error_threshold=args.error_threshold,
            match=args.match, mismatch=args.mismatch, gap=args.gap,
            threads=args.threads, tpu_poa_batches=args.tpupoa_batches,
            tpu_aligner_batches=args.tpualigner_batches,
            tpu_banded_alignment=args.tpu_banded_alignment,
            num_shards=args.num_shards, shard_id=args.shard_id)
    except RaconError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
