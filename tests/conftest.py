"""Test configuration.

Force the CPU backend with 8 virtual devices BEFORE jax initializes, so
sharding/mesh tests exercise the multi-chip code paths without TPU hardware
(the driver separately dry-runs the multi-chip path the same way).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/test/data")


@pytest.fixture(scope="session")
def reference_data():
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference test data not available")
    return REFERENCE_DATA
