"""Test configuration.

Force the CPU backend with 8 virtual devices BEFORE jax initializes, so
sharding/mesh tests exercise the multi-chip code paths without TPU hardware
(the driver separately dry-runs the multi-chip path the same way).

The axon TPU shim (PYTHONPATH=/root/.axon_site on this image) monkeypatches
jax at import and initializes its remote client even when JAX_PLATFORMS
selects cpu — and that client blocks indefinitely when the TPU tunnel is
unreachable. Tests are CPU-only by design, so the shim is stripped from the
import path before jax loads.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent XLA compile cache: the real-data device/fused fixtures compile
# full-envelope programs (minutes of XLA on this 1-core host); caching them
# across runs keeps the default suite affordable (same mechanism bench.py
# uses between its phases)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/racon_tpu_jax_cache")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path = [p for p in sys.path if "axon_site" not in p]
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and "axon_site" not in p)

# pytest plugins may have imported jax already (registration is done, but
# backend init is lazy) — deregister the axon backend factory so jax can
# never try to initialize the remote client, and pin the platform to cpu.
if "jax" in sys.modules:
    import jax

    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        _xb._platform_aliases.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")

import pathlib

import pytest

REFERENCE_DATA = pathlib.Path("/root/reference/test/data")


@pytest.fixture(scope="session")
def reference_data():
    if not REFERENCE_DATA.is_dir():
        pytest.skip("reference test data not available")
    return REFERENCE_DATA
