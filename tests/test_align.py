import numpy as np
import pytest

from racon_tpu.ops.align import BatchAligner, band_offsets, edit_distance


def _mutate(rng, seq: bytes, sub=0.05, ins=0.03, dele=0.03) -> bytes:
    bases = b"ACGT"
    out = bytearray()
    for ch in seq:
        r = rng.random()
        if r < dele:
            continue
        if r < dele + sub:
            out.append(bases[rng.integers(4)])
        else:
            out.append(ch)
        if rng.random() < ins:
            out.append(bases[rng.integers(4)])
    return bytes(out)


def _random_seq(rng, n) -> bytes:
    return bytes(rng.choice(np.frombuffer(b"ACGT", np.uint8), n))


def _cigar_cost_and_spans(runs, q: bytes, t: bytes):
    """Walk op runs, returning (cost, q_consumed, t_consumed)."""
    qi = ti = cost = 0
    for n, op in runs:
        if op == "M":
            for _ in range(n):
                cost += q[qi] != t[ti]
                qi += 1
                ti += 1
        elif op == "I":
            qi += n
            cost += n
        elif op == "D":
            ti += n
            cost += n
    return cost, qi, ti


def test_band_offsets_monotone_and_cover_corners():
    for m, n in [(100, 100), (37, 154), (500, 400), (1, 99)]:
        band = 32
        off = band_offsets(m, n, band, m + n + 1)
        steps = np.diff(off)
        assert ((steps == 0) | (steps == 1)).all()
        assert off[0] <= 0 < off[0] + band
        assert off[m + n] <= m < off[m + n] + band


def test_edit_distance_host():
    assert edit_distance(b"ACGT", b"ACGT") == 0
    assert edit_distance(b"ACGT", b"AGT") == 1
    assert edit_distance(b"AAAA", b"TTTT") == 4
    assert edit_distance(b"", b"ACG") == 3
    assert edit_distance(b"KITTEN", b"SITTING") == 3


@pytest.mark.parametrize("n,err", [(200, 0.05), (900, 0.10), (1500, 0.15)])
def test_banded_alignment_matches_exact_distance(n, err):
    rng = np.random.default_rng(n)
    pairs = []
    for _ in range(4):
        t = _random_seq(rng, n)
        q = _mutate(rng, t, sub=err, ins=err / 2, dele=err / 2)
        pairs.append((q, t))

    runs = BatchAligner().align(pairs)
    for (q, t), r in zip(pairs, runs):
        assert r is not None
        cost, q_used, t_used = _cigar_cost_and_spans(r, q, t)
        assert q_used == len(q) and t_used == len(t)
        exact = edit_distance(q, t)
        # banded result must be a valid alignment; with a 10% band and these
        # error rates it should be exact
        assert cost == exact


def test_mixed_length_buckets():
    rng = np.random.default_rng(7)
    pairs = []
    for n in (100, 600, 600, 3000):
        t = _random_seq(rng, n)
        q = _mutate(rng, t)
        pairs.append((q, t))
    runs = BatchAligner().align(pairs)
    for (q, t), r in zip(pairs, runs):
        cost, q_used, t_used = _cigar_cost_and_spans(r, q, t)
        assert q_used == len(q) and t_used == len(t)


def test_oversize_rejected():
    al = BatchAligner(max_length=512)
    res = al.align([(b"A" * 600, b"A" * 600)])
    assert res == [None]


def test_determinism():
    rng = np.random.default_rng(3)
    t = _random_seq(rng, 400)
    q = _mutate(rng, t)
    r1 = BatchAligner().align([(q, t)])
    r2 = BatchAligner().align([(q, t)])
    assert r1 == r2


def test_pathological_indel_rejected_not_wrong():
    """A large balanced indel forces the optimal path far off the ideal
    diagonal; the banded kernel must flag it for exact host realignment
    (reference pattern: cudaaligner status -> CPU, cudaaligner.cpp:63-71)
    instead of returning a silently clipped alignment."""
    rng = np.random.default_rng(11)
    t = _random_seq(rng, 2000)
    # rotation: the optimal path runs ~1000 rows off the ideal diagonal,
    # far outside a 128-wide band; the in-band "alignment" is mismatch soup
    q = t[1000:] + t[:1000]
    al = BatchAligner(band_width=128)
    res = al.align([(q, t)])
    assert res == [None]
    assert al.n_band_rejects == 1


def test_device_aligner_through_polisher(reference_data):
    """tpu_aligner_batches=1 routes PAF overlaps through the device kernel
    with host fallback; windows/layers must match the host-only path."""
    from racon_tpu.core.polisher import create_polisher, PolisherType

    def build(dev):
        p = create_polisher(
            str(reference_data / "sample_reads.fastq.gz"),
            str(reference_data / "sample_overlaps.paf.gz"),
            str(reference_data / "sample_layout.fasta.gz"),
            PolisherType.kC, 500, 10.0, 0.3, num_threads=2,
            tpu_aligner_batches=dev)
        p.initialize()
        return p

    host = build(0)
    dev = build(1)
    assert len(host.windows) == len(dev.windows)
    n_equal = sum(hw.num_layers == dw.num_layers
                  for hw, dw in zip(host.windows, dev.windows))
    # banded device CIGARs may shift a few window boundaries (the reference
    # accepts the same CPU-vs-GPU divergence); structure must agree broadly
    assert n_equal >= int(0.9 * len(host.windows))
