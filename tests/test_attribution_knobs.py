"""C++ attribution-knob coverage (native/src/poa.cpp; PARITY.md).

The three quality-gap attribution knobs — RACON_TPU_HOST_BAND,
RACON_TPU_CONSENSUS_EXT, RACON_TPU_TIEBREAK — were measured once for
PARITY.md and then left untested (ADVICE round-5): a regression in, e.g.,
the branch-completion re-scan would go unnoticed while the knobs stay
documented in README. Each knob latches from getenv in a static
initializer (one read per process), so every configuration runs in its
own subprocess.
"""

import os
import random
import subprocess
import sys

from racon_tpu.native import edit_distance

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ACGT = b"ACGT"

#: child: build one deterministic spanning window (seed 11; length 400 so
#: the default band 256 is genuinely narrower than the DP matrix), run the
#: host POA, print truth / backbone / consensus / coverages
SNIPPET = """\
import os, random
from racon_tpu.native import poa_batch

ACGT = b"ACGT"
rng = random.Random(11)


def mutate(s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


truth = bytes(rng.choice(ACGT) for _ in range(400))
clean = os.environ.get("RACON_KNOB_WINDOW") == "clean"
bb = truth if clean else mutate(truth, 0.08)
win = [(bb, None, 0, len(bb) - 1)]
for _ in range(5):
    lay = truth if clean else mutate(truth, 0.08)
    win.append((lay, None, 0, len(bb) - 1))
cons, cov = poa_batch([win], 3, -5, -4)[0]
print(truth.decode())
print(bb.decode())
print(cons.decode())
print(",".join(str(x) for x in cov.tolist()))
"""


def run_poa(env_extra=None, window="mut"):
    env = dict(os.environ, RACON_KNOB_WINDOW=window, **(env_extra or {}))
    proc = subprocess.run([sys.executable, "-c", SNIPPET], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    truth, bb, cons, cov = proc.stdout.strip().splitlines()[-4:]
    return (truth.encode(), bb.encode(), cons.encode(),
            [int(x) for x in cov.split(",")])


def test_host_band_zero_matches_default():
    """HOST_BAND=0 (exact full DP always) must equal the default banded
    run on a fixture window — the PARITY.md exoneration pinned as a test:
    the clip-retry rule recovers everything banding could lose."""
    t0, b0, cons_default, cov_default = run_poa()
    t1, b1, cons_full, cov_full = run_poa({"RACON_TPU_HOST_BAND": "0"})
    assert (t0, b0) == (t1, b1)  # same deterministic window
    assert cons_full == cons_default
    assert cov_full == cov_default


def test_consensus_ext_branch_yields_valid_spanning_path():
    """CONSENSUS_EXT=branch (spoa-style branch completion) must still
    produce a valid spanning consensus: ACGT-only, window-scale length,
    and at least as close to the truth as the unpolished backbone."""
    truth, bb, cons, cov = run_poa({"RACON_TPU_CONSENSUS_EXT": "branch"})
    assert cons and set(cons) <= set(ACGT)
    assert 0.8 * len(bb) <= len(cons) <= 1.2 * len(bb)
    assert len(cov) == len(cons)
    assert all(1 <= c <= 6 for c in cov)
    assert edit_distance(cons, truth) <= edit_distance(bb, truth)


def test_tiebreak_dhv_identical_on_tie_free_window():
    """On a window whose layers equal the backbone exactly, the all-match
    diagonal path is strictly optimal — no equal-score indel choice exists
    for the tie order to flip — so dhv must reproduce the default
    byte-for-byte (a changed output would mean the knob alters more than
    equal-score tie selection)."""
    _, _, cons_default, cov_default = run_poa(window="clean")
    _, _, cons_dhv, cov_dhv = run_poa({"RACON_TPU_TIEBREAK": "dhv"},
                                      window="clean")
    assert cons_dhv == cons_default
    assert cov_dhv == cov_default


def test_tiebreak_dhv_valid_on_noisy_window():
    """On a noisy window dhv may legitimately pick different equal-score
    indel placements (PARITY.md: tie-class noise); the output must still
    be a valid consensus of the same quality class."""
    truth, bb, cons, cov = run_poa({"RACON_TPU_TIEBREAK": "dhv"})
    assert cons and set(cons) <= set(ACGT)
    assert 0.8 * len(bb) <= len(cons) <= 1.2 * len(bb)
    assert len(cov) == len(cons)
    assert edit_distance(cons, truth) <= edit_distance(bb, truth)


def test_knob_defaults_are_inert():
    """Setting every knob to its documented default value must be a
    no-op vs an env-free run (guards against the getenv comparisons
    drifting from the documented defaults)."""
    base = run_poa()
    pinned = run_poa({"RACON_TPU_HOST_BAND": "256",
                      "RACON_TPU_TIEBREAK": "dvh",
                      "RACON_TPU_CONSENSUS_EXT": "greedy"})
    assert pinned == base
