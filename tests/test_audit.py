"""Online identity-audit sentinel (racon_tpu/obs/audit.py + wiring).

The ISSUE-13 acceptance pins: deterministic content-keyed sampling,
oracle-path equality on clean runs, injected silent-corruption (`sdc`)
detection with online winner-table demotion persisting across
processes, lane quarantine/re-probe, telemetry isolation (a sampled run
leaves production pipeline counters identical to an unsampled one), the
flagless byte-identity pin (audit off => no audit surface anywhere),
and THE end-to-end sentinel pin: a live serve run with audit rate 1.0
and a fault plan corrupting one device chunk detects the mismatch
(labeled counter + typed journal event + dual-stream flight dump),
demotes the persisted winner entry on disk, quarantines then re-probes
the lane, and the job's final FASTA is STILL byte-identical to a clean
solo run."""

from __future__ import annotations

import json
import os
import sys
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from racon_tpu.core.window import WindowType, create_window  # noqa: E402
from racon_tpu.obs.audit import (WindowAuditor,  # noqa: E402
                                 window_sample_fraction)
from racon_tpu.ops.oracle import (OracleExecutor, oracle_active,  # noqa: E402
                                  oracle_scope, rebuild_window,
                                  snapshot_window)
from racon_tpu.ops.poa import BatchPOA  # noqa: E402
from racon_tpu.resilience.faults import FaultPlan  # noqa: E402
from racon_tpu.sched.autotune import (Autotuner,  # noqa: E402
                                      reset_autotuner_cache)


def make_windows(n=6, seed=3, length=60, depth=4):
    """Small consensus-ready windows: backbone + mutated layers."""
    import random

    rng = random.Random(seed)
    acgt = "ACGT"
    windows = []
    for k in range(n):
        bb = "".join(rng.choice(acgt) for _ in range(length))
        w = create_window(0, k, WindowType.kNGS, bb.encode(),
                          b"!" * length)
        for _ in range(depth):
            layer = "".join(c if rng.random() > 0.05
                            else rng.choice(acgt) for c in bb)
            w.add_layer(layer.encode(), None, 0, length - 1)
        windows.append(w)
    return windows


def host_params(**kw):
    """A polisher-parameters stub for host-engine consensus."""
    base = dict(match=3, mismatch=-5, gap=-4, window_length=500,
                trim=True, num_threads=1, tpu_poa_batches=0,
                tpu_banded_alignment=False, tpu_aligner_band_width=0,
                tpu_engine=None, tpu_pipeline_depth=0,
                tpu_device_timeout=0.0)
    base.update(kw)
    return types.SimpleNamespace(**base)


# ------------------------------------------------------------- sampling
def test_content_keyed_sampling_deterministic():
    """The sample decision is a pure function of the window bytes: the
    same content always lands at the same fraction, rates NEST (the
    r=0.2 sampled set is a subset of the r=0.7 set), and distinct
    windows spread across [0, 1)."""
    windows = make_windows(n=32)
    fracs = [window_sample_fraction(w) for w in windows]
    assert fracs == [window_sample_fraction(w) for w in windows]
    assert all(0.0 <= f < 1.0 for f in fracs)
    assert len(set(fracs)) == len(fracs)  # content-distinct -> distinct
    low = {w.rank for w, f in zip(windows, fracs) if f < 0.2}
    high = {w.rank for w, f in zip(windows, fracs) if f < 0.7}
    assert low <= high
    # content sensitivity: one flipped base moves the fraction
    w = windows[0]
    mutated = create_window(0, 0, WindowType.kNGS,
                            b"A" + w.sequences[0][1:], w.qualities[0])
    assert window_sample_fraction(mutated) != fracs[0]


def test_sampling_rate_bounds():
    auditor = WindowAuditor(rate=0.0)
    assert not auditor.armed
    auditor.set_rate(2.0)
    assert auditor.rate == 1.0
    auditor.set_rate(-1.0)
    assert auditor.rate == 0.0


# ------------------------------------------------------------- oracle
def test_oracle_scope_is_thread_local():
    from racon_tpu.ops.dtypes import dtype_mode
    from racon_tpu.ops.encode import pack_bases_enabled
    from racon_tpu.ops.poa_fused import fused_mode
    from racon_tpu.ops.poa_pallas import pallas_mode

    assert not oracle_active()
    with oracle_scope():
        assert oracle_active()
        assert pallas_mode() == "off"
        assert dtype_mode() == "int32"
        assert fused_mode() == "0"
        assert not pack_bases_enabled()
    assert not oracle_active()

    import threading
    seen = {}

    def probe():
        seen["active"] = oracle_active()

    with oracle_scope():
        t = threading.Thread(target=probe)
        t.start()
        t.join()
    assert seen["active"] is False  # other threads stay production


def test_oracle_matches_clean_host_run():
    """Oracle-path equality on a clean run: re-executing every window
    through the oracle reproduces the production host consensus
    bit-for-bit (including the <3-sequence backbone fallback)."""
    windows = make_windows(n=5)
    thin = create_window(0, 99, WindowType.kNGS, b"ACGTACGT", b"!" * 8)
    windows.append(thin)
    p = host_params()
    BatchPOA(p.match, p.mismatch, p.gap, p.window_length,
             num_threads=1).generate_consensus(windows, p.trim)
    ex = OracleExecutor()
    clones = ex.consensus(p, [snapshot_window(w) for w in windows])
    for w, c in zip(windows, clones):
        assert w.consensus == c.consensus
        assert w.polished == c.polished
    ex.close()


def test_rebuild_window_roundtrip():
    w = make_windows(n=1)[0]
    clone = rebuild_window(snapshot_window(w))
    assert clone.sequences == w.sequences
    assert clone.positions == w.positions
    assert clone.consensus == b"" and not clone.polished


# ---------------------------------------------------------- sdc faults
def test_sdc_fault_flips_one_base_silently():
    windows = make_windows(n=3)
    BatchPOA(3, -5, -4, 500, num_threads=1).generate_consensus(
        windows, True)
    before = [w.consensus for w in windows]
    plan = FaultPlan.parse("device:chunk=1:sdc")
    # fire() must NOT treat sdc as a stage hook (no raise, stays armed)
    plan.fire("device", 1)
    assert plan.unfired
    assert plan.corrupt_consensus(windows) == 1
    after = [w.consensus for w in windows]
    assert after[0] == before[0] and after[2] == before[2]
    assert after[1] != before[1]
    assert len(after[1]) == len(before[1])  # a flip, not a truncation
    assert all(w.polished for w in windows)  # silent: nothing degraded
    # one-shot: a second pass finds the fault consumed
    assert plan.corrupt_consensus(windows) == 0


def test_batchpoa_consumes_sdc_plan():
    from racon_tpu.pipeline import DispatchPipeline

    windows = make_windows(n=3)
    plan = FaultPlan.parse("device:chunk=0:sdc")
    pl = DispatchPipeline(depth=0, faults=plan)
    BatchPOA(3, -5, -4, 500, num_threads=1,
             pipeline=pl).generate_consensus(windows, True)
    clean = make_windows(n=3)
    BatchPOA(3, -5, -4, 500, num_threads=1).generate_consensus(
        clean, True)
    assert windows[0].consensus != clean[0].consensus
    assert [w.consensus for w in windows[1:]] == \
        [w.consensus for w in clean[1:]]
    assert pl.stats.snapshot()["faults"] == 1


# ------------------------------------------------------ auditor core
def test_auditor_clean_run_no_mismatch():
    windows = make_windows(n=6)
    p = host_params()
    BatchPOA(p.match, p.mismatch, p.gap, p.window_length,
             num_threads=1).generate_consensus(windows, p.trim)
    auditor = WindowAuditor(rate=1.0)
    n = auditor.audit_windows([(w, p) for w in windows],
                              lane_index=0, iteration=1)
    snap = auditor.snapshot()
    assert n == 0
    assert snap["windows"] == 6 and snap["sampled"] == 6
    assert snap["audited"] == 6 and snap["clean"] == 6
    assert snap["mismatches"] == 0 and not snap["alert_firing"]
    auditor.close()


def test_auditor_detects_and_repairs_corruption(tmp_path):
    """A silently corrupted window is caught, labeled, dumped with both
    byte streams, REPAIRED with the oracle bytes, and flips the alert
    until acked; the known-good probe is captured for the lane
    re-probe."""
    windows = make_windows(n=4)
    p = host_params()
    BatchPOA(p.match, p.mismatch, p.gap, p.window_length,
             num_threads=1).generate_consensus(windows, p.trim)
    truth = windows[1].consensus
    corrupted = bytearray(truth)
    corrupted[0] = ord("A") if corrupted[0] != ord("A") else ord("C")
    windows[1].consensus = bytes(corrupted)
    alerts = []
    auditor = WindowAuditor(rate=1.0, flight_dir=str(tmp_path),
                            on_alert=lambda s, d: alerts.append(s))
    n = auditor.audit_windows([(w, p) for w in windows],
                              lane_index=3, iteration=7)
    assert n == 1
    assert windows[1].consensus == truth  # repaired before delivery
    snap = auditor.snapshot()
    assert snap["mismatches"] == 1 and snap["repaired"] == 1
    assert snap["alert_firing"] and alerts == ["firing"]
    samples = auditor.mismatch_samples()
    assert len(samples) == 1
    labels, count = samples[0]
    assert count == 1 and labels["engine"] == "host"
    assert labels["lane"] == "3"
    dumps = [f for f in os.listdir(tmp_path) if "audit-mismatch" in f]
    assert len(dumps) == 1
    doc = json.load(open(tmp_path / dumps[0]))
    fl = doc["flight"]
    assert fl["oracle"].encode("latin-1") == truth
    assert fl["produced"].encode("latin-1") == bytes(corrupted)
    assert fl["labels"]["lane"] == "3"
    probe = auditor.probe()
    assert probe is not None and probe[2] == truth
    # operator ack clears the alert; the NEXT mismatch re-fires it
    auditor.ack()
    assert not auditor.alert_firing and alerts[-1] == "clear"
    auditor.close()


def test_autotuner_demote(tmp_path):
    """The online veto: matching non-oracle entries rewrite to the
    oracle candidate (xla/split at int32, identical=False,
    demoted=True), the rewrite persists atomically, and already-oracle
    entries are untouched."""
    path = str(tmp_path / "t.json")
    at = Autotuner(path)
    at.record("session", (64, 128), (3, -5, -4, 8),
              {"kernel": "pallas", "dtype": "int16", "ms": {"a": 1},
               "identical": True})
    at.record("session", (128, 256), (3, -5, -4, 8),
              {"kernel": "xla", "dtype": "int32", "ms": {},
               "identical": True})
    at.record("fused_loop", (256, 64, 8), (3, -5, -4, 8),
              {"kernel": "fused", "dtype": "int32", "ms": {},
               "identical": True})
    at.record("aligner", (512, 64), (),
              {"kernel": "pallas", "dtype": "int16", "ms": {},
               "identical": True})
    at.save()
    demoted = at.demote(engine="session")
    assert len(demoted) == 1 and "64x128" in demoted[0]
    # a second sweep finds nothing left to demote
    assert at.demote(engine="session") == []
    demoted = at.demote(engine="fused_loop")
    assert len(demoted) == 1
    # persistence across processes: a FRESH handle sees the veto
    re = Autotuner(path)
    ent = re.table[Autotuner.key("session", (64, 128), (3, -5, -4, 8))]
    assert ent == {"kernel": "xla", "dtype": "int32", "ms": {"a": 1},
                   "identical": False, "demoted": True}
    fl = re.table[Autotuner.key("fused_loop", (256, 64, 8),
                                (3, -5, -4, 8))]
    assert fl["kernel"] == "split" and fl["demoted"]
    # the aligner entry (different engine) survived untouched
    al = re.table[Autotuner.key("aligner", (512, 64))]
    assert al["kernel"] == "pallas" and "demoted" not in al


def test_demote_scoped_to_backend(tmp_path):
    path = str(tmp_path / "t.json")
    at = Autotuner(path)
    at.record("session", (64, 128), (), {"kernel": "pallas",
                                         "dtype": "int16", "ms": {},
                                         "identical": True})
    other = Autotuner.key("session", (64, 128), (), backend="tpu")
    at.table[other] = {"kernel": "pallas", "dtype": "int16", "ms": {},
                       "identical": True}
    demoted = at.demote(engine="session")  # this backend (cpu) only
    assert len(demoted) == 1 and not demoted[0].startswith("tpu|")
    assert "demoted" not in at.table[other]


# ----------------------------------------------- lane quarantine logic
class _FakeAuditor:
    """Probe-only auditor stand-in for the batcher's re-probe path."""

    def __init__(self, probe):
        self._probe = probe
        self.events = []
        self.armed = True

    def probe(self):
        return self._probe

    def lane_event(self, lane, state, **fields):
        self.events.append((lane, state))


@pytest.fixture
def two_lane_batcher():
    import jax

    from racon_tpu.serve.batcher import WindowBatcher

    b = WindowBatcher(worker_lanes=2, devices=jax.devices("cpu")[:2])
    yield b
    b.close(timeout=5)


def test_lane_quarantine_reprobe_rejoins(two_lane_batcher):
    """A quarantined lane whose re-probe reproduces the known-good
    bytes rejoins at health 1.0 (engines rebuilt along the way)."""
    b = two_lane_batcher
    with b._cond:
        lanes = b._lanes_locked()
    p = host_params()
    w = make_windows(n=1)[0]
    snap = snapshot_window(w)
    ex = OracleExecutor()
    truth = ex.consensus(p, [snap])[0]
    ex.close()
    b.auditor = _FakeAuditor((p, snap, truth.consensus, truth.polished))
    b.quarantine_lane(1)
    assert lanes[1].quarantined and lanes[1].health == 0.0
    assert lanes[1].flush_engines
    assert b._reprobe_lane(lanes[1]) is True
    assert not lanes[1].quarantined and lanes[1].health == 1.0
    assert not lanes[1].flush_engines  # cache was rebuilt
    snap_b = b.snapshot()
    assert snap_b["lane_quarantines"] == 1
    assert snap_b["lane_rejoins"] == 1
    assert (1, "quarantined") in b.auditor.events
    assert (1, "rejoined") in b.auditor.events


def test_lane_quarantine_stays_when_probe_fails(two_lane_batcher):
    """A failing re-probe keeps the lane quarantined while a healthy
    sibling serves; the LAST lane instead rejoins DEGRADED (health 0.5)
    rather than wedging the service."""
    b = two_lane_batcher
    with b._cond:
        lanes = b._lanes_locked()
    p = host_params()
    snap = snapshot_window(make_windows(n=1)[0])
    b.auditor = _FakeAuditor((p, snap, b"NOT-THE-REAL-BYTES", True))
    b.quarantine_lane(1)
    assert b._reprobe_lane(lanes[1]) is False
    assert lanes[1].quarantined and lanes[1].health == 0.0
    # now lane 0 is quarantined too: its failed probe degrades instead
    b.quarantine_lane(0)
    assert b._reprobe_lane(lanes[0]) is True
    assert not lanes[0].quarantined and lanes[0].health == 0.5
    assert (0, "degraded") in b.auditor.events


def test_solo_jobs_avoid_quarantined_lanes(two_lane_batcher):
    b = two_lane_batcher
    with b._cond:
        lanes = b._lanes_locked()
        healthy = [l for l in lanes if not l.quarantined]
    assert len(healthy) == 2
    b.quarantine_lane(0)
    with b._cond:
        healthy = [l for l in lanes if not l.quarantined]
    assert [l.index for l in healthy] == [1]


# -------------------------------------------------------- serve pins
@pytest.fixture(scope="module")
def serve_dataset(tmp_path_factory):
    from racon_tpu.serve import make_synth_dataset

    tmp = tmp_path_factory.mktemp("audit_data")
    return make_synth_dataset(str(tmp))


def start_server(tmp_path, **kw):
    from racon_tpu.serve import PolishClient, PolishServer

    sock = str(tmp_path / f"s{len(os.listdir(tmp_path))}.sock")
    server = PolishServer(socket_path=sock, workers=1, warmup=False,
                          quality_threshold=-1.0, **kw)
    server.start()
    return server, PolishClient(socket_path=sock)


def solo_fasta(paths, **opts):
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*paths, PolisherType.kC,
                        opts.get("window_length", 500), -1.0, 0.3,
                        num_threads=2,
                        tpu_poa_batches=opts.get("tpu_poa_batches", 0),
                        tpu_pipeline_depth=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


def test_flagless_serve_has_no_audit_surface(serve_dataset, tmp_path):
    """THE flagless pin: audit off (the default) => no auditor object,
    no audit/lane-health scrape families, zero audit accounting — and
    the served FASTA byte-identical to a solo run."""
    server, client = start_server(tmp_path)
    try:
        resp = client.submit(*serve_dataset)
        assert server.auditor is None
        scrape = client.scrape()
        assert "racon_tpu_audit" not in scrape
        assert "racon_tpu_lane_health" not in scrape
        snap = server.batcher.snapshot()
        assert snap["audit_s"] == 0.0
        assert snap["lane_quarantines"] == 0
        assert client.stats()["audit"] is None
        assert resp.fasta == solo_fasta(serve_dataset)
    finally:
        server.drain(timeout=20)


def test_audited_run_keeps_production_telemetry_clean(serve_dataset,
                                                     tmp_path):
    """Satellite pin: shadow executions bill to the audit.* namespace
    only — a rate-1.0 run's PRODUCTION pipeline/scheduler counters and
    autotuner consult meters are identical to a rate-0 run's, while the
    audit namespace shows the shadow work."""
    from racon_tpu.sched.autotune import get_autotuner

    server_on, client_on = start_server(tmp_path, audit_rate=1.0)
    server_off, client_off = start_server(tmp_path)
    try:
        consults_before = dict(get_autotuner().consults)
        on = client_on.submit(*serve_dataset)
        off = client_off.submit(*serve_dataset)
        assert on.fasta == off.fasta
        pipe_on = server_on.batcher._merged_pipeline()
        pipe_off = server_off.batcher._merged_pipeline()
        for key in ("launches", "chunks", "errors", "faults",
                    "quarantined"):
            assert pipe_on[key] == pipe_off[key], key
        # the shadow work exists — and is accounted SEPARATELY
        a = server_on.auditor.snapshot()
        assert a["audited"] > 0
        assert a["shadow"]["launches"] > 0
        assert dict(get_autotuner().consults) == consults_before
        # per-job production metrics: same structural counters
        assert (on.metrics["pipeline"]["launches"]
                == off.metrics["pipeline"]["launches"])
    finally:
        server_on.drain(timeout=20)
        server_off.drain(timeout=20)


@pytest.mark.usefixtures("serve_dataset")
def test_e2e_sentinel_pin(serve_dataset, tmp_path, monkeypatch):
    """THE acceptance pin (ISSUE 13): RACON_TPU_AUDIT_RATE=1.0 + a
    fault plan corrupting one device chunk on a live serve run =>
    mismatch detected (labeled counter + typed journal event +
    dual-stream flight dump), persisted winner entry demoted ON DISK
    (visible to a fresh process-level handle), lane quarantined then
    re-probed back to health, and the job's final FASTA byte-identical
    to a clean solo run."""
    at_path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("RACON_TPU_AUTOTUNE_CACHE", at_path)
    reset_autotuner_cache()
    at = Autotuner(at_path)
    at.record("session", (64, 128), (3, -5, -4, 8),
              {"kernel": "pallas", "dtype": "int16", "ms": {},
               "identical": True})
    at.save()
    reset_autotuner_cache()
    journal_path = str(tmp_path / "journal.jsonl")
    flight_dir = str(tmp_path / "flight")
    server, client = start_server(tmp_path, audit_rate=1.0,
                                  journal=journal_path,
                                  flight_dir=flight_dir)
    opts = {"tpu_poa_batches": 1, "window_length": 100}
    try:
        clean = client.submit(*serve_dataset, options=opts)
        assert server.auditor.snapshot()["mismatches"] == 0
        bad = client.submit(*serve_dataset, options=opts,
                            fault_plan="device:chunk=1:sdc")
        # repaired: identical to the clean serve run AND to solo
        assert bad.fasta == clean.fasta
        assert bad.fasta == solo_fasta(serve_dataset, **opts)
        a = server.auditor.snapshot()
        assert a["mismatches"] == 1 and a["repaired"] == 1
        assert a["demotions"] >= 1
        # labeled counter + alert + lane health in the live scrape
        scrape = client.scrape()
        assert 'racon_tpu_audit_mismatches_total{' in scrape
        assert 'engine="session"' in scrape
        assert "racon_tpu_audit_alert 1" in scrape
        # winner table demoted ON DISK, visible to a fresh handle
        reset_autotuner_cache()
        ent = Autotuner(at_path).table[
            Autotuner.key("session", (64, 128), (3, -5, -4, 8))]
        assert ent["demoted"] and ent["kernel"] == "xla"
        assert ent["dtype"] == "int32" and not ent["identical"]
        # lane: quarantined, then re-probed back to health 1.0
        deadline = time.time() + 20
        while time.time() < deadline:
            lanes = server.batcher.snapshot()["lanes"]
            if lanes and all(l["health"] == 1.0 for l in lanes):
                break
            time.sleep(0.1)
        snap = server.batcher.snapshot()
        assert snap["lane_quarantines"] == 1
        assert snap["lane_rejoins"] == 1
        assert all(l["health"] == 1.0 for l in snap["lanes"])
        # dual-stream dump on disk
        dumps = [f for f in os.listdir(flight_dir)
                 if "audit-mismatch" in f]
        assert len(dumps) == 1
        fl = json.load(open(os.path.join(flight_dir, dumps[0])))["flight"]
        assert fl["produced"] != fl["oracle"]
        # ack clears the alert
        client.audit_ack()
        assert "racon_tpu_audit_alert 0" in client.scrape()
    finally:
        server.drain(timeout=30)
    # journal: typed audit-mismatch in the owning job's timeline, the
    # lane transitions as annotations, and the consistency check (plus
    # obsreport --check) stays green
    from racon_tpu.obs.journal import check_consistency, read_journal

    entries = read_journal(journal_path)
    mism = [e for e in entries if e["event"] == "audit-mismatch"]
    assert len(mism) == 1
    assert mism[0]["job"] == bad.job_id
    assert mism[0]["engine"] == "session"
    assert mism[0]["flight"]
    lane_events = [e["state"] for e in entries
                   if e["event"] == "audit-lane"]
    assert "quarantined" in lane_events and "rejoined" in lane_events
    alert_states = [e["state"] for e in entries
                    if e["event"] == "alert"
                    and e.get("kind") == "audit-mismatch"]
    assert alert_states[0] == "firing" and alert_states[-1] == "clear"
    assert check_consistency(entries) == []
    import obsreport

    rc = obsreport.main(["--journal", journal_path, "--check",
                         "--flight-dir", flight_dir])
    assert rc == 0


def test_obsreport_renders_audit_mismatch_in_timeline(tmp_path,
                                                      capsys):
    """Satellite pin: obsreport renders `audit-mismatch` in the owning
    job's timeline and --check stays rc 0 (annotation events)."""
    import obsreport

    t = time.time()
    entries = [
        {"t": t, "event": "received", "job": "j1"},
        {"t": t + 0.01, "event": "admitted", "job": "j1"},
        {"t": t + 0.02, "event": "started", "job": "j1"},
        {"t": t + 0.5, "event": "audit-mismatch", "job": "j1",
         "engine": "session", "kernel": "pallas", "dtype": "int16",
         "bucket": "8x500", "lane": "0", "iteration": 4,
         "window": "0:3", "flight": "/tmp/f.json"},
        {"t": t + 0.6, "event": "audit-lane", "lane": 0,
         "state": "quarantined"},
        {"t": t + 0.9, "event": "finished", "job": "j1",
         "sequences": 0},
    ]
    path = tmp_path / "j.jsonl"
    with open(path, "w") as fh:
        for e in entries:
            fh.write(json.dumps(e) + "\n")
    rc = obsreport.main(["--journal", str(path), "--check",
                         "--flight-dir", str(tmp_path / "none")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "audit-mismatch" in out and "engine=session" in out
    assert "consistency: OK" in out


def test_fleet_federates_audit_families():
    """Satellite pin: the aggregator federates the labeled audit and
    lane-health families — per-(name, labels) sums across replicas —
    and the federated body re-renders parseably."""
    from racon_tpu.obs import prom
    from racon_tpu.obs.fleet import FleetSnapshot, ReplicaSample

    def body(mism, health):
        counters = {
            "audit.sampled": 10,
            "audit.mismatches": prom.Labeled(
                [({"engine": "session", "kernel": "pallas",
                   "dtype": "int16", "bucket": "8x500", "lane": "0"},
                  mism)])}
        gauges = {
            "audit.alert": 1 if mism else 0,
            "lane_health": prom.Labeled([({"lane": "0"}, health)])}
        return prom.render(counters, gauges)

    snap = FleetSnapshot()
    for k, (mism, health) in enumerate([(1, 0.0), (2, 1.0)]):
        rs = ReplicaSample(f"r{k}")
        rs.parsed = prom.parse(body(mism, health))
        rs.ok = True
        snap.replicas.append(rs)
    from racon_tpu.obs.fleet import FleetAggregator

    FleetAggregator._merge(snap)
    series = snap.counter_series["racon_tpu_audit_mismatches_total"]
    assert len(series) == 1
    (_labels, total), = series.values()
    assert total == 3  # summed per identical label set
    assert snap.counters["racon_tpu_audit_sampled_total"] == 20
    assert snap.gauges["racon_tpu_audit_alert"] >= 1  # summed gauge
    health = snap.gauge_series["racon_tpu_lane_health"]
    (_labels, h), = health.values()
    assert h == 1.0  # summed; per-replica detail stays in replicas
    # the merged labeled families re-render into a parseable body
    merged = prom.render(
        {n: prom.Labeled([(l, v) for l, v in s.values()])
         for n, s in snap.counter_series.items()},
        {n: prom.Labeled([(l, v) for l, v in s.values()])
         for n, s in snap.gauge_series.items()})
    reparsed = prom.parse(merged)
    assert ("racon_tpu_audit_mismatches_total"
            in reparsed.counter_series)


def test_servetop_renders_audit_cell():
    """Satellite pin: servetop's per-replica audit cell reads the new
    scrape families (sampled/s, mismatches, demotions, lane health)."""
    import servetop

    from racon_tpu.obs import prom

    text = prom.render(
        {"serve.batch.iterations": 5,
         "audit.sampled": 40,
         "audit.demotions": 2,
         "audit.mismatches": prom.Labeled(
             [({"engine": "session", "kernel": "pallas",
                "dtype": "int16", "bucket": "8x500", "lane": "1"},
               3)])},
        {"serve.queue_depth": 0, "serve.inflight": 0,
         "serve.worker_lanes": 2,
         "audit.alert": 1,
         "lane_health": prom.Labeled([({"lane": "0"}, 1.0),
                                      ({"lane": "1"}, 0.0)])})
    parsed = prom.parse(text)
    cell = servetop.audit_cell(parsed, {}, 0.0)
    assert cell == {"sampled": 40, "sampled_rate": 0.0,
                    "mismatches": 3, "demotions": 2,
                    "lane_health_min": 0.0, "alert": True}
    # rate from the previous poll
    cell2 = servetop.audit_cell(
        parsed, {"audit": {"sampled": 20}}, 2.0)
    assert cell2["sampled_rate"] == 10.0
    # a replica without audit families renders no cell
    plain = prom.parse(prom.render({"serve.batch.iterations": 5}, {}))
    assert servetop.audit_cell(plain, {}, 1.0) is None

    scrape = parsed

    class _RS:
        endpoint = "r0"
        ok = True
        draining = False
        error = None
        parsed = scrape
        scrape_s = 0.001

    row = servetop.replica_row(_RS(), {}, 0.0)
    assert row["audit"]["mismatches"] == 3

    class _Snap:
        replicas = [_RS()]
        poll_s = 0.01
        counters = scrape.counters
        gauges = scrape.gauges
        counter_series = scrape.counter_series
        gauge_series = scrape.gauge_series

    screen = servetop.render_screen(_Snap(), {}, [row], {}, 0.0)
    assert "audit" in screen and "[ALERT]" in screen
    line = servetop.fleet_line(_Snap(), {}, {}, 0.0)
    assert "audit 3 mism" in line and "[AUDIT-ALERT]" in line


def test_demotion_flushes_every_lane(two_lane_batcher):
    """Review pin: an online demotion flags EVERY lane's cached
    engines stale (not just the quarantined lane's), and the stale
    cache is rebuilt at the lane's next use — a vetoed winner must
    stop dispatching fleet-wide, immediately."""
    b = two_lane_batcher
    with b._cond:
        lanes = b._lanes_locked()
    p = host_params()
    for lane in lanes:
        with lane.lock:
            b._lane_engine(lane, ("k",), p)
        assert lane.engines
    b.flush_lane_engines()
    assert all(l.flush_engines for l in lanes)
    for lane in lanes:
        with lane.lock:
            b._fresh_engines_locked(lane)
        assert not lane.engines and not lane.flush_engines


def test_mismatch_exemplar_rides_real_shadow_observation(tmp_path):
    """Review pin: no phantom zero-duration samples — the shadow
    histogram gets exactly ONE observation per pass, and a mismatching
    pass's own bucket carries the exemplar naming the dual-stream
    artifact."""
    from racon_tpu.obs.hist import HistogramSet

    windows = make_windows(n=3)
    p = host_params()
    BatchPOA(p.match, p.mismatch, p.gap, p.window_length,
             num_threads=1).generate_consensus(windows, p.trim)
    windows[0].consensus = b"X" + windows[0].consensus[1:]
    hists = HistogramSet()
    auditor = WindowAuditor(rate=1.0, hists=hists,
                            flight_dir=str(tmp_path))
    auditor.audit_windows([(w, p) for w in windows],
                          lane_index=0, iteration=1)
    h = hists.get("audit.shadow")
    assert h.count == 1  # one pass, one sample
    assert h.min > 0.0   # no phantom 0.0 observation
    exemplars = h.bucket_exemplars()
    assert len(exemplars) == 1
    (_le, ex), = exemplars.items()
    assert "audit-mismatch" in ex["flight"]
    assert ex["value"] == h.max  # the pass's real duration bucket
    auditor.close()


def test_probe_does_not_pin_the_polisher():
    """Review pin: the known-good probe snapshots only the slim
    parameter fields, never the mismatched job's Polisher (which would
    pin its whole dataset in memory)."""
    windows = make_windows(n=2)
    p = host_params()
    BatchPOA(p.match, p.mismatch, p.gap, p.window_length,
             num_threads=1).generate_consensus(windows, p.trim)
    windows[0].consensus = b"X" + windows[0].consensus[1:]
    auditor = WindowAuditor(rate=1.0)
    auditor.audit_windows([(w, p) for w in windows],
                          lane_index=0, iteration=1)
    probe_p = auditor.probe()[0]
    assert probe_p is not p
    assert probe_p.match == p.match
    assert probe_p.trim == p.trim
    assert not hasattr(probe_p, "windows")  # slim, not a Polisher
    auditor.close()
