"""Elastic replica autoscaling units (serve/autoscale.py) — the
decision function driven clocklessly with injected spawn/stop, plus the
router's armed-only exposure surfaces:

  - config: every env twin strict-parses (a typo fails the start),
    unknown kwargs and inverted fleet bounds raise;
  - scale-up only on SUSTAINED pressure (a one-poll burst never
    scales), bounded by the ceiling and the cooldown;
  - scale-down only after sustained full idle, only replicas the loop
    itself spawned, newest first, UNROUTED before stopped (the
    zero-job-loss ordering), never below the floor;
  - spawn failures count, never throw, and never join the routing set;
  - journal `autoscale-up` / `autoscale-down` records; snapshot keys;
  - healthz carries an `autoscale` block and /metrics the
    `racon_tpu_router_autoscale_*` families ONLY once armed — the
    off-knob exposition stays byte-identical.
"""

from __future__ import annotations

import threading
import types
import urllib.request

import pytest

from racon_tpu.errors import RaconError
from racon_tpu.serve import PolishClient, PolishRouter, PolishServer
from racon_tpu.serve.autoscale import Autoscaler, AutoscaleConfig


# ---------------------------------------------------------------- fakes
class _Replica:
    def __init__(self):
        self.routable = True


class _Fleet:
    def __init__(self):
        self.snap = None

    def last(self):
        return self.snap


class _Journal:
    def __init__(self):
        self.events: list[tuple] = []

    def record(self, event, **kw):
        self.events.append((event, kw))


class _Router:
    """The autoscaler-facing sliver of PolishRouter."""

    def __init__(self, n: int = 1):
        self.fleet = _Fleet()
        self._state_lock = threading.Lock()
        self.replicas = [_Replica() for _ in range(n)]
        self._inflight_jobs = 0
        self._requeued_outstanding = 0
        self.journal = None
        self.autoscaler = None
        self.added: list[str] = []
        self.removed: list[str] = []

    def add_replica(self, spec):
        self.added.append(spec)
        self.replicas.append(_Replica())

    def remove_replica(self, spec):
        self.removed.append(spec)
        self.replicas.pop()


def _snap(queue_depths):
    reps = [types.SimpleNamespace(ok=True,
                                  health={"queue_depth": q, "inflight": 0})
            for q in queue_depths]
    return types.SimpleNamespace(replicas=reps, burn=None)


def _scaler(router, tmp_path, monkeypatch, ready=True, spawn=None,
            stop=None, **kw):
    monkeypatch.setattr(Autoscaler, "_wait_ready",
                        lambda self, spec: ready)
    base = dict(min_replicas=1, max_replicas=3, up_pressure=2.0,
                up_sustain_s=1.0, down_idle_s=2.0, cooldown_s=0.0,
                interval_s=999.0, socket_dir=str(tmp_path))
    base.update(kw)
    cfg = AutoscaleConfig(**base)
    spawned: list[str] = []
    stopped: list[str] = []
    sc = Autoscaler(
        router, cfg,
        spawn=spawn or (lambda spec: spawned.append(spec) or spec),
        stop=stop or (lambda h: stopped.append(h)))
    return sc, spawned, stopped


# --------------------------------------------------------------- config
def test_autoscale_config_env_strict_parse(monkeypatch):
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_MIN", "two")
    with pytest.raises(RaconError, match="AUTOSCALE_MIN"):
        AutoscaleConfig()
    monkeypatch.delenv("RACON_TPU_ROUTER_AUTOSCALE_MIN")
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_UP_PRESSURE", "hot")
    with pytest.raises(RaconError, match="UP_PRESSURE"):
        AutoscaleConfig()
    monkeypatch.delenv("RACON_TPU_ROUTER_AUTOSCALE_UP_PRESSURE")
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_MAX", "8")
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_DOWN_IDLE_S", "5.5")
    cfg = AutoscaleConfig()
    assert cfg.max_replicas == 8 and cfg.down_idle_s == 5.5
    assert cfg.min_replicas == 1  # defaults survive alongside
    with pytest.raises(RaconError, match="unknown autoscale option"):
        AutoscaleConfig(bogus=1)
    with pytest.raises(RaconError, match="bad fleet bounds"):
        AutoscaleConfig(min_replicas=5, max_replicas=2)


# ------------------------------------------------------------- scale up
def test_scale_up_requires_sustained_pressure(tmp_path, monkeypatch):
    router = _Router(n=1)
    router.journal = _Journal()
    router.fleet.snap = _snap([5])  # pressure 5/1
    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch)
    assert sc.step(now=0.0) is None  # pressure noted, not sustained
    assert sc.step(now=0.5) is None
    assert sc.step(now=1.1) == "up"
    assert spawned and spawned[0].endswith("autoscale_1.sock")
    assert router.added == spawned
    assert sc.counters["scale_ups"] == 1
    assert [e for e, _ in router.journal.events] == ["autoscale-up"]


def test_pressure_burst_that_subsides_never_scales(tmp_path,
                                                   monkeypatch):
    router = _Router(n=1)
    router.fleet.snap = _snap([5])
    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch)
    assert sc.step(now=0.0) is None
    router.fleet.snap = _snap([0])  # burst over: sustain clock resets
    assert sc.step(now=0.9) is None
    router.fleet.snap = _snap([5])
    assert sc.step(now=1.5) is None  # restarted sustain, not elapsed
    assert spawned == [] and sc.counters["scale_ups"] == 0


def test_scale_up_respects_ceiling_and_cooldown(tmp_path, monkeypatch):
    router = _Router(n=3)  # already at max_replicas
    router.fleet.snap = _snap([9, 9, 9])
    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch)
    assert sc.step(now=0.0) is None
    assert sc.step(now=5.0) is None
    assert spawned == []

    router = _Router(n=1)
    router.fleet.snap = _snap([9])
    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch,
                             cooldown_s=5.0)
    sc.step(now=0.0)
    assert sc.step(now=1.1) == "up"
    assert sc.step(now=1.2) is None  # sustain restarts
    assert sc.step(now=2.5) is None  # sustained again, but cooling down
    assert sc.step(now=7.0) == "up"  # cooldown elapsed
    assert len(spawned) == 2


def test_spawn_failure_counts_and_never_routes(tmp_path, monkeypatch):
    router = _Router(n=1)
    router.fleet.snap = _snap([9])

    def boom(_spec):
        raise OSError("fork failed")

    sc, _, _ = _scaler(router, tmp_path, monkeypatch, spawn=boom)
    sc.step(now=0.0)
    assert sc.step(now=1.5) is None
    assert sc.counters["spawn_failures"] == 1
    assert router.added == [] and sc.spawned == []

    # spawned but never answered healthz: stopped, counted, not routed
    router = _Router(n=1)
    router.fleet.snap = _snap([9])
    sc, spawned, stopped = _scaler(router, tmp_path, monkeypatch,
                                   ready=False)
    sc.step(now=0.0)
    assert sc.step(now=1.5) is None
    assert sc.counters["spawn_failures"] == 1
    assert spawned and stopped == spawned and router.added == []


# ----------------------------------------------------------- scale down
def test_scale_down_unroutes_before_stopping(tmp_path, monkeypatch):
    router = _Router(n=1)
    router.journal = _Journal()
    router.fleet.snap = _snap([5])
    order: list[str] = []

    def stop(handle):  # the zero-job-loss ordering: unroute FIRST
        assert handle in router.removed
        order.append(handle)

    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch, stop=stop)
    sc.step(now=0.0)
    assert sc.step(now=1.1) == "up"
    router.fleet.snap = _snap([0, 0])  # fleet fully idle
    assert sc.step(now=2.0) is None  # idle noted, not sustained
    assert sc.step(now=4.1) == "down"
    assert order == spawned and router.removed == spawned
    assert sc.counters["scale_downs"] == 1 and sc.spawned == []
    assert [e for e, _ in router.journal.events] \
        == ["autoscale-up", "autoscale-down"]


def test_never_drains_operator_replicas(tmp_path, monkeypatch):
    router = _Router(n=2)  # both operator-provisioned
    router.fleet.snap = _snap([0, 0])
    sc, _, stopped = _scaler(router, tmp_path, monkeypatch)
    assert sc.step(now=0.0) is None
    assert sc.step(now=100.0) is None  # idle forever: owns nothing
    assert stopped == [] and router.removed == []


def test_inflight_jobs_block_scale_down(tmp_path, monkeypatch):
    router = _Router(n=1)
    router.fleet.snap = _snap([5])
    sc, _, stopped = _scaler(router, tmp_path, monkeypatch)
    sc.step(now=0.0)
    assert sc.step(now=1.1) == "up"
    router.fleet.snap = _snap([0, 0])
    router._inflight_jobs = 1  # router still owes a client a merge
    assert sc.step(now=2.0) is None
    assert sc.step(now=10.0) is None
    router._inflight_jobs = 0
    sc.step(now=11.0)
    assert sc.step(now=13.1) == "down"
    assert len(stopped) == 1


def test_held_shards_count_as_pressure(tmp_path, monkeypatch):
    """A shard holding in the dispatch loop for an idle replica IS
    backlog: router._dispatch_waiting drives the pressure signal, so
    the hold summons the scale-up it waits for."""
    router = _Router(n=1)
    router.fleet.snap = _snap([0])
    sc, spawned, _ = _scaler(router, tmp_path, monkeypatch)
    assert sc.step(now=0.0) is None  # truly idle: no pressure
    router._dispatch_waiting = 3  # three shards holding for capacity
    sc.step(now=1.0)
    assert sc._last_pressure == 3.0
    assert sc.step(now=2.1) == "up"
    assert len(spawned) == 1
    # holding shards also block scale-down (they are not idle)
    router._dispatch_waiting = 1
    router.fleet.snap = _snap([0, 0])
    assert sc.step(now=20.0) is None


def test_dispatch_hold_insists_on_idle_replica(tmp_path):
    """The autoscale hold machinery in PolishRouter: with
    max_inflight=1 only an idle replica qualifies, and headroom is
    True only while an armed autoscaler is below its ceiling."""
    router = PolishRouter(replicas=str(tmp_path / "rep.sock"),
                          socket_path=str(tmp_path / "r.sock"))
    # no autoscaler armed: never hold
    assert router._scaleup_headroom() is False
    # capped pick refuses the busy replica, uncapped takes it
    r = router._pick_replica(set(), max_inflight=1)
    assert r is not None and r.inflight == 1
    assert router._pick_replica(set(), max_inflight=1) is None
    assert router._pick_replica(set()) is not None
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=2,
                          socket_dir=str(tmp_path))
    assert cfg.hold_s == 5.0  # default on; 0 disables
    Autoscaler(router, cfg, spawn=lambda spec: spec,
               stop=lambda h: None)
    assert router._scaleup_headroom() is True  # 1 replica < max 2
    router.add_replica(str(tmp_path / "rep2.sock"))
    assert router._scaleup_headroom() is False  # at the ceiling


def test_hold_s_config_strict_parse(monkeypatch, tmp_path):
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_HOLD_S", "forever")
    with pytest.raises(RaconError, match="AUTOSCALE_HOLD_S"):
        AutoscaleConfig()
    monkeypatch.setenv("RACON_TPU_ROUTER_AUTOSCALE_HOLD_S", "2.5")
    assert AutoscaleConfig().hold_s == 2.5
    monkeypatch.delenv("RACON_TPU_ROUTER_AUTOSCALE_HOLD_S")
    with pytest.raises(RaconError, match="hold_s"):
        AutoscaleConfig(hold_s=-1.0)


def test_snapshot_shape(tmp_path, monkeypatch):
    router = _Router(n=1)
    router.fleet.snap = _snap([4])
    sc, _, _ = _scaler(router, tmp_path, monkeypatch)
    sc.step(now=0.0)
    snap = sc.snapshot()
    assert snap == {"min": 1, "max": 3, "spawned": 0, "pressure": 4.0,
                    "scale_ups": 0, "scale_downs": 0,
                    "spawn_failures": 0}


# ------------------------------------------------- armed-only exposure
def test_router_surfaces_autoscale_only_when_armed(tmp_path,
                                                   monkeypatch):
    srv = PolishServer(socket_path=str(tmp_path / "rep.sock"),
                       workers=1).start()
    router = PolishRouter(replicas=srv.config.socket_path,
                          socket_path=str(tmp_path / "r.sock"),
                          metrics_port=0,
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        base = f"http://127.0.0.1:{router.config.metrics_port}"
        hz = cli.request({"type": "healthz"})
        assert "autoscale" not in hz  # off-knob surface unchanged
        body = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        assert "racon_tpu_router_autoscale" not in body
        # arming (constructor attaches; no loop needed) flips both on
        monkeypatch.setattr(Autoscaler, "_wait_ready",
                            lambda self, spec: True)
        Autoscaler(router,
                   AutoscaleConfig(socket_dir=str(tmp_path)),
                   spawn=lambda spec: spec, stop=lambda h: None)
        hz = cli.request({"type": "healthz"})
        assert hz["autoscale"]["min"] == 1
        assert hz["autoscale"]["spawned"] == 0
        body = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        assert "racon_tpu_router_autoscale_spawned 0" in body
        assert "racon_tpu_router_autoscale_scale_ups" in body
        assert "racon_tpu_router_autoscale_pressure" in body
    finally:
        router.drain()
        srv.drain(timeout=10)
