"""Persisted per-bucket kernel autotuner (sched/autotune.py) + the
kernel-plane acceptance pins.

The autotuner's contract: profile once on the live backend, persist the
winner table next to the XLA compile cache, and have every later process
dispatch the measured winner under RACON_TPU_PALLAS=auto WITHOUT running
a single candidate again. And whatever the table says, the polished
FASTA must not move: the kernel plane is a pure perf decision, pinned
byte-identical across every (pallas, dtype, depth) posture here.
"""

import json
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from racon_tpu.sched import autotune
from racon_tpu.sched.autotune import (Autotuner, default_table_path,
                                      get_autotuner,
                                      reset_autotuner_cache)


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path, monkeypatch):
    """Every test gets its own on-disk table; the process cache is
    dropped around each so no test sees another's winners."""
    monkeypatch.setenv("RACON_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    reset_autotuner_cache()
    yield
    reset_autotuner_cache()


# ------------------------------------------------------------- the table

def test_table_roundtrip_persists_across_instances(tmp_path):
    path = str(tmp_path / "t.json")
    at = Autotuner(path)
    entry = {"kernel": "pallas", "dtype": "int16",
             "ms": {"xla:int32": 1.5, "pallas:int16": 0.5},
             "identical": True}
    at.record("session", (192, 128), (3, -5, -4, 8), entry)
    assert at.save() == path
    # a different instance on the same path (a new process, as far as
    # the table is concerned) sees the same winner
    again = Autotuner(path)
    assert again.winner("session", (192, 128), (3, -5, -4, 8)) == entry
    assert again.winner("session", (192, 128), (5, -4, -8, 8)) is None
    assert again.winner("aligner", (192, 128)) is None


def test_key_is_backend_scoped():
    k_cpu = Autotuner.key("session", (96, 96), (3, -5, -4), backend="cpu")
    k_tpu = Autotuner.key("session", (96, 96), (3, -5, -4), backend="tpu")
    assert k_cpu != k_tpu  # a table profiled on chip never leaks to CPU
    assert Autotuner.key("aligner", 512, backend="cpu") \
        == Autotuner.key("aligner", (512,), backend="cpu")


def test_corrupt_or_stale_table_treated_as_absent(tmp_path):
    path = tmp_path / "t.json"
    path.write_text("{not json")
    assert Autotuner(str(path)).table == {}
    path.write_text(json.dumps({"version": -1, "winners": {"k": {}}}))
    assert Autotuner(str(path)).table == {}
    path.write_text(json.dumps({"version": autotune.VERSION,
                                "winners": {"k": {"kernel": "xla"}}}))
    assert Autotuner(str(path)).table == {"k": {"kernel": "xla"}}


def test_default_table_path_resolution(monkeypatch, tmp_path):
    monkeypatch.setenv("RACON_TPU_AUTOTUNE_CACHE", "/x/y.json")
    assert default_table_path() == "/x/y.json"
    monkeypatch.delenv("RACON_TPU_AUTOTUNE_CACHE")
    monkeypatch.setenv("RACON_TPU_COMPILE_CACHE", str(tmp_path))
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    # next to the XLA compile cache, so both warm together
    assert default_table_path() == str(tmp_path / autotune.BASENAME)


# ---------------------------------------------------------- profiling

def test_profile_buckets_then_warm_second_process_profiles_nothing(
        monkeypatch):
    """The acceptance pin: a cold profile measures every candidate and
    verifies identity; a second process (fresh Autotuner on the saved
    table) returns the persisted entry WITHOUT timing anything."""
    at = get_autotuner()
    entry, fresh = at.profile_session_bucket(96, 96, 4, 3, -5, -4,
                                             rows=4, reps=1)
    assert fresh
    assert entry["kernel"] in ("xla", "pallas")
    assert entry["dtype"] in ("int32", "int16")
    # every candidate ran: both kernels x both dtypes (the proof holds
    # at this bucket), and all reproduced the int32 XLA oracle
    assert set(entry["ms"]) == {"xla:int32", "xla:int16",
                                "pallas:int32", "pallas:int16"}
    assert entry["identical"] is True

    a_entry, fresh = at.profile_aligner_bucket(128, 32, rows=4, reps=1)
    assert fresh
    assert set(a_entry["ms"]) == {"xla:int32", "xla:int16",
                                  "pallas:int32", "pallas:int16"}
    assert a_entry["identical"] is True
    at.save()

    # same process, same instance: warm
    _, fresh = at.profile_session_bucket(96, 96, 4, 3, -5, -4)
    assert not fresh

    # "second process": drop the cache, reload from disk, and make any
    # attempt to actually time a candidate blow up
    reset_autotuner_cache()
    monkeypatch.setattr(Autotuner, "_time", staticmethod(
        lambda *a, **k: pytest.fail("warm profile ran a candidate")))
    warm = get_autotuner()
    e2, fresh = warm.profile_session_bucket(96, 96, 4, 3, -5, -4)
    assert not fresh and e2 == entry
    e3, fresh = warm.profile_aligner_bucket(128, 32)
    assert not fresh and e3 == a_entry


def test_profile_fused_bucket_warm_second_process_profiles_nothing(
        monkeypatch):
    """The fused-loop plane joins the autotuner contract: a cold
    profile times split-vs-fused on the live backend under the identity
    veto; a second process returns the persisted entry without running
    a candidate."""
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    at = get_autotuner()
    entry, fresh = at.profile_fused_bucket(192, 96, 8, 4, 3, -5, -4,
                                           rows=2, reps=1)
    assert fresh
    assert entry["kernel"] in ("split", "fused")
    dt = entry["dtype"]
    assert set(entry["ms"]) == {f"split:{dt}", f"fused:{dt}"}
    assert entry["identical"] is True
    at.save()

    _, fresh = at.profile_fused_bucket(192, 96, 8, 4, 3, -5, -4)
    assert not fresh

    reset_autotuner_cache()
    monkeypatch.setattr(Autotuner, "_time", staticmethod(
        lambda *a, **k: pytest.fail("warm profile ran a candidate")))
    warm = get_autotuner()
    e2, fresh = warm.profile_fused_bucket(192, 96, 8, 4, 3, -5, -4)
    assert not fresh and e2 == entry


def test_pick_vetoes_non_identical_candidates():
    ms = {"xla:int32": 2.0, "pallas:int16": 0.1}
    outs = {"xla:int32": np.arange(4), "pallas:int16": np.arange(4) + 1}
    entry = Autotuner._pick(ms, outs, "xla:int32")
    # the fast candidate disagreed with the oracle: disqualified AND
    # flagged — never dispatched, however fast
    assert entry["kernel"] == "xla" and entry["dtype"] == "int32"
    assert entry["identical"] is False
    outs["pallas:int16"] = np.arange(4)
    entry = Autotuner._pick(ms, outs, "xla:int32")
    assert entry["kernel"] == "pallas" and entry["dtype"] == "int16"
    assert entry["identical"] is True


# ------------------------------------------- dispatchers under `auto`

def test_session_engine_plan_follows_winner_table(monkeypatch):
    from racon_tpu.ops.poa_graph import DeviceGraphPOA

    monkeypatch.setenv("RACON_TPU_PALLAS", "auto")

    def engine():
        return DeviceGraphPOA(3, -5, -4, max_nodes=96, max_len=96,
                              buckets=((96, 96),), batch_rows=4)

    # cold: no table entry -> XLA exactly as off (dtype still shrinks by
    # the proof alone)
    eng = engine()
    assert eng.pallas_posture == "auto"
    assert eng._plan(96, 96) == (False, "int16")

    # a measured winner flips the SAME construction to the pallas
    # kernel, at the measured dtype (int32 here: the table beats the
    # proof's default-narrow)
    at = get_autotuner()
    at.record("session", (96, 96), (3, -5, -4, eng.max_pred),
              {"kernel": "pallas", "dtype": "int32", "ms": {},
               "identical": True})
    at.save()
    reset_autotuner_cache()
    assert engine()._plan(96, 96) == (True, "int32")


def test_fused_engine_dtype_follows_winner_table(monkeypatch):
    from racon_tpu.ops.poa_fused import FusedPOA

    monkeypatch.setenv("RACON_TPU_PALLAS", "auto")
    kw = dict(max_nodes=256, max_len=128, batch_rows=4,
              depth_buckets=(4,))
    assert FusedPOA(3, -5, -4, **kw).score_dtype == "int16"
    at = get_autotuner()
    at.record("fused", (256, 128), (3, -5, -4, 8),
              {"kernel": "xla", "dtype": "int32", "ms": {},
               "identical": True})
    at.save()
    reset_autotuner_cache()
    assert FusedPOA(3, -5, -4, **kw).score_dtype == "int32"


def test_tpu_smoke_profile_step_writes_keys_engines_consult(monkeypatch):
    """The cold->warm weld: the buckets/params tpu_smoke's
    PALLAS_PROFILE step profiles must be EXACTLY the keys the
    default-constructed production dispatchers look up under `auto` —
    a table written under any other (scoring, max_pred, band) tuple is
    dead weight and `auto` stays permanently cold."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import tpu_smoke

    from racon_tpu.ops.align import BatchAligner
    from racon_tpu.ops.poa_graph import BUCKETS, MAX_PRED

    calls = {"session": [], "aligner": [], "fused_loop": []}

    class Rec:
        table = {}

        def profile_session_bucket(self, nb, lb, mp, m, x, g, **kw):
            calls["session"].append((nb, lb, mp, m, x, g))
            return {"kernel": "xla", "dtype": "int32", "ms": {},
                    "identical": True}, True

        def profile_aligner_bucket(self, edge, band, **kw):
            calls["aligner"].append((edge, band))
            return {"kernel": "xla", "dtype": "int32", "ms": {},
                    "identical": True}, True

        def profile_fused_bucket(self, nb, lb, d, mp, m, x, g, **kw):
            calls["fused_loop"].append((nb, lb, d, mp, m, x, g))
            return {"kernel": "split", "dtype": "int32", "ms": {},
                    "identical": True}, True

        def save(self):
            return "<recorded>"

    monkeypatch.setattr(autotune, "Autotuner", Rec)
    exec(compile(tpu_smoke.PALLAS_PROFILE, "PALLAS_PROFILE", "exec"), {})

    # session: every static bucket at the polisher/CLI default scoring
    # and the engine's MAX_PRED — the exact _plan() lookup tuple
    assert set(calls["session"]) >= {
        (nb, lb, MAX_PRED, 3, -5, -4) for nb, lb in BUCKETS}
    # aligner: whatever band the auto rule derives for pairs anywhere in
    # a profiled bucket must have been profiled for that bucket
    ba = BatchAligner()
    profiled = set(calls["aligner"])
    edges = sorted({e for e, _ in profiled})
    for edge, prev in zip(edges, [0] + edges):
        for length in (prev + 1, (prev + edge) // 2 + 1, edge):
            pairs = [(b"A" * length, b"A" * length)]
            assert (edge, ba._band_for(pairs, [0])) in profiled, \
                f"auto band for len {length} not profiled at edge {edge}"
    # fused-loop: whatever consult key FusedPOA._fused_plan derives for
    # ANY chunk depth (N, L, leading chain bucket at the default
    # scoring/MAX_PRED) must have been profiled — the weld that lets
    # RACON_TPU_FUSED=auto go warm at production dispatch keys
    from racon_tpu.ops.poa_fused import FUSED_LOOP_MAX_DEPTH, FusedPOA

    eng = FusedPOA(3, -5, -4)
    fused_profiled = set(calls["fused_loop"])
    for depth in range(1, FUSED_LOOP_MAX_DEPTH + 1):
        plan = eng._chain_plan(depth)
        assert (eng.N, eng.L, plan[0], eng.P, 3, -5, -4) \
            in fused_profiled, \
            f"fused consult key for chunk depth {depth} not profiled"


# --------------------------------------- the byte-identity acceptance pin

class _ForcedTable:
    """A winner table that answers 'pallas, int16' for every bucket —
    the most aggressive posture `auto` could ever take. The envelope
    proofs and VMEM gates still apply downstream, so this drives every
    legally-narrowable bucket onto the narrow resident kernel."""

    def winner(self, engine, bucket, params=()):
        return {"kernel": "pallas", "dtype": "int16", "ms": {},
                "identical": True}


@pytest.mark.parametrize("engine", ["session", "fused"])
def test_polisher_fasta_identical_across_kernel_plane_modes(
        engine, tmp_path, monkeypatch):
    """THE acceptance pin: polished FASTA byte-identical across
    RACON_TPU_PALLAS={0,1,auto} x dtype {int32, shrunk} x pipeline
    depth {0,2}, aligner + POA device engines armed, interpret-mode
    kernels on the CPU backend. The kernel plane may move every perf
    number; it may not move one output byte."""
    from test_pipeline import _synth_dataset

    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    paths = [str(x) for x in _synth_dataset(tmp_path, random.Random(23))]

    def run(pallas, dtype, depth):
        monkeypatch.setenv("RACON_TPU_PALLAS", pallas)
        monkeypatch.setenv("RACON_TPU_DTYPE", dtype)
        if pallas == "auto":
            # a table that forces the aggressive plane everywhere; the
            # cold-table `auto` == off case is covered separately below
            monkeypatch.setattr(autotune, "get_autotuner",
                                lambda: _ForcedTable())
        else:
            monkeypatch.setattr(autotune, "get_autotuner", get_autotuner)
        p = create_polisher(*paths, PolisherType.kC, 500, -1.0, 0.3,
                            num_threads=2, tpu_aligner_batches=1,
                            tpu_poa_batches=1, tpu_engine=engine,
                            tpu_pipeline_depth=depth)
        p.initialize()
        return [(s.name, s.data) for s in p.polish()]

    ref = run("0", "int32", 0)
    assert ref and all(d for _, d in ref)
    # the matrix, minus the reference itself: every pallas posture at
    # both depths, wide and shrunk
    for pallas in ("0", "1", "auto"):
        for dtype, depth in (("int32", 2), ("auto", 0), ("auto", 2)):
            if pallas == "0" and (dtype, depth) == ("int32", 0):
                continue
            assert run(pallas, dtype, depth) == ref, \
                f"FASTA diverged at pallas={pallas} dtype={dtype} " \
                f"depth={depth}"
    # cold-table auto: no entries -> dispatches exactly like off
    monkeypatch.setenv("RACON_TPU_PALLAS", "auto")
    monkeypatch.setenv("RACON_TPU_DTYPE", "auto")
    monkeypatch.setattr(autotune, "get_autotuner", get_autotuner)
    reset_autotuner_cache()
    assert run("auto", "auto", 0) == ref
