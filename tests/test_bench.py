"""bench.py is the round's driver-facing artifact: its LAST stdout line
must be one parseable JSON metric under every failure mode (the round-3
lesson — a timed-out device phase must not lose the host number)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    not os.path.isdir("/root/reference/test/data"),
    reason="sample data missing")


def run_bench(env_extra, timeout=400):
    env = dict(os.environ, **env_extra)
    # CPU-only child: the axon shim must not be able to hang the phases
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    return proc


def test_bench_host_only_emits_json_line():
    proc = run_bench({"RACON_TPU_POA_BATCHES": "0"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "sample_polish_consensus_throughput_host"
    assert rec["unit"] == "windows/sec"
    assert rec["value"] > 0
    # both fields are independently rounded (value to 2 dp, vs_baseline to
    # 3 dp) — compare with an absolute tolerance covering both roundings
    assert rec["vs_baseline"] == pytest.approx(rec["value"] / 50.0,
                                               abs=1.1e-3)
    # the artifact must carry the per-stage pipeline counters so CI can
    # see a silently-dead pipeline: the compute stage reading ~0 seconds
    # while the phase reported a throughput would be the tell
    stages = rec["stages"]
    for key in ("pack_s", "device_s", "unpack_s", "fallback_s",
                "launches", "chunks", "errors"):
        assert key in stages
    assert stages["device_s"] > 0
    assert stages["launches"] >= 1
    assert stages["errors"] == 0
    # the unified observability snapshot rides the same line: one
    # namespaced schema consolidating the stage/occupancy/degradation
    # telemetry (racon_tpu/obs), consistent with the legacy fields
    metrics = rec["metrics"]
    for ns in ("pipeline", "resilience", "sched"):
        assert ns in metrics
    assert metrics["pipeline"]["chunks"] == stages["chunks"]
    assert all(not v for v in metrics["resilience"].values())


def test_bench_emits_json_even_when_phases_cannot_run():
    # budget too small for any phase: the host phase still gets its floor
    # cap and the line is still emitted
    proc = run_bench({"RACON_TPU_POA_BATCHES": "0",
                      "RACON_TPU_BENCH_BUDGET": "1"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["unit"] == "windows/sec"
