"""CLI tests (reference src/main.cpp:47-169 option surface)."""

import io
import os
import sys

import pytest

from racon_tpu.cli import parse_args, main

DATA = "/root/reference/test/data/"


def test_defaults_and_positionals():
    o = parse_args(["reads.fq", "ovl.paf", "tgt.fa"])
    assert o["window_length"] == 500
    assert o["quality_threshold"] == 10.0
    assert o["error_threshold"] == 0.3
    assert o["match"] == 3 and o["mismatch"] == -5 and o["gap"] == -4
    assert o["trim"] and o["drop_unpolished_sequences"]
    assert not o["fragment_correction"]
    assert o["paths"] == ["reads.fq", "ovl.paf", "tgt.fa"]


def test_full_option_mix():
    o = parse_args(["-w", "1000", "-q", "-1", "--no-trimming", "-m", "8",
                    "-x", "-6", "-g", "-8", "-t", "4", "-c", "2",
                    "--tpualigner-batches", "3", "--tpualigner-band-width=64",
                    "reads.fq", "ovl.paf", "tgt.fa"])
    assert o["window_length"] == 1000
    assert o["quality_threshold"] == -1.0
    assert not o["trim"]
    assert o["match"] == 8 and o["mismatch"] == -6 and o["gap"] == -8
    assert o["num_threads"] == 4
    assert o["tpu_poa_batches"] == 2
    assert o["tpu_aligner_batches"] == 3
    assert o["tpu_aligner_band_width"] == 64


def test_tpu_engine_flag():
    o = parse_args(["--tpu-engine", "fused", "r.fq", "o.paf", "t.fa"])
    assert o["tpu_engine"] == "fused"
    o = parse_args(["--tpu-engine=session", "r.fq", "o.paf", "t.fa"])
    assert o["tpu_engine"] == "session"
    with pytest.raises(SystemExit):
        parse_args(["--tpu-engine", "warp", "r.fq", "o.paf", "t.fa"])


def test_optional_c_argument():
    # -c with no value defaults to 1 (reference main.cpp:113-125)
    o = parse_args(["-ufc", "a.fq", "b.paf", "c.fa"])
    assert not o["drop_unpolished_sequences"]
    assert o["fragment_correction"]
    assert o["tpu_poa_batches"] == 1
    assert o["paths"] == ["a.fq", "b.paf", "c.fa"]


def test_tpu_pipeline_depth_flag():
    o = parse_args(["r.fq", "o.paf", "t.fa"])
    assert o["tpu_pipeline_depth"] == 2  # default: double buffering
    o = parse_args(["--tpu-pipeline-depth", "0", "r.fq", "o.paf", "t.fa"])
    assert o["tpu_pipeline_depth"] == 0  # synchronous bisection path
    o = parse_args(["--tpu-pipeline-depth=3", "r.fq", "o.paf", "t.fa"])
    assert o["tpu_pipeline_depth"] == 3


def test_missing_inputs_exit_code():
    assert main([]) == 1


def test_version_and_help(capsys):
    assert main(["--version"]) == 0
    assert capsys.readouterr().out.startswith("v")
    assert main(["--help"]) == 0
    assert "usage: racon_tpu" in capsys.readouterr().out


@pytest.mark.skipif(not os.path.isdir(DATA), reason="sample data missing")
def test_cli_end_to_end_sam(capsys, monkeypatch):
    # full pipeline through the CLI entry point, FASTA on stdout
    buf = io.BytesIO()
    buf.buffer = buf  # cli writes to sys.stdout.buffer

    class _Out:
        buffer = buf

        @staticmethod
        def write(s):
            pass

        @staticmethod
        def flush():
            pass

    monkeypatch.setattr(sys, "stdout", _Out)
    rc = main(["-t", "2", DATA + "sample_reads.fastq.gz",
               DATA + "sample_overlaps.sam.gz",
               DATA + "sample_layout.fasta.gz"])
    assert rc == 0
    out = buf.getvalue()
    assert out.startswith(b">utg000001l")
    assert b"LN:i:" in out and b"RC:i:" in out and b"XC:f:" in out
    # one record: header + sequence
    assert out.count(b">") == 1
    seq = out.split(b"\n", 2)[1]
    assert 45000 < len(seq) < 50000


# ---------------------------------------------- one-shot -f parity oracle
def _cli_bytes(args, monkeypatch):
    buf = io.BytesIO()

    class _Out:
        buffer = buf

        @staticmethod
        def write(s):
            pass

        @staticmethod
        def flush():
            pass

    monkeypatch.setattr(sys, "stdout", _Out)
    rc = main(args)
    assert rc == 0
    return buf.getvalue()


@pytest.fixture(scope="module")
def frag_dataset(tmp_path_factory):
    from racon_tpu.serve.server import make_fragment_dataset
    return make_fragment_dataset(str(tmp_path_factory.mktemp("cli_frag")))


def test_cli_fragment_correction_parity(frag_dataset, monkeypatch):
    """One-shot `-f` parity (ISSUE 20 satellite): the CLI's fragment
    correction run on the reads-correcting-reads fixture is invariant
    over pipeline depth 0/2 and the session/fused engines, and equals
    the library-level kF oracle — the pinned identity target for the
    serve fragment traffic class (tests/test_serve_fragment.py)."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*frag_dataset, PolisherType.kF, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    golden = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                      for s in p.polish(True))
    # corrected reads come back "r"-tagged with per-read accounting
    assert golden.startswith(b">f0r LN:i:")
    assert golden.count(b">") == 17

    for depth in ("0", "2"):
        for engine in ("session", "fused"):
            got = _cli_bytes(["-f", "-t", "2",
                              "--tpu-engine", engine,
                              "--tpu-pipeline-depth", depth,
                              *frag_dataset], monkeypatch)
            assert got == golden, (engine, depth)
