"""Determinism contract.

The reference's whole-genome CI test requires byte-identical output across
runs (ci/gpu/cuda_test.sh:30-44 diffs a 5.2 MB golden FASTA exactly). The
same property must hold here: same inputs => byte-identical polished FASTA,
regardless of thread count or repeated runs, for both engines.
"""

import os

import pytest

from racon_tpu.core.polisher import create_polisher, PolisherType

DATA = "/root/reference/test/data/"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference sample data not available")


def polish_bytes(threads: int, device: int = 0) -> bytes:
    p = create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.sam.gz",
                        DATA + "sample_layout.fasta.gz",
                        PolisherType.kC, 500, 10.0, 0.3,
                        match=5, mismatch=-4, gap=-8, num_threads=threads,
                        tpu_poa_batches=device)
    p.initialize()
    out = b""
    for seq in p.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return out


def test_host_output_bit_stable_across_runs_and_threads():
    a = polish_bytes(threads=1)
    b = polish_bytes(threads=4)
    c = polish_bytes(threads=4)
    assert a == b == c
    assert a.startswith(b">utg000001l")


def test_device_output_bit_stable():
    a = polish_bytes(threads=2, device=1)
    b = polish_bytes(threads=2, device=1)
    assert a == b
