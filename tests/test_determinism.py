"""Determinism + cross-engine identity contract.

The reference's whole-genome CI test requires byte-identical output across
runs (ci/gpu/cuda_test.sh:30-44 diffs a 5.2 MB golden FASTA exactly). The
same property must hold here: same inputs => byte-identical polished FASTA,
regardless of thread count or repeated runs. On top of that this design
makes a claim the reference cannot (its CPU and GPU engines diverge,
racon_test.cpp:107 vs :312): the device engine's output is byte-identical
to the host engine's on real data, because every layer is aligned against
the evolving graph with host-identical DP and tie-breaking
(ops/poa_graph.py).
"""

import os

import pytest

from racon_tpu.core.polisher import create_polisher, PolisherType

DATA = "/root/reference/test/data/"


@pytest.fixture(autouse=True)
def _one_device_mesh(monkeypatch):
    # real-data identity fixtures exercise the production envelope, not
    # sharding (dedicated sharded tests cover that at small shapes) — on
    # the 8-virtual-device CPU test mesh every shard re-runs the
    # sequential DP, so pin this heavyweight module to one device
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")


pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference sample data not available")


def polish_bytes(threads: int, device: int = 0) -> bytes:
    p = create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.sam.gz",
                        DATA + "sample_layout.fasta.gz",
                        PolisherType.kC, 500, 10.0, 0.3,
                        match=5, mismatch=-4, gap=-8, num_threads=threads,
                        tpu_poa_batches=device)
    p.initialize()
    out = b""
    for seq in p.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return out


def test_host_output_bit_stable_across_runs_and_threads():
    a = polish_bytes(threads=1)
    b = polish_bytes(threads=4)
    c = polish_bytes(threads=4)
    assert a == b == c
    assert a.startswith(b">utg000001l")


def test_device_output_matches_host_bytes(monkeypatch):
    """Device engine == host engine byte-for-byte on the full sample (SAM
    path): the strongest form of the engine-identity claim, and transitive
    determinism (the host run is bit-stable by the test above). STRICT so
    a device failure cannot silently host-polish into a vacuous pass."""
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    host = polish_bytes(threads=2)
    device = polish_bytes(threads=2, device=1)
    assert device == host


def _kf_subset_paths(tmp_path, n_reads: int):
    """Materialize an n-read subset of the sample's all-vs-all workload
    (reads FASTA + filtered PAF) for fragment-correction fixtures."""
    import gzip

    from racon_tpu.io.parsers import create_sequence_parser

    reads: list = []
    create_sequence_parser(DATA + "sample_reads.fastq.gz",
                           "kFsubset").parse(reads, -1)
    keep = {r.name.split(" ")[0] for r in reads[:n_reads]}
    reads_path = tmp_path / "reads.fasta"
    with open(reads_path, "wb") as fh:
        for r in reads[:n_reads]:
            fh.write(b">" + r.name.encode() + b"\n" + r.data + b"\n")
    paf_path = tmp_path / "ava.paf"
    with gzip.open(DATA + "sample_ava_overlaps.paf.gz", "rt") as src, \
            open(paf_path, "w") as dst:
        for line in src:
            f = line.split("\t")
            if f[0] in keep and f[5] in keep:
                dst.write(line)
    return reads_path, paf_path


def _kf_polish_bytes(reads_path, paf_path, device: int) -> bytes:
    p = create_polisher(str(reads_path), str(paf_path), str(reads_path),
                        PolisherType.kF, 500, 10.0, 0.3,
                        match=1, mismatch=-1, gap=-1, num_threads=2,
                        tpu_poa_batches=device)
    p.initialize()
    out = b""
    for seq in p.polish(False):
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return out


def test_device_matches_host_fragment_correction_small(monkeypatch,
                                                       tmp_path):
    """Default-suite kF identity guard (round-4 verdict: the strongest
    contracts must not all hide behind RACON_TPU_FULL_GOLDENS): device
    == host byte-for-byte on a 16-read fragment-correction workload —
    NGS-style short windows, small device buckets, subgraph jobs, unit
    scores. STRICT so a device failure cannot silently host-polish into
    a vacuous pass. The 48-read variant below stays gated."""
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    reads_path, paf_path = _kf_subset_paths(tmp_path, 16)
    assert _kf_polish_bytes(reads_path, paf_path, 1) == \
        _kf_polish_bytes(reads_path, paf_path, 0)


@pytest.mark.skipif(not os.environ.get("RACON_TPU_FULL_GOLDENS"),
                    reason="several-minute fixture; RACON_TPU_FULL_GOLDENS=1")
def test_device_output_matches_host_bytes_fragment_correction(monkeypatch,
                                                              tmp_path):
    """Same identity claim on the fragment-correction workload (kF, NGS-
    style short windows — exercises the small device buckets and subgraph
    jobs the contig sample rarely hits). STRICT, like the contig variant.

    The workload is a 48-read subset of the sample's all-vs-all data:
    full kF polishes ~3300 read-windows, which the 1-core CPU test
    backend cannot do at device speed inside a sane fixture budget — the
    subset keeps every code path (NGS buckets, subgraphs, unit scores)
    at ~1/7 the windows."""
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    reads_path, paf_path = _kf_subset_paths(tmp_path, 48)
    assert _kf_polish_bytes(reads_path, paf_path, 1) == \
        _kf_polish_bytes(reads_path, paf_path, 0)
