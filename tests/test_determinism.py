"""Determinism + cross-engine identity contract.

The reference's whole-genome CI test requires byte-identical output across
runs (ci/gpu/cuda_test.sh:30-44 diffs a 5.2 MB golden FASTA exactly). The
same property must hold here: same inputs => byte-identical polished FASTA,
regardless of thread count or repeated runs. On top of that this design
makes a claim the reference cannot (its CPU and GPU engines diverge,
racon_test.cpp:107 vs :312): the device engine's output is byte-identical
to the host engine's on real data, because every layer is aligned against
the evolving graph with host-identical DP and tie-breaking
(ops/poa_graph.py).
"""

import os

import pytest

from racon_tpu.core.polisher import create_polisher, PolisherType

DATA = "/root/reference/test/data/"


@pytest.fixture(autouse=True)
def _one_device_mesh(monkeypatch):
    # real-data identity fixtures exercise the production envelope, not
    # sharding (dedicated sharded tests cover that at small shapes) — on
    # the 8-virtual-device CPU test mesh every shard re-runs the
    # sequential DP, so pin this heavyweight module to one device
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")


pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference sample data not available")


def polish_bytes(threads: int, device: int = 0) -> bytes:
    p = create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.sam.gz",
                        DATA + "sample_layout.fasta.gz",
                        PolisherType.kC, 500, 10.0, 0.3,
                        match=5, mismatch=-4, gap=-8, num_threads=threads,
                        tpu_poa_batches=device)
    p.initialize()
    out = b""
    for seq in p.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return out


def test_host_output_bit_stable_across_runs_and_threads():
    a = polish_bytes(threads=1)
    b = polish_bytes(threads=4)
    c = polish_bytes(threads=4)
    assert a == b == c
    assert a.startswith(b">utg000001l")


def test_device_output_matches_host_bytes():
    """Device engine == host engine byte-for-byte on the full sample (SAM
    path): the strongest form of the engine-identity claim, and transitive
    determinism (the host run is bit-stable by the test above)."""
    host = polish_bytes(threads=2)
    device = polish_bytes(threads=2, device=1)
    assert device == host


@pytest.mark.skipif(not os.environ.get("RACON_TPU_FULL_GOLDENS"),
                    reason="several-minute fixture; RACON_TPU_FULL_GOLDENS=1")
def test_device_output_matches_host_bytes_fragment_correction():
    """Same identity claim on the fragment-correction workload (kF, NGS-
    style short windows — exercises the small device buckets and subgraph
    jobs the contig sample rarely hits)."""
    from racon_tpu.core.polisher import PolisherType

    def run(device):
        p = create_polisher(DATA + "sample_reads.fastq.gz",
                            DATA + "sample_ava_overlaps.paf.gz",
                            DATA + "sample_reads.fastq.gz",
                            PolisherType.kF, 500, 10.0, 0.3,
                            match=1, mismatch=-1, gap=-1, num_threads=2,
                            tpu_poa_batches=device)
        p.initialize()
        out = b""
        for seq in p.polish(False):
            out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
        return out

    assert run(1) == run(0)
