"""Device POA engine tests (ops/poa_graph + native session + parallel/mesh).

Run on the CPU backend with 8 virtual devices (conftest.py), exercising the
same sharded code paths the TPU uses — the testing scheme SURVEY.md §4
prescribes in place of the reference's CPU-vs-GPU duality.

The central contract here is the one the engine's docstrings claim and the
reference never had: device-engine consensus is BYTE-IDENTICAL to the host
engine (the reference pins diverging GPU numbers separately,
test/racon_test.cpp:292-496; this design aligns every layer against the
evolving graph with host-identical DP and tie-breaking, so it must match
exactly). Coverage includes subgraph alignment, the banded clipped->full-DP
retry, and the unfit-window host fallback, with tiny forced envelopes so
XLA compiles stay fast.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from racon_tpu.core.window import Window, WindowType
from racon_tpu.native import PoaSession, edit_distance, poa_batch
from racon_tpu.ops.poa import BatchPOA
from racon_tpu.ops.poa_graph import DeviceGraphPOA, graph_aligner
from racon_tpu.parallel.mesh import BatchRunner

ACGT = b"ACGT"


def mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def optimal_score(q, t, match, mismatch, gap):
    m, n = len(q), len(t)
    H = np.zeros((m + 1, n + 1), dtype=np.int32)
    H[0, :] = np.arange(n + 1) * gap
    H[:, 0] = np.arange(m + 1) * gap
    for i in range(1, m + 1):
        sub = np.where(np.frombuffer(t, np.uint8) == q[i - 1], match, mismatch)
        for j in range(1, n + 1):
            H[i, j] = max(H[i - 1, j - 1] + sub[j - 1], H[i - 1, j] + gap,
                          H[i, j - 1] + gap)
    return int(H[m, n])


def linear_graph_inputs(ts, qs, n_nodes, seq_len, max_pred=4):
    """Densify linear-chain graphs (sequence-as-graph) the way the session
    does, so the kernel can be tested directly against plain NW."""
    B = len(ts)
    codes = np.full((B, n_nodes), 5, dtype=np.int8)
    preds = np.full((B, n_nodes, max_pred), -1, dtype=np.int16)
    centers = np.zeros((B, n_nodes), dtype=np.int16)
    sinks = np.zeros((B, n_nodes), dtype=np.uint8)
    seqs = np.full((B, seq_len), 5, dtype=np.int8)
    lens = np.zeros(B, dtype=np.int32)
    band = np.zeros(B, dtype=np.int32)
    code_of = np.full(256, 4, dtype=np.int8)
    for i, b in enumerate(b"ACGT"):
        code_of[b] = i
    for k, (t, q) in enumerate(zip(ts, qs)):
        codes[k, :len(t)] = code_of[np.frombuffer(t, np.uint8)]
        preds[k, 0, 0] = 0
        for r in range(1, len(t)):
            preds[k, r, 0] = r
        centers[k, :len(t)] = np.arange(1, len(t) + 1)
        sinks[k, len(t) - 1] = 1
        seqs[k, :len(q)] = code_of[np.frombuffer(q, np.uint8)]
        lens[k] = len(q)
    return codes, preds, centers, sinks, seqs, lens, band


def kernel_path_score(ranks, q, t, n_nodes, match, mismatch, gap):
    """Score of the kernel's alignment of q against the linear graph of t:
    per-base match/mismatch (rank >= 0) or insertion gap, plus a gap for
    every chain node the path skipped."""
    score, matched = 0, 0
    for i, r in enumerate(ranks[:len(q)]):
        if r >= 0:
            score += match if q[i] == t[r] else mismatch
            matched += 1
        else:
            score += gap
    return score + gap * (len(t) - matched)


def test_graph_aligner_optimal_on_linear_graphs():
    """On a linear graph the graph-NW kernel must reproduce plain NW's
    optimal score (full DP, no band)."""
    rng = random.Random(2)
    fn = graph_aligner(64, 64, 4, 3, -5, -4)
    ts = [bytes(rng.choice(ACGT) for _ in range(rng.randrange(20, 60)))
          for _ in range(16)]
    qs = [mutate(rng, t, 0.25) or b"A" for t in ts]
    args = linear_graph_inputs(ts, qs, 64, 64)
    ranks = np.asarray(fn(*args))
    for k, (t, q) in enumerate(zip(ts, qs)):
        got = kernel_path_score(ranks[k], q, t, 64, 3, -5, -4)
        assert got == optimal_score(q, t, 3, -5, -4), k


def test_ring_and_full_carry_programs_identical():
    """The ring-carry variant (last RING rows resident) must be
    bit-identical to the full-carry program whenever predecessor
    distances fit the ring — including banded jobs."""
    from racon_tpu.ops.poa_graph import RING

    rng = random.Random(17)
    N, L = 192, 128
    ts = [bytes(rng.choice(ACGT) for _ in range(rng.randrange(100, 180)))
          for _ in range(8)]
    qs = [(mutate(rng, t, 0.15) or b"A")[:L] for t in ts]
    args = list(linear_graph_inputs(ts, qs, N, L))
    full = graph_aligner(N, L, 4, 5, -4, -8, ring=0)
    ringp = graph_aligner(N, L, 4, 5, -4, -8, ring=RING)
    np.testing.assert_array_equal(np.asarray(ringp(*args)),
                                  np.asarray(full(*args)))
    args[6] = np.full(len(ts), 32, dtype=np.int32)  # banded
    np.testing.assert_array_equal(np.asarray(ringp(*args)),
                                  np.asarray(full(*args)))


def test_ring_carry_boundary_distance():
    """A back-edge of exactly RING ranks is the last ring-safe distance:
    the ring program must still match the full program there, and the
    dispatcher's distance measure must flag RING+1 for full-carry."""
    from racon_tpu.ops.poa_graph import RING, max_pred_distance

    rng = random.Random(23)
    N, L = RING + 32, 96
    t = bytes(rng.choice(ACGT) for _ in range(N - 8))
    q = (mutate(rng, t, 0.1) or b"A")[:L]
    args = list(linear_graph_inputs([t], [q], N, L))
    # add a second pred with back-reach exactly RING: DP row k reads row
    # k - RING (a deletion-like long edge)
    k = RING + 4
    args[1][0, k - 1, 1] = k - RING
    assert max_pred_distance(args[1]) == RING
    full = graph_aligner(N, L, 4, 5, -4, -8, ring=0)
    ringp = graph_aligner(N, L, 4, 5, -4, -8, ring=RING)
    np.testing.assert_array_equal(np.asarray(ringp(*args)),
                                  np.asarray(full(*args)))
    # one rank further is out of the ring: the dispatcher must see it
    args[1][0, k - 1, 1] = k - RING - 1
    assert max_pred_distance(args[1]) == RING + 1
    eng = DeviceGraphPOA(5, -4, -8, max_nodes=N, max_len=L, max_pred=4,
                         buckets=((N, L),), batch_rows=2)
    fn_ring = eng._scan_kernel(N, L, ring_ok=True)
    fn_full = eng._scan_kernel(N, L, ring_ok=False)
    assert fn_full is full and fn_ring is not full


def _make_windows(rng, n_windows, length=60, depth=6, rate=0.08,
                  spanning=True):
    windows = []
    truths = []
    for _ in range(n_windows):
        truth = bytes(rng.choice(ACGT) for _ in range(length))
        bb = mutate(rng, truth, rate)
        w = Window(0, 0, WindowType.kTGS, bb, b"!" * len(bb))
        for k in range(depth):
            if spanning:
                lay, b, e = mutate(rng, truth, rate), 0, len(bb) - 1
            else:
                # interior slice: exercises the bpos-subgraph path
                b = rng.randrange(0, len(bb) // 3)
                e = rng.randrange(2 * len(bb) // 3, len(bb) - 1)
                lay = mutate(rng, truth[b:e + 1], rate)
            w.add_layer(lay or b"A", None, b, e)
        windows.append(w)
        truths.append(truth)
    return windows, truths


def _pack(w):
    return [(w.sequences[i], w.qualities[i], w.positions[i][0],
             w.positions[i][1]) for i in range(len(w.sequences))]


def test_device_consensus_byte_identical_to_host():
    """>= 20 windows, spanning + subgraph layers: device-engine output must
    equal the host engine's byte-for-byte (consensus AND coverages)."""
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 12, length=80, depth=6)
    sub_windows, _ = _make_windows(rng, 10, length=90, depth=5,
                                   spanning=False)
    windows += sub_windows
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(3, -5, -4, num_threads=2, max_nodes=192,
                         max_len=128, buckets=((96, 96), (192, 128)),
                         batch_rows=8)
    dev, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4, n_threads=2)

    assert (statuses == 0).all(), statuses.tolist()
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(dev, host)):
        assert dc == hc, f"window {i} consensus diverged"
        np.testing.assert_array_equal(dcov, hcov, err_msg=f"window {i}")


def _block_swap_windows(rng):
    """Windows whose last layer is a homopolymer block swap: same length
    (so the 256-band is used) but the true path drifts ~300 columns off
    the band — the in-band result is mismatch soup, the exact case the
    clipped -> full-DP retry exists for."""
    windows = []
    for _ in range(3):
        bb = b"A" * 300 + b"C" * 300
        w = Window(0, 0, WindowType.kTGS, bb, b"!" * len(bb))
        w.add_layer(mutate(rng, bb, 0.05), None, 0, len(bb) - 1)
        w.add_layer(mutate(rng, bb, 0.05), None, 0, len(bb) - 1)
        w.add_layer(b"C" * 300 + b"A" * 300, None, 0, len(bb) - 1)
        windows.append(w)
    return windows


def test_device_banded_retry_byte_identical():
    """The banded clipped -> full-DP retry must fire and the output must
    still match the host engine exactly."""
    rng = random.Random(11)
    windows = _block_swap_windows(rng)
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(5, -4, -8, max_nodes=1280, max_len=640,
                         buckets=((1280, 640),), batch_rows=8)
    dev, statuses = eng.consensus(packed)
    host = poa_batch(packed, 5, -4, -8)

    assert (statuses == 0).all(), statuses.tolist()
    assert eng.last_stats["redos"] >= 3, eng.last_stats
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(dev, host)):
        assert dc == hc, f"window {i} consensus diverged"
        np.testing.assert_array_equal(dcov, hcov, err_msg=f"window {i}")


def test_banded_only_mode_skips_retry():
    """-b / banded-only (the reference's --cuda-banded-alignment speed
    trade, cudabatch.cpp:56-59): banded results are trusted as-is — no
    full-DP retries — and the engine still polishes every window."""
    rng = random.Random(11)
    windows = _block_swap_windows(rng)
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(5, -4, -8, max_nodes=1280, max_len=640,
                         buckets=((1280, 640),), batch_rows=8,
                         banded_only=True)
    dev, statuses = eng.consensus(packed)
    assert (statuses == 0).all(), statuses.tolist()
    assert eng.last_stats["redos"] == 0, eng.last_stats
    assert all(len(c) > 0 for c, _ in dev)


def test_device_unfit_windows_host_fallback_identical():
    """Windows outside a tiny forced envelope (too many nodes / layer too
    long) must be host-polished (status 1) with output identical to the
    host engine — the per-window GPU->CPU fallback discipline
    (cudapolisher.cpp:354-383)."""
    rng = random.Random(6)
    windows, _ = _make_windows(rng, 2, length=60)
    big = Window(0, 0, WindowType.kTGS, b"ACGT" * 25, b"!" * 100)
    big.add_layer(b"ACGT" * 25, None, 0, 99)
    big.add_layer(b"ACGTA" * 20, None, 0, 99)
    windows.append(big)  # 100 nodes > max_nodes=96 -> unfit
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(3, -5, -4, max_nodes=96, max_len=96,
                         buckets=((96, 96),), batch_rows=8)
    dev, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert statuses.tolist() == [0, 0, 1]
    assert eng.last_stats["unfit"] == 1
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(dev, host)):
        assert dc == hc, f"window {i} consensus diverged"
        np.testing.assert_array_equal(dcov, hcov, err_msg=f"window {i}")


def test_batch_poa_device_engine_end_to_end():
    rng = random.Random(7)
    windows, truths = _make_windows(rng, 4)
    engine = BatchPOA(3, -5, -4, 60, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    for w, truth in zip(windows, truths):
        assert w.polished
        assert edit_distance(w.consensus, truth) <= \
            edit_distance(w.sequences[0], truth)


def test_precompile_covers_all_buckets():
    eng = DeviceGraphPOA(3, -5, -4, max_nodes=96, max_len=96,
                         buckets=((64, 64), (96, 96)), batch_rows=8)
    eng.precompile()  # must not raise; compiles both buckets
    assert set(eng.batch_rows) == {(64, 64), (96, 96)}


def test_sharded_matches_single_device():
    """Identical kernel outputs on 1 device vs the full 8-device mesh."""
    rng = random.Random(9)
    fn = graph_aligner(64, 64, 4, 3, -5, -4)
    ts = [bytes(rng.choice(ACGT) for _ in range(50)) for _ in range(16)]
    qs = [mutate(rng, t, 0.2) or b"A" for t in ts]
    args = linear_graph_inputs(ts, qs, 64, 64)

    single = BatchRunner(devices=jax.devices()[:1])
    multi = BatchRunner()
    assert multi.n_devices == 8, "conftest should provide 8 virtual devices"
    r1 = np.asarray(single.run(fn, *args))
    r8 = np.asarray(multi.run(fn, *args))
    np.testing.assert_array_equal(r1, r8)


def test_session_stats_counters():
    rng = random.Random(21)
    windows, _ = _make_windows(rng, 3, length=50, depth=4)
    packed = [_pack(w) for w in windows]
    session = PoaSession(packed, 3, -5, -4, 128, 8, 96, max_jobs=8)
    jobs = session.prepare()
    assert jobs is not None and jobs["n"] == 3
    stats = session.stats()
    assert stats["prepared"] == 3 and stats["committed"] == 0
    session.close()


def test_graft_entry_dryrun(capsys):
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    ranks = fn(*args)
    assert np.asarray(ranks).shape[0] == args[0].shape[0]
    __graft_entry__.dryrun_multichip(8)
    # the dryrun's assertions must actually have RUN: its success line
    # is the receipt. A skip sentinel (MULTICHIP_r01.json recorded one
    # passing with rc 0) must fail here, not slip through tier 1.
    out = capsys.readouterr().out
    assert "__GRAFT_DRYRUN_SKIP__" not in out
    assert "dryrun_multichip: 8-device batch-sharded POA + aligner + " \
           "fused kernels OK" in out


def test_max_nodes_env_knob_resolves_at_construction(monkeypatch, capsys):
    """RACON_TPU_MAX_NODES must take effect at ENGINE CONSTRUCTION (a
    late setenv — e.g. from a fixture or driver — must not be silently
    ignored as an import-time read would), be shared by both engines,
    and fall back with a warning on invalid values instead of crashing
    or degenerating the bucket ladder."""
    from racon_tpu.ops.poa_fused import FusedPOA
    from racon_tpu.ops.poa_graph import MAX_NODES, DeviceGraphPOA

    monkeypatch.setenv("RACON_TPU_MAX_NODES", "3072")
    sess = DeviceGraphPOA(5, -4, -8, batch_rows=8)
    fused = FusedPOA(5, -4, -8, batch_rows=8)
    assert sess.max_nodes == 3072
    assert sess.buckets[-1] == (3072, 640)
    assert fused.N == 3072

    for bad in ("bogus", "0", "-5", "999999999"):
        monkeypatch.setenv("RACON_TPU_MAX_NODES", bad)
        eng = DeviceGraphPOA(5, -4, -8, batch_rows=8)
        assert eng.max_nodes == MAX_NODES, bad
        assert "ignoring invalid" in capsys.readouterr().err

    # explicit constructor argument always beats the env var
    monkeypatch.setenv("RACON_TPU_MAX_NODES", "3072")
    eng = DeviceGraphPOA(5, -4, -8, max_nodes=768, batch_rows=8)
    assert eng.max_nodes == 768
