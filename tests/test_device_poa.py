"""Device POA path tests (ops/poa_device + parallel/mesh).

Run on the CPU backend with 8 virtual devices (conftest.py), exercising the
same sharded code paths the TPU uses — the testing scheme SURVEY.md §4
prescribes in place of the reference's CPU-vs-GPU duality.

Shapes are kept tiny (monkeypatched buckets) so XLA compiles stay fast.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import racon_tpu.ops.poa_device as poa_device
from racon_tpu.core.window import Window, WindowType
from racon_tpu.native import edit_distance, poa_batch
from racon_tpu.ops.encode import encode_padded
from racon_tpu.ops.poa import BatchPOA
from racon_tpu.parallel.mesh import BatchRunner

ACGT = b"ACGT"


def mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def optimal_score(q, t, match, mismatch, gap):
    m, n = len(q), len(t)
    H = np.zeros((m + 1, n + 1), dtype=np.int32)
    H[0, :] = np.arange(n + 1) * gap
    H[:, 0] = np.arange(m + 1) * gap
    for i in range(1, m + 1):
        sub = np.where(np.frombuffer(t, np.uint8) == q[i - 1], match, mismatch)
        for j in range(1, n + 1):
            H[i, j] = max(H[i - 1, j - 1] + sub[j - 1], H[i - 1, j] + gap,
                          H[i, j - 1] + gap)
    return int(H[m, n])


def path_score(nd, ps, q, t, match, mismatch, gap):
    score = 0
    for n_, p_ in zip(nd, ps):
        if n_ >= 0 and p_ >= 0:
            score += match if q[p_] == t[n_] else mismatch
        else:
            score += gap
    return score


def test_device_aligner_is_optimal():
    rng = random.Random(2)
    fn = poa_device._aligner(64, 64, 3, -5, -4)
    ts = [bytes(rng.choice(ACGT) for _ in range(rng.randrange(20, 60)))
          for _ in range(16)]
    qs = [mutate(rng, t, 0.25) or b"A" for t in ts]
    q_codes, q_lens = encode_padded(qs, 64)
    t_codes, t_lens = encode_padded(ts, 64)
    nodes, poss = map(np.asarray, fn(q_codes, q_lens, t_codes, t_lens))
    for k in range(len(qs)):
        sel = nodes[k] != -2
        nd, ps = nodes[k][sel][::-1], poss[k][sel][::-1]
        assert list(ps[ps >= 0]) == list(range(len(qs[k])))
        assert list(nd[nd >= 0]) == list(range(len(ts[k])))
        got = path_score(nd, ps, qs[k], ts[k], 3, -5, -4)
        assert got == optimal_score(qs[k], ts[k], 3, -5, -4), k


def _make_windows(rng, n_windows, length=60, depth=6):
    windows = []
    truths = []
    for _ in range(n_windows):
        truth = bytes(rng.choice(ACGT) for _ in range(length))
        bb = mutate(rng, truth, 0.08)
        w = Window(0, 0, WindowType.kTGS, bb, b"!" * len(bb))
        for _ in range(depth):
            lay = mutate(rng, truth, 0.08)
            w.add_layer(lay, None, 0, len(bb) - 1)
        windows.append(w)
        truths.append(truth)
    return windows, truths


def test_device_prealign_consensus_quality(monkeypatch):
    """Device-prealigned consensus must recover the truth about as well as
    the host evolving-graph engine."""
    monkeypatch.setattr(poa_device, "_BUCKETS", ((96, 96),))
    rng = random.Random(5)
    windows, truths = _make_windows(rng, 6)

    pre = poa_device.device_prealign(windows, 3, -5, -4)
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in windows]
    dev = poa_batch(packed, 3, -5, -4, prealigned=pre)
    host = poa_batch(packed, 3, -5, -4)

    for (dc, _), (hc, _), truth, w in zip(dev, host, truths, windows):
        d_dev = edit_distance(dc, truth)
        d_host = edit_distance(hc, truth)
        d_bb = edit_distance(w.sequences[0], truth)
        assert d_dev <= max(d_host + 2, d_bb // 2), \
            (d_dev, d_host, d_bb)


def test_device_prealign_oversize_falls_back(monkeypatch):
    monkeypatch.setattr(poa_device, "_BUCKETS", ((64, 64),))
    rng = random.Random(6)
    windows, _ = _make_windows(rng, 2, length=60)
    big = Window(0, 0, WindowType.kTGS, b"A" * 100, b"!" * 100)
    big.add_layer(b"A" * 100, None, 0, 99)
    big.add_layer(b"A" * 100, None, 0, 99)
    windows.append(big)
    pre = poa_device.device_prealign(windows, 3, -5, -4)
    assert pre[0] is not None and pre[1] is not None
    assert pre[2] is None  # oversize window -> host fallback


def test_batch_poa_device_engine_end_to_end(monkeypatch):
    monkeypatch.setattr(poa_device, "_BUCKETS", ((96, 96),))
    rng = random.Random(7)
    windows, truths = _make_windows(rng, 4)
    engine = BatchPOA(3, -5, -4, 60, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    for w, truth in zip(windows, truths):
        assert w.polished
        assert edit_distance(w.consensus, truth) <= \
            edit_distance(w.sequences[0], truth)


def test_sharded_matches_single_device():
    """Identical kernel outputs on 1 device vs the full 8-device mesh."""
    rng = random.Random(9)
    fn = poa_device._aligner(64, 64, 3, -5, -4)
    ts = [bytes(rng.choice(ACGT) for _ in range(50)) for _ in range(16)]
    qs = [mutate(rng, t, 0.2) or b"A" for t in ts]
    q_codes, q_lens = encode_padded(qs, 64)
    t_codes, t_lens = encode_padded(ts, 64)

    single = BatchRunner(devices=jax.devices()[:1])
    multi = BatchRunner()
    assert multi.n_devices == 8, "conftest should provide 8 virtual devices"
    n1, p1 = map(np.asarray, single.run(fn, q_codes, q_lens, t_codes, t_lens))
    n8, p8 = map(np.asarray, multi.run(fn, q_codes, q_lens, t_codes, t_lens))
    np.testing.assert_array_equal(n1, n8)
    np.testing.assert_array_equal(p1, p8)


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    nodes, poss = fn(*args)
    assert np.asarray(nodes).shape[0] == args[0].shape[0]
    __graft_entry__.dryrun_multichip(8)


def test_banded_device_aligner_matches_full_on_diagonal_pairs():
    """Static-band kernel (the -b flag, cudapoa static_band mode) must
    agree with the full kernel whenever the path stays near the diagonal."""
    rng = random.Random(13)
    full = poa_device._aligner(96, 96, 3, -5, -4)
    banded = poa_device._aligner(96, 96, 3, -5, -4, 32)
    ts = [bytes(rng.choice(ACGT) for _ in range(80)) for _ in range(8)]
    qs = [mutate(rng, t, 0.08) or b"A" for t in ts]
    q_codes, q_lens = encode_padded(qs, 96)
    t_codes, t_lens = encode_padded(ts, 96)
    nf, pf = map(np.asarray, full(q_codes, q_lens, t_codes, t_lens))
    nb, pb = map(np.asarray, banded(q_codes, q_lens, t_codes, t_lens))
    for k in range(len(qs)):
        # both must consume exactly the pair
        for nodes, poss in ((nf[k], pf[k]), (nb[k], pb[k])):
            sel = nodes != -2
            nd, ps = nodes[sel][::-1], poss[sel][::-1]
            assert list(ps[ps >= 0]) == list(range(len(qs[k]))), k
            assert list(nd[nd >= 0]) == list(range(len(ts[k]))), k
        # near-diagonal pairs: identical path scores
        sf = path_score(nf[k][nf[k] != -2][::-1], pf[k][pf[k] != -2][::-1],
                        qs[k], ts[k], 3, -5, -4)
        sb = path_score(nb[k][nb[k] != -2][::-1], pb[k][pb[k] != -2][::-1],
                        qs[k], ts[k], 3, -5, -4)
        assert sb == sf, (k, sb, sf)


def test_banded_batchpoa_end_to_end(monkeypatch):
    monkeypatch.setattr(poa_device, "_BUCKETS", ((96, 96),))
    rng = random.Random(17)
    windows, truths = _make_windows(rng, 4)
    engine = BatchPOA(3, -5, -4, 60, device_batches=1, banded=True,
                      band_width=32)
    engine.generate_consensus(windows, trim=False)
    for w, truth in zip(windows, truths):
        assert w.polished
        assert edit_distance(w.consensus, truth) <= \
            edit_distance(w.sequences[0], truth) + 2
