"""Fleet observability plane: scrape round-trip, merge, alerts.

Pins the PR-12 contracts:

  - the Prometheus text round trip is EXACT: `parse(render(registry))`
    reproduces every counter, gauge, labeled family and histogram
    bucket (plus the exact min/max sidecars and OpenMetrics exemplars),
    and the strict parser rejects drifted bodies;
  - histogram `merge()` is associative and order-independent — the
    property fleet aggregation silently depends on — and a 3-replica
    in-process fleet's merged quantiles EQUAL the quantiles of the
    pooled raw observations;
  - `/healthz` answers 503 `{"draining": true}` (HTTP) / `ok: false`
    (RPC) once a replica starts draining, on BOTH transports;
  - an injected deadline-miss flood trips the SLO burn-rate alert
    (typed `alert` journal event + `racon_tpu_slo_burn_alert` gauge
    flip) and the latency exemplar names the flight dump of an
    actually-missed job;
  - per-tenant queue-depth/credit gauges and autotuner consult
    counters ride the scrape as properly labeled series;
  - obsreport `--check` tolerates `alert` (and unknown) event types
    and renders alerts in the per-job timeline; perfgate gates the
    servebench `--fleet` scrape-overhead column at the <2% budget.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from racon_tpu.obs import prom
from racon_tpu.obs.fleet import (BurnRateTracker, Endpoint,
                                 FleetAggregator)
from racon_tpu.obs.hist import Histogram, HistogramSet
from racon_tpu.obs.journal import check_consistency
from racon_tpu.serve.protocol import recv_frame, send_frame
from racon_tpu.serve.server import PolishServer, make_synth_dataset


# ----------------------------------------------------------- prom round trip
def _hist_state(h: Histogram) -> tuple:
    buckets, count, total = h.export()
    return (tuple(buckets), count, total, h.min, h.max)


def test_prom_roundtrip_exact():
    """parse(render(registry)) reproduces every counter, gauge and
    histogram bucket exactly — the property federation rests on."""
    rng = random.Random(7)
    hs = HistogramSet()
    for _ in range(500):
        hs.observe("job.latency", rng.lognormvariate(-1.5, 1.5))
    hs.observe("job.latency", 0.42,
               exemplar={"trace_id": "t-1", "flight": "/tmp/f.json"})
    for _ in range(50):
        hs.observe("serve.iteration", rng.uniform(0, 2))
    counters = {"serve.jobs.completed": 421,
                "serve.compiles": (7, "engine compiles"),
                "sched.autotune.consults": prom.Labeled(
                    [({"engine": "aligner", "decision": "pallas",
                       "dtype": "int16"}, 12),
                     ({"engine": "fused_loop", "decision": "none",
                       "dtype": ""}, 3)], "consults")}
    gauges = {"serve.queue_depth": 5,
              "serve.draining": False,
              "serve.tenant_queue_depth": prom.Labeled(
                  [({"tenant": "gold"}, 3), ({"tenant": ""}, 1)])}
    text = prom.render(counters, gauges, hs)
    s = prom.parse(text)
    assert s.counters["racon_tpu_serve_jobs_completed_total"] == 421
    assert s.counters["racon_tpu_serve_compiles_total"] == 7
    assert s.gauges["racon_tpu_serve_queue_depth"] == 5
    assert s.gauges["racon_tpu_serve_draining"] == 0
    consults = s.counter_series[
        "racon_tpu_sched_autotune_consults_total"]
    by_engine = {lbl["engine"]: (lbl["decision"], lbl["dtype"], v)
                 for _, (lbl, v) in consults.items()}
    assert by_engine == {"aligner": ("pallas", "int16", 12.0),
                         "fused_loop": ("none", "", 3.0)}
    tenants = s.gauge_series["racon_tpu_serve_tenant_queue_depth"]
    assert {lbl["tenant"]: v for _, (lbl, v) in tenants.items()} == \
        {"gold": 3.0, "": 1.0}
    for name in ("job.latency", "serve.iteration"):
        orig = hs.get(name)
        back = s.histogram(prom.metric_name(name) + "_seconds")
        assert _hist_state(back) == _hist_state(orig)
    # the exemplar survived, on the same bucket, with its labels
    orig = hs.get("job.latency")
    back = s.histogram("racon_tpu_job_latency_seconds")
    oex, bex = orig.bucket_exemplars(), back.bucket_exemplars()
    assert oex.keys() == bex.keys()
    (le,) = [le for le, ex in bex.items()
             if ex.get("trace_id") == "t-1"]
    assert bex[le]["flight"] == "/tmp/f.json"
    assert bex[le]["value"] == oex[le]["value"]
    # a re-render of the parsed view parses again (idempotent format)
    prom.parse(prom.render(hists=s.histogram_set()))


def test_prom_parse_strict():
    with pytest.raises(prom.PromParseError):
        prom.parse("this is not prometheus\n")
    with pytest.raises(prom.PromParseError):
        prom.parse("racon_tpu_x 1\n")  # sample without a TYPE line
    with pytest.raises(prom.PromParseError):
        prom.parse("# TYPE racon_tpu_x gauge\n"
                   "racon_tpu_x{tenant=unquoted} 1\n")


# ------------------------------------------------------------- hist merging
def _fill(values) -> Histogram:
    h = Histogram()
    for v in values:
        h.observe(v)
    return h


def test_hist_merge_associative_and_order_independent():
    """merge(a, merge(b, c)) == merge(merge(a, b), c), and every
    permutation pools to the same exact state — count/sum/min/max and
    every bucket, not approximately."""
    rng = random.Random(11)
    # dyadic values (k/1024): every partial sum is exactly
    # representable, so float addition is genuinely associative and
    # the `sum` comparison below is EXACT, not approximately-equal
    parts = [[rng.randrange(1, 1 << 14) / 1024.0 for _ in range(n)]
             for n in (137, 1, 55)]

    def state(h):
        return (tuple(h.counts), h.count, h.sum, h.min, h.max)

    # associativity
    left = _fill(parts[0])
    bc = _fill(parts[1])
    bc.merge(_fill(parts[2]))
    left.merge(bc)
    right = _fill(parts[0])
    right.merge(_fill(parts[1]))
    right.merge(_fill(parts[2]))
    assert state(left) == state(right)
    # order independence, vs the pooled ground truth
    pooled = _fill([v for p in parts for v in p])
    import itertools

    for perm in itertools.permutations(range(3)):
        acc = Histogram()
        for i in perm:
            acc.merge(_fill(parts[i]))
        assert state(acc) == state(pooled), f"order {perm} diverged"
    # an empty histogram is the identity
    ident = Histogram()
    ident.merge(pooled)
    assert state(ident) == state(pooled)


def test_hist_from_export_roundtrip():
    h = _fill([0.001, 0.5, 0.5, 700.0, 50000.0])  # incl. overflow
    h.observe(0.2, exemplar={"trace_id": "x"})
    buckets, count, total = h.export()
    back = Histogram.from_export(buckets, count, total, h.min, h.max,
                                 h.bucket_exemplars())
    assert back.counts == h.counts
    assert (back.count, back.sum, back.min, back.max) == \
        (h.count, h.sum, h.min, h.max)
    assert back.bucket_exemplars().keys() == \
        h.bucket_exemplars().keys()
    for q in (0.5, 0.9, 0.99):
        assert back.quantile(q) == h.quantile(q)


def test_hist_from_export_without_sidecars_stays_usable():
    """A pre-sidecar replica's scrape (no _min/_max): reconstruction
    falls back to bucket-derived bounds — quantile/snapshot/re-render
    must work, never TypeError on None."""
    h = _fill([0.05, 0.3, 2.0])
    buckets, count, total = h.export()
    back = Histogram.from_export(buckets, count, total)  # no min/max
    assert back.min is not None and back.max is not None
    assert back.min <= 0.05 and back.max >= 2.0 * (2 ** -0.25)
    assert back.quantile(0.5) > 0
    assert back.snapshot()["count"] == 3
    prom.parse(prom.render(
        hists=HistogramSet()) + "\n".join(
        prom.histogram_lines("x", back)) + "\n")


# -------------------------------------------------------------- fake fleet
def _fake_replica(sock_path: str, hists: HistogramSet,
                  counters: dict, draining: bool = False,
                  gauges: dict | None = None):
    """A minimal frame-protocol replica answering scrape/healthz —
    enough surface for the aggregator, without a polishing engine."""
    lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    lst.bind(sock_path)
    lst.listen(8)
    lst.settimeout(0.2)
    stop = threading.Event()

    def handle(conn):
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                if req.get("type") == "scrape":
                    send_frame(conn, {
                        "type": "metrics",
                        "text": prom.render(counters=counters,
                                            gauges=gauges,
                                            hists=hists)})
                elif req.get("type") == "healthz":
                    send_frame(conn, {"type": "healthz",
                                      "ok": not draining,
                                      "draining": draining})
                else:
                    send_frame(conn, {"type": "error",
                                      "message": "bad request"})
        except OSError:
            pass
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def loop():
        while not stop.is_set():
            try:
                conn, _ = lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=loop, daemon=True).start()

    def close():
        stop.set()
        with contextlib.suppress(OSError):
            lst.close()

    return close


def test_fleet_merged_quantiles_equal_pooled(tmp_path):
    """The acceptance pin: a 3-replica fleet's merged quantiles equal
    the quantiles of the pooled raw observations — exactly, because
    buckets, count and the min/max sidecars all round-trip exactly."""
    rng = random.Random(3)
    obs = [[rng.lognormvariate(-1, 1.6) for _ in range(n)]
           for n in (200, 31, 77)]
    closers = []
    endpoints = []
    try:
        for i, values in enumerate(obs):
            hs = HistogramSet()
            for v in values:
                hs.observe("job.latency", v)
            path = str(tmp_path / f"r{i}.sock")
            closers.append(_fake_replica(
                path, hs,
                {"serve.jobs.deadline_hit": 10 * (i + 1),
                 "serve.jobs.deadline_miss": i},
                # replicas export their OWN burn gauges (the live
                # server does) — federation must replace them with the
                # fleet tracker's, never duplicate the family
                gauges={"slo.burn_rate": 0.5 * i,
                        "slo.burn_rate_slow": 0.1,
                        "slo.burn_alert": False}))
            endpoints.append(path)
        agg = FleetAggregator(endpoints)
        snap = agg.poll()
        assert snap.healthy
        assert all(r.ok and not r.error for r in snap.replicas)
        merged = snap.hists.get("racon_tpu_job_latency_seconds")
        pooled = _fill([v for part in obs for v in part])
        assert merged.count == pooled.count == sum(map(len, obs))
        assert merged.counts == pooled.counts
        assert (merged.min, merged.max) == (pooled.min, pooled.max)
        for q in (0.5, 0.9, 0.95, 0.99, 1.0):
            assert merged.quantile(q) == pooled.quantile(q), q
        # counters summed across replicas
        assert snap.counters[
            "racon_tpu_serve_jobs_deadline_hit_total"] == 60
        assert snap.counters[
            "racon_tpu_serve_jobs_deadline_miss_total"] == 3
        # federated HTTP endpoint: /metrics parses, /healthz is 200
        port = agg.start_http(0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics") as resp:
            fed_text = resp.read().decode()
        # no duplicated metric family (a real Prometheus server
        # rejects the whole body otherwise) — one TYPE line per name
        type_lines = [ln for ln in fed_text.splitlines()
                      if ln.startswith("# TYPE ")]
        assert len(type_lines) == len(set(type_lines))
        fed = prom.parse(fed_text)
        assert fed.gauges["racon_tpu_fleet_replicas"] == 3
        assert "racon_tpu_slo_burn_rate" in fed.gauges
        refed = fed.histogram("racon_tpu_job_latency_seconds")
        assert refed.counts == pooled.counts
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as resp:
            body = json.loads(resp.read())
        assert body["ok"] is True and len(body["replicas"]) == 3
        # machine-readable snapshot
        doc = agg.to_json()
        assert doc["healthy"] is True
        assert doc["latency"]["racon_tpu_job_latency_seconds"][
            "count"] == pooled.count
        agg.close()
    finally:
        for close in closers:
            close()


def test_fleet_unreachable_and_draining_replicas(tmp_path):
    """healthz contract: ONE draining or unreachable replica makes the
    fleet unhealthy, with per-replica detail saying which and why."""
    hs = HistogramSet()
    hs.observe("job.latency", 0.1)
    up = str(tmp_path / "up.sock")
    drn = str(tmp_path / "drn.sock")
    closers = [_fake_replica(up, hs, {}),
               _fake_replica(drn, hs, {}, draining=True)]
    try:
        agg = FleetAggregator([up, drn, str(tmp_path / "gone.sock")])
        snap = agg.poll()
        assert not snap.healthy
        by_ep = {r.endpoint: r for r in snap.replicas}
        assert by_ep[up].ok and not by_ep[up].draining
        assert by_ep[drn].draining and not by_ep[drn].ok
        assert by_ep[str(tmp_path / "gone.sock")].error
        port = agg.start_http(0)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
        assert exc.value.code == 503
        detail = json.loads(exc.value.read())
        assert detail["ok"] is False
        agg.close()
    finally:
        for close in closers:
            close()


def test_endpoint_spellings():
    assert Endpoint("/tmp/x.sock").kind == "unix"
    assert Endpoint("127.0.0.1:7788").kind == "tcp"
    assert Endpoint("http://127.0.0.1:9090/metrics").kind == "http"
    assert Endpoint("http://127.0.0.1:9090/metrics").base == \
        "http://127.0.0.1:9090"
    with pytest.raises(ValueError):
        Endpoint("not a port")


# ---------------------------------------------------------------- burn rate
def test_burn_rate_tracker_dual_window():
    tr = BurnRateTracker(budget=0.01, fast_s=60, slow_s=600,
                         threshold=2.0, seed_zero=True)
    t0 = 1000.0
    # healthy stream: hits only, never fires
    for i in range(5):
        res = tr.sample(hit=i + 1, miss=0, t=t0 + i)
        assert res["fast"] == 0.0 and not res["firing"]
    # miss flood: both windows blow the budget -> firing, once
    res = tr.sample(hit=5, miss=5, t=t0 + 10)
    assert res["firing"] and res["changed"]
    assert res["fast"] >= 2.0 and res["slow"] >= 2.0
    res = tr.sample(hit=5, miss=6, t=t0 + 11)
    assert res["firing"] and not res["changed"]  # edge fired already
    # recovery: a long quiet stretch ages the misses out of both
    # windows -> one clear edge, then steady clear
    res = tr.sample(hit=500, miss=6, t=t0 + 700)
    assert not res["firing"] and res["changed"]
    res = tr.sample(hit=1000, miss=6, t=t0 + 1400)
    assert not res["firing"] and not res["changed"]


def test_burn_rate_counter_reset_rebases():
    """A summed-counter DECREASE (replica restart) rebases the sample
    history instead of masking an ongoing breach with negative
    deltas: continuing misses re-fire promptly."""
    tr = BurnRateTracker(budget=0.01, fast_s=60, slow_s=600,
                         threshold=2.0, seed_zero=True)
    tr.sample(hit=10, miss=10, t=1000.0)
    assert tr.firing
    # a replica restarts: merged totals drop
    res = tr.sample(hit=4, miss=4, t=1001.0)
    assert not res["firing"]  # history rebased, honest unknown
    # the flood continues on the rebased baseline -> fires again
    res = tr.sample(hit=4, miss=8, t=1002.0)
    assert res["firing"] and res["changed"]


def test_burn_rate_single_window_does_not_fire():
    """The dual-window property: a breach the slow window has already
    absorbed (old misses, quiet since) must not page."""
    tr = BurnRateTracker(budget=0.01, fast_s=10, slow_s=600,
                         threshold=2.0, seed_zero=True)
    tr.sample(hit=0, miss=5, t=1000.0)
    # fast window sees only clean traffic now; slow still remembers
    res = tr.sample(hit=300, miss=5, t=1300.0)
    assert res["fast"] == 0.0
    assert not res["firing"]


# ----------------------------------------------------- live-server contracts
@pytest.fixture(scope="module")
def fleet_dataset(tmp_path_factory):
    return make_synth_dataset(
        str(tmp_path_factory.mktemp("fleet_data")))


def test_healthz_draining_both_transports(fleet_dataset, tmp_path):
    """Satellite pin: a draining replica answers `ok: false` on the
    RPC and 503 `{"draining": true}` on HTTP, so load balancers stop
    routing to it."""
    from racon_tpu.serve.client import PolishClient

    sock = str(tmp_path / "hz.sock")
    srv = PolishServer(socket_path=sock, warmup=False,
                       metrics_port=0).start()
    try:
        cl = PolishClient(socket_path=sock)
        port = srv.config.metrics_port
        hz = cl.healthz()
        assert hz["ok"] is True and hz["draining"] is False
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["ok"] is True
        # flip the drain flag (the exact bit graceful drain sets first)
        srv._draining.set()
        hz = cl.healthz()
        assert hz["ok"] is False and hz["draining"] is True
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert body["draining"] is True and body["ok"] is False
    finally:
        srv._draining.clear()
        srv.drain(timeout=10)


def test_deadline_miss_flood_trips_alert_and_exemplar(fleet_dataset,
                                                     tmp_path):
    """Acceptance pin: a deadline-miss flood trips the burn-rate alert
    (journal `alert` event + gauge flip) and the latency exemplar
    names the flight dump of an actually-missed job."""
    from racon_tpu.obs.journal import read_journal
    from racon_tpu.serve.client import PolishClient

    sock = str(tmp_path / "burn.sock")
    journal = str(tmp_path / "burn_journal.jsonl")
    flight_dir = str(tmp_path / "flight")
    srv = PolishServer(socket_path=sock, warmup=False, journal=journal,
                       flight_dir=flight_dir, workers=3).start()
    try:
        cl = PolishClient(socket_path=sock)
        # every job pops instantly (3 idle workers) but the held
        # feeder pins its service time past the deadline ->
        # deadline_miss for all three, deterministically (the same
        # hold()/release() seam the preemption tests use)
        srv.batcher.hold()
        errs = []

        def flood(i):
            try:
                cl.submit(*fleet_dataset, deadline_s=0.1,
                          trace_id=f"flood-{i}")
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        threads = [threading.Thread(target=flood, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # all popped, all deadlines now past
        srv.batcher.release()
        for t in threads:
            t.join()
        assert not errs, errs
        text = cl.scrape()
        s = prom.parse(text)
        assert s.counters[
            "racon_tpu_serve_jobs_deadline_miss_total"] == 3
        assert s.gauges["racon_tpu_slo_burn_alert"] == 1
        assert s.gauges["racon_tpu_slo_burn_rate"] >= \
            srv.burn.threshold
        # typed alert in the journal, carrying the tripping job's id
        alerts = [e for e in read_journal(journal)
                  if e.get("event") == "alert"]
        assert alerts and alerts[0]["state"] == "firing"
        assert alerts[0]["kind"] == "slo-burn"
        assert alerts[0].get("job")
        # the p99 bucket's exemplar names a real missed job's dump
        h = s.histogram("racon_tpu_job_latency_seconds")
        p99 = h.quantile(0.99)
        ex = [e for le, e in h.bucket_exemplars().items()
              if le >= p99 and "flight" in e]
        assert ex, "no exemplar at/above the p99 bucket"
        assert os.path.isfile(ex[-1]["flight"])
        assert "deadline-miss" in ex[-1]["flight"]
        assert ex[-1]["trace_id"].startswith("flood-")
        with open(ex[-1]["flight"]) as fh:
            dump = json.load(fh)
        assert dump["flight"]["reason"] == "deadline-miss"
    finally:
        srv.drain(timeout=10)


def test_exemplars_disabled_keeps_scrape_clean(fleet_dataset, tmp_path,
                                               monkeypatch):
    """RACON_TPU_SERVE_EXEMPLARS=0: the A/B knob removes every
    exemplar from the exposition (the disabled half of the overhead
    acceptance)."""
    from racon_tpu.serve.client import PolishClient

    monkeypatch.setenv("RACON_TPU_SERVE_EXEMPLARS", "0")
    sock = str(tmp_path / "noex.sock")
    srv = PolishServer(socket_path=sock, warmup=False).start()
    try:
        cl = PolishClient(socket_path=sock)
        cl.submit(*fleet_dataset)
        text = cl.scrape()
        assert " # {" not in text
        h = prom.parse(text).histogram("racon_tpu_job_latency_seconds")
        assert h.count >= 1 and not h.bucket_exemplars()
    finally:
        srv.drain(timeout=10)


def test_tenant_and_autotune_series_in_scrape(tmp_path):
    """Satellite pin: per-tenant queue-depth/credit gauges and
    autotuner consult counters ride the scrape as labeled series."""
    from racon_tpu.sched.autotune import (get_autotuner,
                                          reset_autotuner_cache)
    from racon_tpu.serve.queue import Job

    os.environ["RACON_TPU_AUTOTUNE_CACHE"] = str(
        tmp_path / "autotune.json")
    reset_autotuner_cache()
    try:
        at = get_autotuner()
        at.record("aligner", (128, 64), (), {"kernel": "pallas",
                                             "dtype": "int16"})
        assert at.winner("aligner", (128, 64)) is not None
        at.winner("session", (64, 100))  # cold consult
        srv = PolishServer(socket_path=str(tmp_path / "t.sock"),
                           warmup=False, tenant_quota=0)
        for i, tenant in enumerate(("gold", "gold", "free")):
            srv.queue.submit(Job(f"j{i}", "s", "o", "t", {},
                                 tenant=tenant))
        s = prom.parse(srv.prometheus_text())
        depths = {lbl["tenant"]: v for _, (lbl, v) in s.gauge_series[
            "racon_tpu_serve_tenant_queue_depth"].items()}
        assert depths == {"gold": 2.0, "free": 1.0}
        assert "racon_tpu_serve_tenant_credit" in s.gauge_series
        consults = {(lbl["engine"], lbl["decision"]): v
                    for _, (lbl, v) in s.counter_series[
                        "racon_tpu_sched_autotune_consults_total"
                    ].items()}
        assert consults[("aligner", "pallas")] >= 1
        assert consults[("session", "none")] >= 1
    finally:
        del os.environ["RACON_TPU_AUTOTUNE_CACHE"]
        reset_autotuner_cache()


def test_servetop_once_renders_fleet(fleet_dataset, tmp_path, capsys):
    """servetop --once against a live replica: the non-TTY one-shot
    screen carries the fleet line, the replica row and exit 0."""
    import servetop

    from racon_tpu.serve.client import PolishClient

    sock = str(tmp_path / "top.sock")
    srv = PolishServer(socket_path=sock, warmup=False).start()
    try:
        # a completed tenant-tagged job, so the tenant table and the
        # completed counters actually render (a bare server hid a
        # first-sample KeyError in the tenant rows once)
        PolishClient(socket_path=sock).submit(*fleet_dataset,
                                              tenant="gold")
        rc = servetop.main(["--once", "--endpoints", sock])
        out = capsys.readouterr().out
        assert rc == 0
        assert "fleet" in out and sock in out
        assert "queue" in out
        assert "gold" in out  # tenant table rendered
    finally:
        srv.drain(timeout=10)


# ----------------------------------------------------- obsreport + perfgate
def test_obsreport_check_tolerates_alert_and_unknown_events(tmp_path,
                                                            capsys):
    """Satellite pin: `alert` (and any unknown) journal event types
    must not fail `--check`; alerts render in the job timeline; a
    quota-rejected job is a consistent terminal state."""
    import obsreport

    t = time.time()
    entries = [
        {"t": t, "event": "received", "job": "j1", "trace": "tr"},
        {"t": t, "event": "admitted", "job": "j1"},
        {"t": t + 0.1, "event": "started", "job": "j1"},
        {"t": t + 0.4, "event": "part-streamed", "job": "j1",
         "contig": "c", "part": 1, "bytes": 10},
        {"t": t + 0.5, "event": "alert", "job": "j1",
         "kind": "slo-burn", "state": "firing", "burn_fast": 40.0},
        {"t": t + 0.5, "event": "deadline-miss", "job": "j1"},
        {"t": t + 0.5, "event": "finished", "job": "j1",
         "sequences": 1, "service_s": 0.4},
        # a quota-rejected job: received + rejected-quota is complete
        {"t": t + 1, "event": "received", "job": "j2"},
        {"t": t + 1, "event": "rejected-quota", "job": "j2",
         "retry_after": 0.5},
        # an event type this tool has never heard of, on its own job
        {"t": t + 2, "event": "frobnicated", "job": "j999"},
        {"t": t + 2, "event": "alert", "kind": "slo-burn",
         "state": "clear"},  # fleet-scope alert, no job id
    ]
    path = tmp_path / "journal.jsonl"
    with open(path, "w") as fh:
        for e in entries:
            fh.write(json.dumps(e) + "\n")
    assert check_consistency(entries) == []
    rc = obsreport.main(["--journal", str(path), "--check",
                         "--flight-dir", str(tmp_path / "none")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "alert" in out and "slo-burn" in out  # rendered in timeline
    assert "consistency: OK" in out


def test_perfgate_fleet_scrape_overhead_gate(tmp_path):
    """Satellite pin: perfgate gates fleet.scrape_overhead_pct at the
    2% budget, and an explicit --scrape-overhead-max over an artifact
    without the block exits 2 naming the dotted key."""
    import perfgate

    def artifact(**extra):
        doc = {"mode": "serve",
               "warm": {"seq_p50_s": 1.0, "p50_s": 1.2},
               "cold": {"p50_s": 9.0}}
        doc.update(extra)
        return doc

    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(artifact(
        fleet={"replicas": 3, "scrape_overhead_pct": 0.8})))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(artifact(
        fleet={"replicas": 3, "scrape_overhead_pct": 4.5})))
    plain = tmp_path / "plain.json"
    plain.write_text(json.dumps(artifact()))
    base = ["--ref-value", "1.0", "--tolerance-pct", "50"]
    assert perfgate.main(["--artifact", str(ok)] + base) == 0
    assert perfgate.main(["--artifact", str(bad)] + base) == 1
    # explicit limit over a block-less artifact: broken gate, rc 2
    assert perfgate.main(["--artifact", str(plain),
                          "--scrape-overhead-max", "2.0"] + base) == 2
    # tighter explicit limit is honored
    assert perfgate.main(["--artifact", str(ok),
                          "--scrape-overhead-max", "0.5"] + base) == 1
