"""Fused whole-window device POA engine tests (ops/poa_fused.py).

The engine builds complete POA graphs on device in ONE call per window
batch (the cudapoa single-launch shape, reference cudabatch.cpp:77-270).
The correctness bar mirrors the session engine's: consensus byte-identical
to the host engine on clean data (asserted here), per-window host fallback
for anything outside the envelope.
"""

import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack, mutate  # noqa: E402

from racon_tpu.native import poa_batch  # noqa: E402
from racon_tpu.ops.poa_fused import FusedPOA  # noqa: E402

ACGT = b"ACGT"


def _assert_identical(res, host, statuses, where=""):
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"{where} window {i} consensus diverged " \
                         f"(status {int(statuses[i])})"
        np.testing.assert_array_equal(dcov, hcov, err_msg=f"window {i}")


def test_fused_byte_identical_to_host():
    """Spanning TGS-style windows, incl. a rotated adversarial layer: the
    fused engine's consensus must equal the host engine's byte-for-byte."""
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    bb = windows[0].sequences[0]
    windows[0].add_layer(bb[110:] + bb[:110], None, 0, len(bb) - 1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, num_threads=2, max_nodes=768, max_len=384,
                   batch_rows=8, depth_buckets=(4, 8))
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4, n_threads=2)

    assert (statuses == 0).all(), statuses.tolist()
    assert eng.n_fallback == 0
    _assert_identical(res, host, statuses)


def test_fused_band_clip_retry_byte_identical_to_host():
    """A layer whose only true match region lies OUTSIDE its band (and
    whose filler can match nothing in-band) trips the host's
    band_clipped rule and is redone with the exact full DP — the fused
    engine must replicate that retry, staying byte-identical. The
    session engine's redo counter proves the construction really clips
    (the rule is a safety net: random-DNA soup usually still weaves
    enough coincidental matches to pass it)."""
    from racon_tpu.core.window import Window, WindowType
    from racon_tpu.ops.poa_graph import DeviceGraphPOA

    rng = random.Random(79)
    windows = []
    for _ in range(3):
        R = bytes(rng.choice(ACGT) for _ in range(100))
        bb = b"A" * 300 + R  # the match region sits 300 bp off-diagonal
        w = Window(0, 0, WindowType.kTGS, bb, b"!" * len(bb))
        for _ in range(2):
            lay = mutate(rng, R, 0.03) + b"C" * 250  # C's match nothing
            w.add_layer(lay, None, 0, len(bb) - 1)
        windows.append(w)
    packed = [_pack(w) for w in windows]

    # non-vacuity: the host-identical session engine really does retry
    sess = DeviceGraphPOA(5, -4, -8, max_nodes=1024, max_len=640,
                          buckets=((1024, 640),), batch_rows=4)
    sess.consensus(packed)
    assert sess.last_stats["redos"] >= 3, sess.last_stats

    host = poa_batch(packed, 5, -4, -8)
    eng = FusedPOA(5, -4, -8, max_nodes=1024, max_len=640, batch_rows=4,
                   depth_buckets=(8,))
    res, statuses = eng.consensus(packed)
    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "band-clip")

    # (-b / banded_only is NOT asserted here: on this construction the
    # heaviest-bundle consensus is identical with and without the retry
    # — measured — so a banded-only run cannot be told apart by output;
    # the flag's behavior is covered by the session engine's
    # test_banded_only_mode_skips_retry and the builder keys on it.)


@pytest.mark.skipif(not os.path.isdir("/root/reference/test/data"),
                    reason="reference sample data not available")
def test_fused_real_sample_slice_identity_pinned(monkeypatch):
    """Default-suite regression guard for the fused engine's REAL-DATA
    behavior (round-4 verdict: the strongest contracts must not live only
    behind RACON_TPU_FULL_GOLDENS): on the 24 shallowest real windows of
    the lambda sample, ALL build on device, every consensus is
    byte-identical to the host engine, and coverages match exactly on
    >= 23/24 — the measured state is ONE window (depth 17) whose final
    two coverage values are transposed (17,16 vs 16,17): a
    heaviest-bundle tie at the consensus tail resolved differently by
    the argsort-key topo order, same bases and same total coverage. Any
    byte divergence, a second coverage-divergent window, or a
    non-permutation coverage change fails the pin."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    D = "/root/reference/test/data/"
    p = create_polisher(D + "sample_reads.fastq.gz",
                        D + "sample_overlaps.paf.gz",
                        D + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = sorted((w for w in p.windows if len(w.sequences) >= 3),
                  key=lambda w: len(w.sequences))[:24]
    assert len(wins) == 24
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in wins]
    host = poa_batch(packed, 5, -4, -8, n_threads=2)
    eng = FusedPOA(5, -4, -8, num_threads=2, batch_rows=8)
    res, statuses = eng.consensus(packed, fallback=False)
    assert (statuses == 0).all(), \
        "every shallow window must build on device"
    cov_diverged = []
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"window {i} consensus bytes diverged"
        if not np.array_equal(dcov, hcov):
            # tie-class divergence only: same multiset of coverages
            assert sorted(np.asarray(dcov).tolist()) == \
                sorted(np.asarray(hcov).tolist()), \
                f"window {i} coverage changed beyond a tie permutation"
            cov_diverged.append(i)
    assert len(cov_diverged) <= 1, \
        f"coverage tie-divergence grew: windows {cov_diverged}"


@pytest.mark.skipif(not os.environ.get("RACON_TPU_FULL_GOLDENS")
                    or not os.path.isdir("/root/reference/test/data"),
                    reason="minutes-long real-data fixture")
def test_fused_real_sample_window_identity_pinned():
    """The fused engine's real-data contract, pinned at its measured
    values: on the lambda sample's 96 windows, >= 95 are byte-identical
    to the host engine, and any divergent window's consensus stays
    within edit distance 4 of the host's (measured: one window at
    distance 3 — a topo-order tie, not a quality regression). A drop
    below 95/96 or a bigger per-window distance means a real tie-order
    or DP change, not noise."""
    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.native import edit_distance

    D = "/root/reference/test/data/"
    p = create_polisher(D + "sample_reads.fastq.gz",
                        D + "sample_overlaps.paf.gz",
                        D + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = [w for w in p.windows if len(w.sequences) >= 3]
    assert len(wins) == 96  # the denominator the pins below assume
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in wins]
    host = poa_batch(packed, 5, -4, -8)
    eng = FusedPOA(5, -4, -8, num_threads=2, batch_rows=16)
    res, statuses = eng.consensus(packed, fallback=False)
    assert (statuses == 0).all(), "every window must build on device"
    diverged = [i for i, (r, h) in enumerate(zip(res, host))
                if r[0] != h[0]]
    assert len(diverged) <= 1, \
        f"{len(diverged)}/96 windows diverged from host: {diverged}"
    for i in diverged:
        d = edit_distance(res[i][0], host[i][0])
        assert d <= 4, f"window {i} diverged by distance {d}"


def test_fused_sharded_matches_single_device(monkeypatch):
    """The fused engine's batch axis shards over the mesh (conftest's
    8-virtual-device CPU mesh) through BatchRunner/shard_map — the
    multi-chip analogue of the reference's batch-per-GPU loop
    (cudapolisher.cpp:228-240). Sharded output must equal the
    single-device output window-for-window, including chained calls."""
    rng = random.Random(21)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    packed = [_pack(w) for w in windows]
    kw = dict(max_nodes=768, max_len=384, batch_rows=8,
              depth_buckets=(4,))  # depth 7 -> 2 chained calls

    multi = FusedPOA(3, -5, -4, **kw)
    assert multi.runner.n_devices > 1, \
        "conftest should provide an 8-virtual-device mesh"
    res_m, st_m = multi.consensus([list(p) for p in packed])

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    one = FusedPOA(3, -5, -4, **kw)
    assert one.runner.n_devices == 1
    res_s, st_s = one.consensus([list(p) for p in packed])

    np.testing.assert_array_equal(st_m, st_s)
    assert (st_m == 0).all(), st_m.tolist()
    _assert_identical(res_m, res_s, st_m, "sharded-vs-single")


def test_fused_deep_windows_chain_calls():
    """Depth beyond the largest bucket chains device calls (state streams
    out of one call into the next); output must still match the host."""
    rng = random.Random(9)
    windows, _ = _make_windows(rng, 4, length=220, depth=11, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=768, max_len=384, batch_rows=4,
                   depth_buckets=(4,))  # 11 layers -> 3 chained calls
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "chained")


def test_fused_non_spanning_layers_use_range_subgraph():
    """Non-spanning layers align against the bpos-range-masked subgraph
    on device (the host's Graph::subgraph semantics, with the host's
    begin-sorted layer order and banded DP): output must equal the host
    engine's byte-for-byte."""
    rng = random.Random(12)
    windows, _ = _make_windows(rng, 6, length=110, depth=5,
                               spanning=False, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=512, max_len=256, batch_rows=8,
                   depth_buckets=(8,))
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "subrange")


def test_fused_envelope_overflow_falls_back_to_host():
    """Graphs that outgrow the node envelope must host-fallback per
    window — and the final output is still identical to the host engine
    for every window."""
    rng = random.Random(6)
    windows, _ = _make_windows(rng, 3, length=220, depth=5, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=230, max_len=384, batch_rows=4,
                   depth_buckets=(8,))  # 230 nodes: graphs overflow fast
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert eng.n_fallback >= 1
    _assert_identical(res, host, statuses, "fallback")


def test_fused_backbone_only_windows():
    rng = random.Random(7)
    windows, _ = _make_windows(rng, 1, length=220, depth=4)
    packed = [_pack(windows[0]), [(b"ACGTACGT" * 30, None, 0, 239)]]
    eng = FusedPOA(3, -5, -4, max_nodes=768, max_len=384, batch_rows=4,
                   depth_buckets=(4,))
    res, statuses = eng.consensus(packed)
    assert statuses[1] == 2
    assert res[1][0] == packed[1][0][0]


def test_fused_loop_single_launch_matches_split():
    """The FUSED single-launch program (device-side window slicing, a
    chunk's whole chain in one jitted scan) is bit-identical to the
    split chained path — spanning and non-spanning windows, chained
    depth — while genuinely collapsing launches."""
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 8, length=220, depth=11, rate=0.12)
    more, _ = _make_windows(random.Random(12), 4, length=110, depth=5,
                            spanning=False, rate=0.1)
    packed = [_pack(w) for w in windows] + [_pack(w) for w in more]
    kw = dict(max_nodes=768, max_len=384, batch_rows=8,
              depth_buckets=(4, 8))

    split = FusedPOA(3, -5, -4, num_threads=2, use_fused=False, **kw)
    rs, ss = split.consensus([list(p) for p in packed])
    fused = FusedPOA(3, -5, -4, num_threads=2, use_fused=True, **kw)
    rf, sf = fused.consensus([list(p) for p in packed])

    np.testing.assert_array_equal(ss, sf)
    assert (sf == 0).all(), sf.tolist()
    _assert_identical(rf, rs, sf, "fused-vs-split")
    host = poa_batch(packed, 3, -5, -4, n_threads=2)
    _assert_identical(rf, host, sf, "fused-vs-host")
    # the fusion receipt: one launch per chunk instead of one per
    # chained chain bucket
    assert fused.last_stats["fused_chunks"] >= 1
    assert fused.last_stats["fused_fallbacks"] == 0
    assert fused.last_stats["launches"] < split.last_stats["launches"]


def test_fused_loop_fault_falls_back_to_split_byte_identically():
    """A fault injected at ANY stage of a fused single-launch chunk
    must fall back to the SPLIT chained path — the declared fallback —
    with byte-identical output (the host tail may resolve topo ties
    differently, so falling past split would move bytes under a
    fault)."""
    from racon_tpu.pipeline import DispatchPipeline
    from racon_tpu.resilience import FaultPlan

    rng = random.Random(5)
    windows, _ = _make_windows(rng, 6, length=220, depth=11, rate=0.12)
    packed = [_pack(w) for w in windows]
    kw = dict(max_nodes=768, max_len=384, batch_rows=8,
              depth_buckets=(4, 8))
    ref = FusedPOA(3, -5, -4, use_fused=True, **kw)
    rr, sr = ref.consensus([list(p) for p in packed])

    for stage in ("pack", "device", "unpack"):
        eng = FusedPOA(3, -5, -4, use_fused=True, **kw)
        pl = DispatchPipeline(
            depth=0, faults=FaultPlan.parse(f"{stage}:chunk=0:raise"))
        rf, sf = eng.consensus([list(p) for p in packed], pipeline=pl)
        assert eng.last_stats["fused_fallbacks"] == 1, \
            (stage, eng.last_stats)
        assert pl.stats.snapshot()["faults"] >= 1
        np.testing.assert_array_equal(sr, sf, err_msg=stage)
        _assert_identical(rf, rr, sf, f"fault-{stage}")


def test_fused_loop_auto_follows_winner_table(tmp_path, monkeypatch):
    """RACON_TPU_FUSED=auto consults the persisted autotuner winner
    table per depth bucket (engine "fused_loop"): a cold table
    dispatches the split path exactly as before; a measured fused
    winner flips the SAME construction to the single-launch program."""
    from racon_tpu.sched.autotune import (get_autotuner,
                                          reset_autotuner_cache)

    monkeypatch.setenv("RACON_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.setenv("RACON_TPU_FUSED", "auto")
    reset_autotuner_cache()
    rng = random.Random(9)
    windows, _ = _make_windows(rng, 4, length=220, depth=11, rate=0.1)
    packed = [_pack(w) for w in windows]
    kw = dict(max_nodes=768, max_len=384, batch_rows=4,
              depth_buckets=(4,))

    cold = FusedPOA(3, -5, -4, **kw)
    rs, _ = cold.consensus([list(p) for p in packed])
    assert cold.last_stats["fused_chunks"] == 0  # cold table: split

    at = get_autotuner()
    # depth 11 with buckets (4,): plan [4, 4, 4] -> consult key d=4
    at.record("fused_loop", (768, 384, 4), (3, -5, -4, cold.P),
              {"kernel": "fused", "dtype": "int32", "ms": {},
               "identical": True})
    at.save()
    reset_autotuner_cache()
    warm = FusedPOA(3, -5, -4, **kw)
    rf, sf = warm.consensus([list(p) for p in packed])
    assert warm.last_stats["fused_chunks"] >= 1
    _assert_identical(rf, rs, sf, "auto-vs-cold")
    reset_autotuner_cache()


def test_fused_state_buffers_never_reused_after_donation(monkeypatch):
    """The donation contract (fused_builder donates the 11 state
    buffers on accelerators, nothing on the CPU test backend — which
    silently ignores donation and would mask a reuse bug): across
    chained split calls AND the fused single-launch path, no state
    tuple is ever handed to a device call twice — a donated-then-reused
    buffer would read back garbage on chip. Plus the config pin on both
    backend branches."""
    import jax

    import racon_tpu.ops.poa_fused as pf

    # ---- config pin: what the builder asks jit to donate, per backend
    captured = {}
    real_jit = jax.jit

    def spy_jit(fn, **kw):
        captured["donate"] = kw.get("donate_argnums", ())
        return real_jit(fn, **kw)

    monkeypatch.setattr(jax, "jit", spy_jit)
    # unique shapes so the lru caches cannot serve a pre-spy build
    pf.fused_builder(48, 24, 2, 2, 1, -1, -1)
    assert captured["donate"] == ()  # cpu cannot donate (would warn)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    pf.fused_builder(48, 24, 3, 2, 1, -1, -1, device_slice=True)
    assert captured["donate"] == tuple(range(11))
    monkeypatch.undo()

    # ---- behavioral pin: every state tuple enters a device call once
    donated: list = []  # holds refs so object identity stays unique

    def mark(state):
        for a in state:
            assert not any(a is o for o in donated), \
                "donated state buffer passed to a device call twice"
        donated.extend(state)

    orig_call = pf.FusedPOA._call
    orig_fused = pf.FusedPOA._call_fused

    def spy_call(self, d, state, *rest):
        mark(state)
        return orig_call(self, d, state, *rest)

    def spy_fused(self, D, state, *rest):
        mark(state)
        return orig_fused(self, D, state, *rest)

    monkeypatch.setattr(pf.FusedPOA, "_call", spy_call)
    monkeypatch.setattr(pf.FusedPOA, "_call_fused", spy_fused)

    rng = random.Random(9)
    windows, _ = _make_windows(rng, 4, length=220, depth=11, rate=0.1)
    packed = [_pack(w) for w in windows]
    host = poa_batch(packed, 3, -5, -4)
    kw = dict(max_nodes=768, max_len=384, batch_rows=4,
              depth_buckets=(4,))  # 11 layers -> 3 chained calls
    for use_fused in (False, True):
        eng = FusedPOA(3, -5, -4, use_fused=use_fused, **kw)
        res, st = eng.consensus([list(p) for p in packed])
        assert (st == 0).all()
        _assert_identical(res, host, st, f"donation fused={use_fused}")
    assert len(donated) >= 11 * 2  # both paths actually ran


def test_polisher_fasta_identical_across_fused_dispatch_modes(
        tmp_path, monkeypatch):
    """THE fused-dispatch acceptance pin: polished FASTA byte-identical
    across RACON_TPU_FUSED={0,1,auto} x pipeline depth {0,2} x engine
    {session,fused} x mesh {1,8}. The fused single-launch program may
    move every perf number; it may not move one output byte. `auto` is
    covered both cold (no table -> dispatches split) and with a forced
    all-fused winner table (the most aggressive posture it can take)."""
    from test_pipeline import _synth_dataset

    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.sched import autotune
    from racon_tpu.sched.autotune import (get_autotuner,
                                          reset_autotuner_cache)

    monkeypatch.setenv("RACON_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    reset_autotuner_cache()
    paths = [str(x) for x in _synth_dataset(tmp_path, random.Random(23))]

    class _FusedTable:
        def winner(self, engine, bucket, params=()):
            if engine == "fused_loop":
                return {"kernel": "fused", "dtype": "int32", "ms": {},
                        "identical": True}
            return None

    def run(engine, fused, depth, mesh, forced_table=False):
        monkeypatch.setenv("RACON_TPU_MAX_DEVICES", str(mesh))
        monkeypatch.setenv("RACON_TPU_FUSED", fused)
        monkeypatch.setattr(
            autotune, "get_autotuner",
            (lambda: _FusedTable()) if forced_table else get_autotuner)
        p = create_polisher(*paths, PolisherType.kC, 500, -1.0, 0.3,
                            num_threads=2, tpu_poa_batches=1,
                            tpu_engine=engine, tpu_pipeline_depth=depth)
        p.initialize()
        return [(s.name, s.data) for s in p.polish()]

    ref = run("fused", "0", 0, 1)
    assert ref and all(d for _, d in ref)
    for fused, depth, mesh, forced in (("1", 0, 1, False),
                                       ("1", 2, 1, False),
                                       ("auto", 2, 1, True),
                                       ("auto", 0, 1, False),
                                       ("1", 2, 8, False)):
        assert run("fused", fused, depth, mesh, forced) == ref, \
            f"FASTA diverged at fused={fused} depth={depth} mesh={mesh}"
    # the session engine ignores the knob entirely
    s_ref = run("session", "0", 2, 1)
    assert run("session", "1", 2, 1) == s_ref
    reset_autotuner_cache()


def test_fused_through_batchpoa_env(monkeypatch):
    """RACON_TPU_ENGINE=fused routes BatchPOA's device path through the
    fused engine end-to-end."""
    from racon_tpu.native import edit_distance
    from racon_tpu.ops.poa import BatchPOA

    monkeypatch.setenv("RACON_TPU_ENGINE", "fused")
    rng = random.Random(8)
    windows, truths = _make_windows(rng, 4, length=220, depth=6, rate=0.1)
    engine = BatchPOA(3, -5, -4, 220, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    for w, truth in zip(windows, truths):
        assert w.polished
        assert edit_distance(w.consensus, truth) <= \
            edit_distance(w.sequences[0], truth)


def test_fused_fallback_host_env(monkeypatch, capsys):
    """RACON_TPU_FUSED_FALLBACK=host polishes fused-ineligible windows on
    the C++ engine (the reference's per-window GPU->CPU fallback,
    cudapolisher.cpp:354-383) instead of the session engine — output still
    byte-identical to a pure host run. STRICT so a broken fused path
    fails instead of silently host-polishing everything."""
    from racon_tpu.ops import poa_fused
    from racon_tpu.ops.poa import BatchPOA

    monkeypatch.setenv("RACON_TPU_ENGINE", "fused")
    monkeypatch.setenv("RACON_TPU_FUSED_FALLBACK", "host")
    monkeypatch.setenv("RACON_TPU_STRICT", "1")

    class SmallFused(poa_fused.FusedPOA):  # shrink the envelope so some
        def __init__(self, *a, **kw):      # windows are fused-ineligible
            kw.update(max_nodes=230, max_len=384, batch_rows=4,
                      depth_buckets=(8,))
            super().__init__(*a, **kw)

    monkeypatch.setattr(poa_fused, "FusedPOA", SmallFused)
    rng = random.Random(13)
    windows, _ = _make_windows(rng, 4, length=220, depth=5, rate=0.1)
    host = poa_batch([_pack(w) for w in windows], 3, -5, -4)

    engine = BatchPOA(3, -5, -4, 220, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    err = capsys.readouterr().err
    # prove the branch ran AND fell back: the engine report names the
    # host engine with a nonzero count
    import re

    m = re.search(r"fused engine built \d+ windows.*; (\d+) to host engine",
                  err)
    assert m is not None, err
    assert int(m.group(1)) >= 1
    for w, (hc, _) in zip(windows, host):
        assert w.polished
        assert w.consensus == hc
