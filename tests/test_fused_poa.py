"""Fused whole-window device POA engine tests (ops/poa_fused.py).

The engine builds complete POA graphs on device in ONE call per window
batch (the cudapoa single-launch shape, reference cudabatch.cpp:77-270).
The correctness bar mirrors the session engine's: consensus byte-identical
to the host engine on clean data (asserted here), per-window host fallback
for anything outside the envelope.
"""

import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack, mutate  # noqa: E402

from racon_tpu.native import poa_batch  # noqa: E402
from racon_tpu.ops.poa_fused import FusedPOA  # noqa: E402

ACGT = b"ACGT"


def _assert_identical(res, host, statuses, where=""):
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"{where} window {i} consensus diverged " \
                         f"(status {int(statuses[i])})"
        np.testing.assert_array_equal(dcov, hcov, err_msg=f"window {i}")


def test_fused_byte_identical_to_host():
    """Spanning TGS-style windows, incl. a rotated adversarial layer: the
    fused engine's consensus must equal the host engine's byte-for-byte."""
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    bb = windows[0].sequences[0]
    windows[0].add_layer(bb[110:] + bb[:110], None, 0, len(bb) - 1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, num_threads=2, max_nodes=768, max_len=384,
                   batch_rows=8, depth_buckets=(4, 8))
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4, n_threads=2)

    assert (statuses == 0).all(), statuses.tolist()
    assert eng.n_fallback == 0
    _assert_identical(res, host, statuses)


def test_fused_band_clip_retry_byte_identical_to_host():
    """A layer whose only true match region lies OUTSIDE its band (and
    whose filler can match nothing in-band) trips the host's
    band_clipped rule and is redone with the exact full DP — the fused
    engine must replicate that retry, staying byte-identical. The
    session engine's redo counter proves the construction really clips
    (the rule is a safety net: random-DNA soup usually still weaves
    enough coincidental matches to pass it)."""
    from racon_tpu.core.window import Window, WindowType
    from racon_tpu.ops.poa_graph import DeviceGraphPOA

    rng = random.Random(79)
    windows = []
    for _ in range(3):
        R = bytes(rng.choice(ACGT) for _ in range(100))
        bb = b"A" * 300 + R  # the match region sits 300 bp off-diagonal
        w = Window(0, 0, WindowType.kTGS, bb, b"!" * len(bb))
        for _ in range(2):
            lay = mutate(rng, R, 0.03) + b"C" * 250  # C's match nothing
            w.add_layer(lay, None, 0, len(bb) - 1)
        windows.append(w)
    packed = [_pack(w) for w in windows]

    # non-vacuity: the host-identical session engine really does retry
    sess = DeviceGraphPOA(5, -4, -8, max_nodes=1024, max_len=640,
                          buckets=((1024, 640),), batch_rows=4)
    sess.consensus(packed)
    assert sess.last_stats["redos"] >= 3, sess.last_stats

    host = poa_batch(packed, 5, -4, -8)
    eng = FusedPOA(5, -4, -8, max_nodes=1024, max_len=640, batch_rows=4,
                   depth_buckets=(8,))
    res, statuses = eng.consensus(packed)
    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "band-clip")

    # (-b / banded_only is NOT asserted here: on this construction the
    # heaviest-bundle consensus is identical with and without the retry
    # — measured — so a banded-only run cannot be told apart by output;
    # the flag's behavior is covered by the session engine's
    # test_banded_only_mode_skips_retry and the builder keys on it.)


@pytest.mark.skipif(not os.path.isdir("/root/reference/test/data"),
                    reason="reference sample data not available")
def test_fused_real_sample_slice_identity_pinned(monkeypatch):
    """Default-suite regression guard for the fused engine's REAL-DATA
    behavior (round-4 verdict: the strongest contracts must not live only
    behind RACON_TPU_FULL_GOLDENS): on the 24 shallowest real windows of
    the lambda sample, ALL build on device, every consensus is
    byte-identical to the host engine, and coverages match exactly on
    >= 23/24 — the measured state is ONE window (depth 17) whose final
    two coverage values are transposed (17,16 vs 16,17): a
    heaviest-bundle tie at the consensus tail resolved differently by
    the argsort-key topo order, same bases and same total coverage. Any
    byte divergence, a second coverage-divergent window, or a
    non-permutation coverage change fails the pin."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    D = "/root/reference/test/data/"
    p = create_polisher(D + "sample_reads.fastq.gz",
                        D + "sample_overlaps.paf.gz",
                        D + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = sorted((w for w in p.windows if len(w.sequences) >= 3),
                  key=lambda w: len(w.sequences))[:24]
    assert len(wins) == 24
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in wins]
    host = poa_batch(packed, 5, -4, -8, n_threads=2)
    eng = FusedPOA(5, -4, -8, num_threads=2, batch_rows=8)
    res, statuses = eng.consensus(packed, fallback=False)
    assert (statuses == 0).all(), \
        "every shallow window must build on device"
    cov_diverged = []
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"window {i} consensus bytes diverged"
        if not np.array_equal(dcov, hcov):
            # tie-class divergence only: same multiset of coverages
            assert sorted(np.asarray(dcov).tolist()) == \
                sorted(np.asarray(hcov).tolist()), \
                f"window {i} coverage changed beyond a tie permutation"
            cov_diverged.append(i)
    assert len(cov_diverged) <= 1, \
        f"coverage tie-divergence grew: windows {cov_diverged}"


@pytest.mark.skipif(not os.environ.get("RACON_TPU_FULL_GOLDENS")
                    or not os.path.isdir("/root/reference/test/data"),
                    reason="minutes-long real-data fixture")
def test_fused_real_sample_window_identity_pinned():
    """The fused engine's real-data contract, pinned at its measured
    values: on the lambda sample's 96 windows, >= 95 are byte-identical
    to the host engine, and any divergent window's consensus stays
    within edit distance 4 of the host's (measured: one window at
    distance 3 — a topo-order tie, not a quality regression). A drop
    below 95/96 or a bigger per-window distance means a real tie-order
    or DP change, not noise."""
    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.native import edit_distance

    D = "/root/reference/test/data/"
    p = create_polisher(D + "sample_reads.fastq.gz",
                        D + "sample_overlaps.paf.gz",
                        D + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = [w for w in p.windows if len(w.sequences) >= 3]
    assert len(wins) == 96  # the denominator the pins below assume
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in wins]
    host = poa_batch(packed, 5, -4, -8)
    eng = FusedPOA(5, -4, -8, num_threads=2, batch_rows=16)
    res, statuses = eng.consensus(packed, fallback=False)
    assert (statuses == 0).all(), "every window must build on device"
    diverged = [i for i, (r, h) in enumerate(zip(res, host))
                if r[0] != h[0]]
    assert len(diverged) <= 1, \
        f"{len(diverged)}/96 windows diverged from host: {diverged}"
    for i in diverged:
        d = edit_distance(res[i][0], host[i][0])
        assert d <= 4, f"window {i} diverged by distance {d}"


def test_fused_sharded_matches_single_device(monkeypatch):
    """The fused engine's batch axis shards over the mesh (conftest's
    8-virtual-device CPU mesh) through BatchRunner/shard_map — the
    multi-chip analogue of the reference's batch-per-GPU loop
    (cudapolisher.cpp:228-240). Sharded output must equal the
    single-device output window-for-window, including chained calls."""
    rng = random.Random(21)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    packed = [_pack(w) for w in windows]
    kw = dict(max_nodes=768, max_len=384, batch_rows=8,
              depth_buckets=(4,))  # depth 7 -> 2 chained calls

    multi = FusedPOA(3, -5, -4, **kw)
    assert multi.runner.n_devices > 1, \
        "conftest should provide an 8-virtual-device mesh"
    res_m, st_m = multi.consensus([list(p) for p in packed])

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    one = FusedPOA(3, -5, -4, **kw)
    assert one.runner.n_devices == 1
    res_s, st_s = one.consensus([list(p) for p in packed])

    np.testing.assert_array_equal(st_m, st_s)
    assert (st_m == 0).all(), st_m.tolist()
    _assert_identical(res_m, res_s, st_m, "sharded-vs-single")


def test_fused_deep_windows_chain_calls():
    """Depth beyond the largest bucket chains device calls (state streams
    out of one call into the next); output must still match the host."""
    rng = random.Random(9)
    windows, _ = _make_windows(rng, 4, length=220, depth=11, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=768, max_len=384, batch_rows=4,
                   depth_buckets=(4,))  # 11 layers -> 3 chained calls
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "chained")


def test_fused_non_spanning_layers_use_range_subgraph():
    """Non-spanning layers align against the bpos-range-masked subgraph
    on device (the host's Graph::subgraph semantics, with the host's
    begin-sorted layer order and banded DP): output must equal the host
    engine's byte-for-byte."""
    rng = random.Random(12)
    windows, _ = _make_windows(rng, 6, length=110, depth=5,
                               spanning=False, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=512, max_len=256, batch_rows=8,
                   depth_buckets=(8,))
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert (statuses == 0).all(), statuses.tolist()
    _assert_identical(res, host, statuses, "subrange")


def test_fused_envelope_overflow_falls_back_to_host():
    """Graphs that outgrow the node envelope must host-fallback per
    window — and the final output is still identical to the host engine
    for every window."""
    rng = random.Random(6)
    windows, _ = _make_windows(rng, 3, length=220, depth=5, rate=0.1)
    packed = [_pack(w) for w in windows]

    eng = FusedPOA(3, -5, -4, max_nodes=230, max_len=384, batch_rows=4,
                   depth_buckets=(8,))  # 230 nodes: graphs overflow fast
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)

    assert eng.n_fallback >= 1
    _assert_identical(res, host, statuses, "fallback")


def test_fused_backbone_only_windows():
    rng = random.Random(7)
    windows, _ = _make_windows(rng, 1, length=220, depth=4)
    packed = [_pack(windows[0]), [(b"ACGTACGT" * 30, None, 0, 239)]]
    eng = FusedPOA(3, -5, -4, max_nodes=768, max_len=384, batch_rows=4,
                   depth_buckets=(4,))
    res, statuses = eng.consensus(packed)
    assert statuses[1] == 2
    assert res[1][0] == packed[1][0][0]


def test_fused_through_batchpoa_env(monkeypatch):
    """RACON_TPU_ENGINE=fused routes BatchPOA's device path through the
    fused engine end-to-end."""
    from racon_tpu.native import edit_distance
    from racon_tpu.ops.poa import BatchPOA

    monkeypatch.setenv("RACON_TPU_ENGINE", "fused")
    rng = random.Random(8)
    windows, truths = _make_windows(rng, 4, length=220, depth=6, rate=0.1)
    engine = BatchPOA(3, -5, -4, 220, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    for w, truth in zip(windows, truths):
        assert w.polished
        assert edit_distance(w.consensus, truth) <= \
            edit_distance(w.sequences[0], truth)


def test_fused_fallback_host_env(monkeypatch, capsys):
    """RACON_TPU_FUSED_FALLBACK=host polishes fused-ineligible windows on
    the C++ engine (the reference's per-window GPU->CPU fallback,
    cudapolisher.cpp:354-383) instead of the session engine — output still
    byte-identical to a pure host run. STRICT so a broken fused path
    fails instead of silently host-polishing everything."""
    from racon_tpu.ops import poa_fused
    from racon_tpu.ops.poa import BatchPOA

    monkeypatch.setenv("RACON_TPU_ENGINE", "fused")
    monkeypatch.setenv("RACON_TPU_FUSED_FALLBACK", "host")
    monkeypatch.setenv("RACON_TPU_STRICT", "1")

    class SmallFused(poa_fused.FusedPOA):  # shrink the envelope so some
        def __init__(self, *a, **kw):      # windows are fused-ineligible
            kw.update(max_nodes=230, max_len=384, batch_rows=4,
                      depth_buckets=(8,))
            super().__init__(*a, **kw)

    monkeypatch.setattr(poa_fused, "FusedPOA", SmallFused)
    rng = random.Random(13)
    windows, _ = _make_windows(rng, 4, length=220, depth=5, rate=0.1)
    host = poa_batch([_pack(w) for w in windows], 3, -5, -4)

    engine = BatchPOA(3, -5, -4, 220, device_batches=1)
    engine.generate_consensus(windows, trim=False)
    err = capsys.readouterr().err
    # prove the branch ran AND fell back: the engine report names the
    # host engine with a nonzero count
    import re

    m = re.search(r"fused engine built \d+ windows.*; (\d+) to host engine",
                  err)
    assert m is not None, err
    assert int(m.group(1)) >= 1
    for w, (hc, _) in zip(windows, host):
        assert w.polished
        assert w.consensus == hc
