"""Golden end-to-end fixtures.

Ports the reference's ten RaconPolishingTest integration tests
(/root/reference/test/racon_test.cpp:88-290) plus the factory validation
tests (racon_test.cpp:55-86). Each fixture runs the full pipeline on the
packaged lambda-phage sample data and asserts consensus quality.

The reference pins exact per-backend values (CPU vs CUDA differ:
e.g. 1312 vs 1385 for the first fixture, racon_test.cpp:107,312) — numeric
divergence between engines is accepted, each pinned separately. This
implementation is pinned the same way: every fixture asserts THIS
implementation's measured value exactly (both engines produce the same
bytes, so one pin covers both; tools/measure_fixtures.py regenerates the
numbers after an intentional algorithm change). Reference CPU/GPU values
are noted inline for comparison.
"""

import os

import pytest

from racon_tpu.core.polisher import create_polisher, PolisherType
from racon_tpu.errors import RaconError
from racon_tpu.io.parsers import create_sequence_parser
from racon_tpu.native import edit_distance


@pytest.fixture(autouse=True)
def _one_device_mesh(monkeypatch):
    # real-data identity fixtures exercise the production envelope, not
    # sharding (dedicated sharded tests cover that at small shapes) — on
    # the 8-virtual-device CPU test mesh every shard re-runs the
    # sequential DP, so pin this heavyweight module to one device
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")


DATA = "/root/reference/test/data/"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(DATA), reason="reference sample data not available")


def run_pipeline(reads, overlaps, target, type_=PolisherType.kC,
                 window_length=500, quality_threshold=10.0,
                 error_threshold=0.3, match=5, mismatch=-4, gap=-8,
                 drop_unpolished=True):
    polisher = create_polisher(
        DATA + reads, DATA + overlaps, DATA + target, type_, window_length,
        quality_threshold, error_threshold, True, match, mismatch, gap,
        num_threads=4)
    polisher.initialize()
    return polisher.polish(drop_unpolished)


def reference_distance(polished):
    """Edit distance of the polished contig (reverse-complemented, as in
    racon_test.cpp:104-109) against the curated reference assembly."""
    ref = []
    create_sequence_parser(DATA + "sample_reference.fasta.gz",
                           "test").parse(ref, -1)
    return edit_distance(polished.reverse_complement, ref[0].data)


# -- factory validation (racon_test.cpp:55-86) ----------------------------

def test_polisher_type_error():
    with pytest.raises(RaconError, match="invalid polisher type"):
        create_polisher("", "", "", 3, 0, 0, 0)


def test_window_length_error():
    with pytest.raises(RaconError, match="invalid window length"):
        create_polisher("", "", "", PolisherType.kC, 0, 0, 0)


def test_sequences_path_extension_error():
    with pytest.raises(RaconError, match="unsupported format extension"):
        create_polisher("", "", "", PolisherType.kC, 500, 0, 0)


def test_overlaps_path_extension_error():
    with pytest.raises(RaconError, match="unsupported format extension"):
        create_polisher(DATA + "sample_reads.fastq.gz", "", "",
                        PolisherType.kC, 500, 0, 0)


def test_target_path_extension_error():
    with pytest.raises(RaconError, match="unsupported format extension"):
        create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.paf.gz", "",
                        PolisherType.kC, 500, 0, 0)


# -- contig polishing goldens (racon_test.cpp:88-218) ---------------------
# pins: THIS implementation's measured value, exact

def test_consensus_with_qualities():
    # reference: CPU 1312 / GPU 1385 (racon_test.cpp:107,312)
    polished = run_pipeline("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                            "sample_layout.fasta.gz")
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1352


def test_consensus_without_qualities():
    # reference: CPU 1566 / GPU 1607 (racon_test.cpp:129,334)
    polished = run_pipeline("sample_reads.fasta.gz", "sample_overlaps.paf.gz",
                            "sample_layout.fasta.gz")
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1530


def test_consensus_with_qualities_and_alignments():
    # reference: CPU 1317 / GPU 1541 (racon_test.cpp:151,356)
    polished = run_pipeline("sample_reads.fastq.gz", "sample_overlaps.sam.gz",
                            "sample_layout.fasta.gz")
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1358


def test_consensus_without_qualities_and_with_alignments():
    # reference: CPU 1770 / GPU 1661 (racon_test.cpp:173,378); ~5% behind
    # the reference CPU engine on this one fixture
    polished = run_pipeline("sample_reads.fasta.gz", "sample_overlaps.sam.gz",
                            "sample_layout.fasta.gz")
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1859


def test_consensus_with_qualities_larger_window():
    # reference: CPU 1289 / GPU 4168 (racon_test.cpp:195,400)
    polished = run_pipeline("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                            "sample_layout.fasta.gz", window_length=1000)
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1353


def test_consensus_with_qualities_edit_distance():
    # unit scores m=1 x=-1 g=-1; reference: CPU 1321 / GPU 1361
    # (racon_test.cpp:217,422)
    polished = run_pipeline("sample_reads.fastq.gz", "sample_overlaps.paf.gz",
                            "sample_layout.fasta.gz",
                            match=1, mismatch=-1, gap=-1)
    assert len(polished) == 1
    assert reference_distance(polished[0]) == 1331


# -- fragment correction goldens (racon_test.cpp:220-290) -----------------

def total_length(polished):
    return sum(len(s.data) for s in polished)


def test_fragment_correction_with_qualities():
    # kC on all-vs-all overlaps; reference: 39 seqs, 389394 bp (CPU) /
    # 385543 (GPU) (racon_test.cpp:229-235,434-440)
    polished = run_pipeline("sample_reads.fastq.gz",
                            "sample_ava_overlaps.paf.gz",
                            "sample_reads.fastq.gz",
                            match=1, mismatch=-1, gap=-1)
    assert len(polished) == 39
    assert total_length(polished) == 389340


def test_fragment_correction_with_qualities_full():
    # reference: 236 seqs, 1658216 bp (CPU) / 1655505 (GPU)
    polished = run_pipeline("sample_reads.fastq.gz",
                            "sample_ava_overlaps.paf.gz",
                            "sample_reads.fastq.gz", type_=PolisherType.kF,
                            match=1, mismatch=-1, gap=-1,
                            drop_unpolished=False)
    assert len(polished) == 236
    assert total_length(polished) == 1658859


# -- whole-output golden diff (ci/gpu/cuda_test.sh:30-44 analogue) --------
# the committed file is regenerated only by tools/make_golden.py; both
# engines must reproduce it byte-for-byte

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "sample_golden.fasta")


def polished_fasta_bytes(device_batches=0):
    polisher = create_polisher(
        DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.paf.gz",
        DATA + "sample_layout.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
        True, 5, -4, -8, num_threads=4, tpu_poa_batches=device_batches)
    polisher.initialize()
    out = bytearray()
    for seq in polisher.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return bytes(out)


def test_golden_output_exact_diff_host():
    with open(GOLDEN, "rb") as fh:
        golden = fh.read()
    assert polished_fasta_bytes() == golden


full_goldens = pytest.mark.skipif(
    not os.environ.get("RACON_TPU_FULL_GOLDENS"),
    reason="several-minute fixture; set RACON_TPU_FULL_GOLDENS=1 to run "
           "(verified passing; kept out of the default suite for speed)")


@full_goldens
def test_synth_genome_golden_exact_diff():
    """Whole-genome-scale golden: a deterministic 50 kb synthetic ONT
    workload (tools/synthbench.py, seed 42) must reproduce the committed
    polished FASTA byte-for-byte — the scale analogue of the reference's
    5.2 MB CI golden (ci/gpu/cuda_test.sh:30-44)."""
    import subprocess
    import sys
    import tempfile

    golden_path = os.path.join(os.path.dirname(__file__), "data",
                               "synth_50kb_golden.fasta")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.NamedTemporaryFile(suffix=".fasta") as tmp:
        subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "synthbench.py"),
             "--genome-kb", "50", "--coverage", "20", "--seed", "42",
             "--golden-out", tmp.name],
            check=True, capture_output=True, cwd=repo)
        with open(tmp.name, "rb") as fh:
            got = fh.read()
    with open(golden_path, "rb") as fh:
        assert got == fh.read()


@full_goldens
def test_golden_output_exact_diff_device(monkeypatch, capsys):
    # the device engine must hit the SAME golden (byte-identity design);
    # the default suite covers this via
    # test_determinism.py::test_device_output_matches_host_bytes — this
    # variant additionally diffs the PAF path against the committed file.
    # STRICT catches whole-engine device failures; per-window host
    # fallbacks (status 1) don't raise, so additionally assert the
    # engine's fallback report never appeared — every window really was
    # polished on device
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    with open(GOLDEN, "rb") as fh:
        golden = fh.read()
    out = polished_fasta_bytes(device_batches=1)
    assert "windows polished on host" not in capsys.readouterr().err
    assert out == golden


@full_goldens
def test_fragment_correction_without_qualities_full():
    # reference: 236 seqs, 1663982 bp (CPU) / 1663732 (GPU)
    polished = run_pipeline("sample_reads.fasta.gz",
                            "sample_ava_overlaps.paf.gz",
                            "sample_reads.fasta.gz", type_=PolisherType.kF,
                            match=1, mismatch=-1, gap=-1,
                            drop_unpolished=False)
    assert len(polished) == 236
    assert total_length(polished) == 1664167


@full_goldens
def test_fragment_correction_with_qualities_full_mhap():
    # reference: 236 seqs, 1658216 bp (CPU) / 1655505 (GPU); must equal the
    # PAF fixture's value exactly, as in the reference
    polished = run_pipeline("sample_reads.fastq.gz",
                            "sample_ava_overlaps.mhap.gz",
                            "sample_reads.fastq.gz", type_=PolisherType.kF,
                            match=1, mismatch=-1, gap=-1,
                            drop_unpolished=False)
    assert len(polished) == 236
    assert total_length(polished) == 1658859
