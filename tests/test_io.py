import gzip

import pytest

from racon_tpu import RaconError
from racon_tpu.io import (FastaParser, FastqParser, MhapParser, PafParser,
                          SamParser, create_overlap_parser,
                          create_sequence_parser)


def _write(tmp_path, name, content, gz=False):
    p = tmp_path / name
    data = content if isinstance(content, bytes) else content.encode()
    if gz:
        p.write_bytes(gzip.compress(data))
    else:
        p.write_bytes(data)
    return str(p)


def test_fasta_multiline_and_name_token(tmp_path):
    path = _write(tmp_path, "x.fasta", ">r1 extra comment\nACGT\nacgt\n>r2\nTTTT\n")
    p = FastaParser(path)
    dst = []
    assert p.parse(dst, -1) is False
    assert [s.name for s in dst] == ["r1", "r2"]
    assert dst[0].data == b"ACGTACGT"


def test_fasta_gzip_sniffed(tmp_path):
    path = _write(tmp_path, "x.fa.gz", ">r1\nAC\n", gz=True)
    dst = []
    FastaParser(path).parse(dst)
    assert dst[0].data == b"AC"


def test_fasta_chunked_parse(tmp_path):
    recs = "".join(f">r{i}\n{'ACGT' * 100}\n" for i in range(10))
    path = _write(tmp_path, "x.fasta", recs)
    p = FastaParser(path)
    dst = []
    more = p.parse(dst, 800)  # ~2 records per call
    assert more is True
    assert 1 <= len(dst) <= 3
    while more:
        more = p.parse(dst, 800)
    assert len(dst) == 10


def test_fastq(tmp_path):
    path = _write(tmp_path, "x.fastq", "@r1 d\nACGT\n+\n##!#\n@r2\nGG\n+\n!!\n")
    dst = []
    FastqParser(path).parse(dst)
    assert dst[0].quality == b"##!#"
    assert dst[1].quality == b""  # all-zero quality dropped


def test_paf(tmp_path):
    line = "q1\t100\t5\t95\t-\tt1\t500\t10\t105\t80\t95\t60\tcg:Z:90M\n"
    path = _write(tmp_path, "x.paf", line)
    dst = []
    PafParser(path).parse(dst)
    o = dst[0]
    assert o.q_name == "q1" and o.t_name == "t1" and o.strand


def test_mhap(tmp_path):
    line = "1 2 0.1 42 0 5 95 100 1 10 105 500\n"
    path = _write(tmp_path, "x.mhap", line)
    dst = []
    MhapParser(path).parse(dst)
    o = dst[0]
    assert o.q_id == 0 and o.t_id == 1 and o.strand


def test_sam_skips_header(tmp_path):
    content = "@SQ\tSN:t1\tLN:500\nq1\t0\tt1\t10\t60\t4M\t*\t0\t0\tACGT\t####\n"
    path = _write(tmp_path, "x.sam", content)
    dst = []
    SamParser(path).parse(dst)
    assert len(dst) == 1
    assert dst[0].t_begin == 9


def test_extension_validation():
    with pytest.raises(RaconError, match="unsupported format extension"):
        create_sequence_parser("x.txt", "createPolisher")
    with pytest.raises(RaconError, match="unsupported format extension"):
        create_overlap_parser("x.txt", "createPolisher")


def test_reference_sample_data_parses(reference_data):
    dst = []
    FastqParser(str(reference_data / "sample_reads.fastq.gz")).parse(dst)
    assert len(dst) > 0
    assert all(s.quality for s in dst) or True
    total = sum(len(s.data) for s in dst)
    assert total > 100_000

    ovl = []
    PafParser(str(reference_data / "sample_overlaps.paf.gz")).parse(ovl)
    assert len(ovl) > 0

    sam = []
    SamParser(str(reference_data / "sample_overlaps.sam.gz")).parse(sam)
    assert len(sam) > 0

    mhap = []
    MhapParser(str(reference_data / "sample_ava_overlaps.mhap.gz")).parse(mhap)
    assert len(mhap) > 0


def test_truncated_gzip_raises(tmp_path):
    """A gzip stream cut mid-file must raise, not silently yield a shorter
    read set (interrupted downloads are common; the native loader checks
    gzeof before treating a short read as EOF)."""
    import gzip as _gzip

    p = tmp_path / "reads.fastq.gz"
    with _gzip.open(p, "wb") as f:
        for i in range(200):
            f.write(b"@r%d\nACGTACGTAC\n+\nIIIIIIIIII\n" % i)
    data = p.read_bytes()
    trunc = tmp_path / "trunc.fastq.gz"
    trunc.write_bytes(data[:len(data) // 2])
    with pytest.raises(RaconError, match="malformed FASTQ"):
        out = []
        create_sequence_parser(str(trunc), "test").parse(out, -1)
