"""Pod-scale dispatch tests: sub-mesh runners, lane partitioning,
device-aware iteration packing, per-shard occupancy telemetry, and the
mesh-size byte-identity acceptance pin.

The conftest forces an 8-virtual-device CPU mesh, so every multi-device
path here runs the REAL sharded code without hardware (the same posture
as __graft_entry__.dryrun_multichip)."""

from __future__ import annotations

import numpy as np
import pytest

from racon_tpu.parallel.mesh import BatchRunner, partition_devices


def _devices(n):
    import jax

    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"need {n} virtual devices, have {len(devs)}")
    return devs[:n]


# ---------------------------------------------------------- partitioning
def test_partition_devices_contiguous_and_balanced():
    devs = list(range(8))
    assert partition_devices(devs, 2) == [[0, 1, 2, 3], [4, 5, 6, 7]]
    groups = partition_devices(devs, 3)
    assert [len(g) for g in groups] == [3, 3, 2]
    assert sum(groups, []) == devs  # contiguous, order-preserving
    # k clamps to the device count; k=1 is the whole list
    assert partition_devices(devs, 99) == [[d] for d in devs]
    assert partition_devices(devs, 1) == [devs]


def test_partition_devices_global_list_seam(monkeypatch):
    """The multi-host prep seam: an explicit (global) device list is
    partitioned as given — lanes can span hosts — and devices=None
    auto-discovers jax.devices() under the RACON_TPU_MAX_DEVICES cap,
    matching BatchRunner's discovery exactly."""
    import jax

    # explicit global list: partitioned verbatim, no local filtering —
    # host-contiguity is the CALLER's ordering, preserved here
    global_devs = [("host0", i) for i in range(4)] \
        + [("host1", i) for i in range(4)]
    lanes = partition_devices(global_devs, 2)
    assert lanes == [global_devs[:4], global_devs[4:]]

    # devices=None: the process-wide jax.devices() view
    auto = partition_devices(k=2)
    expect = jax.devices()
    assert sum(auto, []) == list(expect)

    # ...honoring the same cap knob as BatchRunner auto-discovery
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "3")
    capped = partition_devices(k=2)
    assert sum(capped, []) == list(expect)[:3]


# ------------------------------------------------------- sub-mesh runner
def test_for_batch_submesh_and_cache():
    runner = BatchRunner(devices=_devices(4))
    # full batches keep the full mesh
    assert runner.for_batch(4) is runner
    assert runner.for_batch(9) is runner
    # a tail smaller than the mesh gets a prefix sub-mesh of exactly
    # its size — zero padding lanes — and the sub-runner is cached
    sub = runner.for_batch(3)
    assert sub.n_devices == 3
    assert sub.round_batch(3) == 3
    assert sub.devices == runner.devices[:3]
    assert runner.for_batch(3) is sub
    # single-device runners never split
    one = BatchRunner(devices=_devices(1))
    assert one.for_batch(1) is one


def test_run_split_concat_identity():
    """The satellite pin: run_split's per-shard outputs, concatenated
    in device order, equal the single-device kernel result row-for-row
    (shards are now ALL placed before the first dispatch — the
    transfer/compute overlap must not change bytes)."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda a, b: (a * 2 + b, a.sum(axis=1)))
    a = np.arange(8 * 5, dtype=np.int32).reshape(8, 5)
    b = np.ones((8, 5), dtype=np.int32)

    single = BatchRunner(devices=_devices(1))
    multi = BatchRunner(devices=_devices(4))
    ref = single.run_split(fn, a, b)
    shards = multi.run_split(fn, a, b)
    assert isinstance(shards, list) and len(shards) == 4
    cat0 = np.concatenate([np.asarray(s[0]) for s in shards])
    cat1 = np.concatenate([np.asarray(s[1]) for s in shards])
    assert np.array_equal(cat0, np.asarray(ref[0]))
    assert np.array_equal(cat1, np.asarray(ref[1]))


# ------------------------------------------- device-aware pack_iteration
def test_pack_iteration_lane_multiple_rounds_down():
    from racon_tpu.sched import pack_iteration

    items = list(range(10))
    batch, rest = pack_iteration(items, 8, shape_key=lambda e: e,
                                 age_key=lambda e: e, lane_multiple=4)
    # cap 8 is already a multiple of 4: full slab
    assert len(batch) == 8 and len(rest) == 2
    # a 10-deep pool at cap 7 rounds DOWN to 4 (one clean shard split)
    batch, rest = pack_iteration(items, 7, shape_key=lambda e: e,
                                 age_key=lambda e: e, lane_multiple=4)
    assert len(batch) == 4 and len(rest) == 6
    assert min(batch) == 0  # the oldest always ships
    # a pool smaller than one multiple ships whole (sub-mesh dispatch)
    batch, rest = pack_iteration(list(range(3)), 8,
                                 shape_key=lambda e: e,
                                 age_key=lambda e: e, lane_multiple=4)
    assert len(batch) == 3 and rest == []


def test_pack_iteration_lane_multiple_keeps_oldest():
    from racon_tpu.sched import pack_iteration

    # oldest (age 0) sits at the LARGE end of the shape sort; the
    # rounded slab must still contain it
    items = [(shape, age) for shape, age in
             zip(range(10), [9, 8, 7, 6, 5, 4, 3, 2, 1, 0])]
    batch, rest = pack_iteration(items, 6, shape_key=lambda e: e[0],
                                 age_key=lambda e: e[1],
                                 lane_multiple=4)
    assert len(batch) == 4
    assert (9, 0) in batch
    assert len(batch) + len(rest) == 10


# ------------------------------------------------ per-shard occupancy
def test_occupancy_mesh_counters_accumulate():
    from racon_tpu.sched import OccupancyStats

    stats = OccupancyStats()
    stats.record("eng", (64,), jobs=4, lanes=4, useful_cells=90,
                 total_cells=100, n_devices=2, shard_useful=[50, 40],
                 full_mesh_cells=120)
    stats.record("eng", (64,), jobs=2, lanes=2, useful_cells=30,
                 total_cells=40, n_devices=2, shard_useful=[20, 10],
                 full_mesh_cells=60)
    snap = stats.snapshot()["eng"]
    b = snap["buckets"]["(64,)"]
    # the PR-3 invariant still holds with the new counters riding along
    assert b["useful_cells"] + b["padded_cells"] == 140
    assert b["shard_useful"] == [70, 50]
    assert b["full_mesh_cells"] == 180
    assert b["n_devices"] == 2
    # engine-level aggregates: balance = 70/50, padded fractions actual
    # vs the full-mesh-rounding baseline
    assert snap["shard_useful"] == [70, 50]
    assert snap["shard_balance"] == pytest.approx(1.4)
    assert snap["padded_frac"] == pytest.approx(20 / 140)
    assert snap["padded_frac_full_mesh"] == pytest.approx(60 / 180)
    # the baseline is the worse number: sub-mesh dispatch really saved
    assert snap["padded_frac"] < snap["padded_frac_full_mesh"]


def test_aligner_submesh_tail_records_mesh_view():
    """A 3-pair batch on an 8-device mesh dispatches on a 3-device
    sub-mesh: zero padding lanes, and the recorded full-mesh baseline
    shows what round_batch would have burned."""
    from racon_tpu.ops.align import BatchAligner

    rng = np.random.default_rng(5)
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    pairs = []
    for _ in range(3):
        t = rng.choice(bases, size=150).tobytes()
        pairs.append((t[:70] + t[80:], t))
    runner = BatchRunner(devices=_devices(8))
    aligner = BatchAligner(band_width=64, runner=runner)
    runs = aligner.align(list(pairs))
    assert all(r is not None for r in runs)
    snap = aligner.sched.stats.snapshot()["aligner"]
    (bucket,) = snap["buckets"].values()
    assert bucket["lanes"] == 3          # not padded up to 8
    assert bucket["n_devices"] == 3
    assert len(bucket["shard_useful"]) == 3
    # the full-mesh baseline carries the 5 whole padding lanes we
    # skipped: capacity ratio is exactly 8/3 of the dispatched cells
    dispatched = bucket["useful_cells"] + bucket["padded_cells"]
    assert bucket["full_mesh_cells"] * 3 == dispatched * 8
    assert snap["padded_frac"] < snap["padded_frac_full_mesh"]
    # and the sub-mesh result equals the single-device result
    single = BatchAligner(band_width=64,
                          runner=BatchRunner(devices=_devices(1)))
    assert single.align(list(pairs)) == runs


# ------------------------------------------------- mesh-size identity pin
@pytest.mark.parametrize("engine", ["session", "fused"])
def test_polished_fasta_identical_across_mesh_sizes(engine, tmp_path,
                                                    monkeypatch):
    """THE acceptance pin (one-shot half): polished FASTA byte-identical
    at 1 vs 8 virtual devices for both device consensus engines — mesh
    width may move every perf number, never an output byte. (The serve
    half — worker lanes {1,2} — is pinned in tests/test_serve.py.)"""
    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.serve import make_synth_dataset

    _devices(8)
    monkeypatch.setenv("RACON_TPU_MAX_NODES", "768")
    paths = make_synth_dataset(str(tmp_path))

    def run(max_devices: str | None) -> bytes:
        if max_devices is None:
            monkeypatch.delenv("RACON_TPU_MAX_DEVICES", raising=False)
        else:
            monkeypatch.setenv("RACON_TPU_MAX_DEVICES", max_devices)
        p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                            num_threads=2, tpu_poa_batches=1,
                            tpu_engine=engine)
        p.initialize()
        return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                        for s in p.polish())

    one = run("1")
    assert one
    assert run(None) == one  # the full 8-virtual-device mesh


def test_occupancy_merge_from_folds_lane_stats():
    """The serve batcher's per-lane OccupancyStats (exact per-iteration
    compile deltas under lane concurrency) merge into one lifetime
    view: counters sum, shard lists sum element-wise, descriptors
    survive, compile totals add."""
    from racon_tpu.sched import OccupancyStats

    a, b, merged = OccupancyStats(), OccupancyStats(), OccupancyStats()
    a.record("eng", (64,), jobs=2, lanes=2, useful_cells=30,
             total_cells=40, kernel="xla", dtype="int32", n_devices=2,
             shard_useful=[20, 10], full_mesh_cells=40)
    b.record("eng", (64,), jobs=1, lanes=2, useful_cells=10,
             total_cells=40, n_devices=2, shard_useful=[10, 0],
             full_mesh_cells=40)
    b.record("eng", (128,), jobs=1, lanes=1, useful_cells=5,
             total_cells=8)
    a.record_compile("eng", 1.5)
    b.record_compile("eng", 0.5)
    merged.merge_from(a)
    merged.merge_from(b)
    snap = merged.snapshot()["eng"]
    bucket = snap["buckets"]["(64,)"]
    assert bucket["jobs"] == 3 and bucket["batches"] == 2
    assert bucket["useful_cells"] == 40
    assert bucket["useful_cells"] + bucket["padded_cells"] == 80
    assert bucket["shard_useful"] == [30, 10]
    assert bucket["full_mesh_cells"] == 80
    assert bucket["kernel"] == "xla" and bucket["n_devices"] == 2
    assert "(128,)" in snap["buckets"]
    assert snap["compiles"] == 2
    assert snap["compile_s"] == pytest.approx(2.0)
