"""Unit tests for the native host library (racon_tpu/native).

Covers the edlib-equivalent Myers bit-parallel NW (exact distance + CIGAR)
and the spoa-equivalent POA consensus engine, the two compute roles the
reference gets from vendored C++ (SURVEY.md §2b).
"""

import random

import numpy as np
import pytest

from racon_tpu.native import edit_distance, nw_cigar, nw_cigar_batch, poa_batch
from racon_tpu.utils.cigar import parse_cigar

ACGT = b"ACGT"


def lev_reference(a: bytes, b: bytes) -> int:
    """Independent O(n^2) Levenshtein (vectorized rows + prefix-min scan)."""
    a = np.frombuffer(a, dtype=np.uint8)
    b = np.frombuffer(b, dtype=np.uint8)
    n = len(b)
    prev = np.arange(n + 1, dtype=np.int32)
    idx = np.arange(n + 1, dtype=np.int32)
    for i in range(1, len(a) + 1):
        cost = (a[i - 1] != b).astype(np.int32)
        tmp = np.empty(n + 1, dtype=np.int32)
        tmp[0] = i
        tmp[1:] = np.minimum(prev[1:] + 1, prev[:-1] + cost)
        prev = np.minimum.accumulate(tmp - idx) + idx
    return int(prev[n])


def mutate(rng: random.Random, s: bytes, rate: float) -> bytes:
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def assert_cigar_consistent(q: bytes, t: bytes, cigar: bytes, dist: int):
    """The CIGAR must consume exactly q and t and cost exactly `dist`."""
    ops, lens = parse_cigar(cigar)
    qi = ti = cost = 0
    for op, length in zip(ops, lens):
        ch = chr(op)
        if ch == "M":
            for _ in range(length):
                cost += 1 if q[qi] != t[ti] else 0
                qi += 1
                ti += 1
        elif ch == "I":
            qi += length
            cost += length
        elif ch == "D":
            ti += length
            cost += length
        else:  # pragma: no cover
            pytest.fail(f"unexpected op {ch}")
    assert qi == len(q) and ti == len(t)
    assert cost == dist


def test_myers_matches_reference_dp_fuzz():
    rng = random.Random(11)
    # sizes straddling the 64-bit block and 128-column checkpoint boundaries
    for size in [1, 5, 63, 64, 65, 127, 128, 129, 200, 513, 2000]:
        t = bytes(rng.choice(ACGT) for _ in range(size))
        q = mutate(rng, t, rng.choice([0.0, 0.05, 0.3, 0.8])) or b"A"
        d = edit_distance(q, t)
        assert d == lev_reference(q, t)
        assert_cigar_consistent(q, t, nw_cigar(q, t), d)


def test_myers_empty_and_degenerate():
    assert edit_distance(b"", b"ACGT") == 4
    assert edit_distance(b"ACGT", b"") == 4
    assert nw_cigar(b"", b"ACGT") == b"4D"
    assert nw_cigar(b"ACGT", b"") == b"4I"
    assert edit_distance(b"ACGT", b"ACGT") == 0
    assert nw_cigar(b"ACGT", b"ACGT") == b"4M"


def test_myers_non_acgt_bytes_match_exactly():
    # raw byte equality, like edlib: N matches N, case is distinct
    assert edit_distance(b"ANNA", b"ANNA") == 0
    assert edit_distance(b"ANRA", b"ANNA") == 1


def test_nw_cigar_batch_matches_single():
    rng = random.Random(5)
    pairs = []
    for _ in range(20):
        t = bytes(rng.choice(ACGT) for _ in range(rng.randrange(1, 400)))
        q = mutate(rng, t, 0.2) or b"C"
        pairs.append((q, t))
    batch = nw_cigar_batch(pairs, n_threads=3)
    for (q, t), cig in zip(pairs, batch):
        assert cig == nw_cigar(q, t)


def test_poa_consensus_recovers_truth():
    """20 noisy copies + a noisy backbone must reconstruct the truth almost
    exactly (the spoa role, reference window.cpp:65-142)."""
    rng = random.Random(7)
    truth = bytes(rng.choice(ACGT) for _ in range(500))
    backbone = mutate(rng, truth, 0.10)
    layers = [mutate(rng, truth, 0.10) for _ in range(20)]
    window = [(backbone, None, 0, len(backbone) - 1)] + \
             [(l, None, 0, len(l) - 1) for l in layers]
    cons, cov = poa_batch([window], 3, -5, -4)[0]
    assert edit_distance(backbone, truth) > 30     # the draft is noisy
    assert edit_distance(cons, truth) <= 12        # the consensus is not
    assert len(cov) == len(cons)
    assert cov[len(cov) // 2] >= 15                # mid-window coverage


def test_poa_quality_weights_respected():
    """A high-quality minority base should win over low-quality majority."""
    backbone = b"ACGTACGTACGTACGTACGT"
    variant = b"ACGTACGTATGTACGTACGT"  # C->T at position 9
    lo = bytes([33 + 2]) * 20    # Phred 2
    hi = bytes([33 + 60]) * 20   # Phred 60
    window = [(backbone, b"!" * 20, 0, 19),
              (variant, hi, 0, 19), (variant, hi, 0, 19),
              (backbone, lo, 0, 19), (backbone, lo, 0, 19),
              (backbone, lo, 0, 19)]
    cons, _ = poa_batch([window], 3, -5, -4)[0]
    assert cons == variant


def test_poa_subwindow_layers():
    """Layers covering only part of the window align against the matching
    subgraph (reference window.cpp:87-103)."""
    rng = random.Random(3)
    bb = bytes(rng.choice(ACGT) for _ in range(300))
    lay = bb[100:200]
    window = [(bb, None, 0, 299)] + [(lay, None, 100, 199)] * 3
    cons, cov = poa_batch([window], 3, -5, -4)[0]
    assert cons == bb
    assert cov[150] == 4 and cov[50] == 1


def test_poa_batch_threads_deterministic():
    rng = random.Random(9)
    windows = []
    for _ in range(8):
        truth = bytes(rng.choice(ACGT) for _ in range(200))
        win = [(mutate(rng, truth, 0.1), None, 0, 199)]
        win += [(mutate(rng, truth, 0.1), None, 0, 199) for _ in range(6)]
        windows.append([(s, q, b, min(e, len(win[0][0]) - 1))
                        for (s, q, b, e) in win])
    a = poa_batch(windows, 3, -5, -4, n_threads=1)
    b = poa_batch(windows, 3, -5, -4, n_threads=4)
    for (ca, _), (cb, _) in zip(a, b):
        assert ca == cb
