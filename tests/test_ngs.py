"""Short-read (kNGS) polishing path.

BASELINE.md lists Illumina short-read polishing of an ONT draft (SAM
input, small windows) as a target config. Mean read length <= 1000 selects
WindowType.kNGS (reference polisher.cpp:276-277), which skips the TGS
coverage trim (window.cpp:118-127). Synthetic end-to-end: accurate 150 bp
reads over a noisy 3 kb draft must repair most draft errors.
"""

import gzip
import random

import pytest

from racon_tpu.core.polisher import create_polisher, PolisherType
from racon_tpu.core.window import WindowType
from racon_tpu.native import edit_distance

ACGT = b"ACGT"


def mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


@pytest.fixture
def ngs_dataset(tmp_path):
    rng = random.Random(23)
    truth = bytes(rng.choice(ACGT) for _ in range(3000))
    draft = mutate(rng, truth, 0.04)

    reads, paf = [], []
    read_len, step = 150, 50
    for start in range(0, len(truth) - read_len, step):
        read = mutate(rng, truth[start:start + read_len], 0.005)
        name = f"r{start}"
        reads.append((name, read))
        # approximate mapping onto the draft (same scale; NW fixes details)
        t_begin = min(start, len(draft) - 1)
        t_end = min(start + read_len, len(draft))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t+\tdraft\t"
                   f"{len(draft)}\t{t_begin}\t{t_end}\t{read_len}\t"
                   f"{read_len}\t60")

    reads_path = tmp_path / "reads.fasta.gz"
    with gzip.open(reads_path, "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    paf_path = tmp_path / "ovl.paf.gz"
    with gzip.open(paf_path, "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    draft_path = tmp_path / "draft.fasta.gz"
    with gzip.open(draft_path, "wb") as f:
        f.write(b">draft\n" + draft + b"\n")
    return reads_path, paf_path, draft_path, truth, draft


def test_short_read_polishing_selects_ngs_and_repairs(ngs_dataset):
    reads_path, paf_path, draft_path, truth, draft = ngs_dataset
    p = create_polisher(str(reads_path), str(paf_path), str(draft_path),
                        PolisherType.kC, 200, -1.0, 0.3, num_threads=2)
    p.initialize()
    assert p.windows and p.windows[0].type == WindowType.kNGS
    polished = p.polish()
    assert len(polished) == 1
    d_draft = edit_distance(draft, truth)
    d_polished = edit_distance(polished[0].data, truth)
    assert d_polished < d_draft * 0.25  # most draft errors repaired
