"""Observability layer: trace/metrics integrity, leveled logging.

Pins the PR-4 contracts:
  - every emitted span is well-formed (ph/pid/tid/name present, dur >= 0)
    and the trace file is valid Chrome trace-event JSON;
  - per-stage span-duration sums agree with the PipelineStats wall-clock
    counters (they share perf_counter endpoints, so within tolerance);
  - fault-plan runs produce resilience instant events matching the
    degradation counters exactly (both come from the same bump);
  - concurrent pipeline threads produce a parseable trace;
  - tracing off by default, and a traced run's FASTA is byte-identical;
  - the metrics registry namespaces (pipeline/sched/resilience/aligner),
    the --tpu-metrics dump, and the bench-facing snapshot;
  - leveled logging (quiet/info/debug), warn_dedup suppression, and the
    Logger.total() open-section fix.
"""

import gzip
import json
import os
import random
import time

import pytest

from racon_tpu.obs import trace
from racon_tpu.obs.metrics import MetricsRegistry
from racon_tpu.utils import logger as ulog

ACGT = b"ACGT"


@pytest.fixture(autouse=True)
def _reset_obs(monkeypatch):
    """Every test starts with tracing unarmed, dedup empty and the log
    level re-resolving from a clean environment."""
    monkeypatch.delenv("RACON_TPU_TRACE", raising=False)
    monkeypatch.delenv("RACON_TPU_METRICS", raising=False)
    monkeypatch.delenv("RACON_TPU_LOG_LEVEL", raising=False)
    monkeypatch.delenv("RACON_TPU_FAULT_PLAN", raising=False)
    trace.reset()
    ulog.reset_dedup()
    ulog.set_log_level(None)
    yield
    trace.reset()
    ulog.reset_dedup()
    ulog.set_log_level(None)


# ------------------------------------------------------------------ fixture
def _mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Small synthetic polishing job (the faultcheck shape): a 2 kb
    draft, windowed reads, PAF overlaps — enough windows and layers to
    drive both pipeline phases on the host backend in well under a
    second."""
    rng = random.Random(11)
    truth = bytes(rng.choice(ACGT) for _ in range(2000))
    draft = _mutate(rng, truth, 0.04)
    jobs = [(start, 400) for start in range(0, len(truth) - 400, 100)]
    reads, paf = [], []
    for k, (start, read_len) in enumerate(jobs):
        read = _mutate(rng, truth[start:start + read_len], 0.05)
        reads.append((f"r{k}", read))
        t_end = min(start + read_len, len(draft))
        paf.append(f"r{k}\t{len(read)}\t0\t{len(read)}\t+\tdraft\t"
                   f"{len(draft)}\t{start}\t{t_end}\t{read_len}\t"
                   f"{read_len}\t60")
    d = tmp_path_factory.mktemp("obsdata")
    paths = (str(d / "reads.fasta.gz"), str(d / "ovl.paf.gz"),
             str(d / "draft.fasta.gz"))
    with gzip.open(paths[0], "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    with gzip.open(paths[2], "wb") as f:
        f.write(b">draft\n" + draft + b"\n")
    return paths


def _polish(paths, depth=2):
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*paths, PolisherType.kC, 500, -1.0, 0.3,
                        num_threads=2, tpu_pipeline_depth=depth)
    p.initialize()
    out = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                   for s in p.polish())
    return out, p


def _load_trace(path):
    with open(path) as fh:
        doc = json.load(fh)
    return doc["traceEvents"]


# ------------------------------------------------------------- span tracing
def test_tracing_off_by_default():
    assert trace.get_tracer() is None
    # the disabled convenience span is a working no-op context
    with trace.span("noop", x=1):
        pass


def test_trace_events_well_formed(dataset, tmp_path):
    path = str(tmp_path / "trace.json")
    trace.configure(path)
    _polish(dataset, depth=2)
    events = _load_trace(path)  # polish() end saves automatically
    assert events, "traced polish emitted no events"
    names = {e["name"] for e in events}
    for expected in ("polisher.initialize", "polisher.consensus",
                     "pipeline.pack", "pipeline.device",
                     "pipeline.unpack"):
        assert expected in names, f"missing {expected} spans"
    for ev in events:
        for field in ("name", "ph", "pid", "tid"):
            assert field in ev, f"event missing {field}: {ev}"
        if ev["ph"] == "X":
            assert ev["dur"] >= 0  # end >= start
            assert ev["ts"] >= 0


def test_span_sums_match_stage_stats(dataset, tmp_path):
    path = str(tmp_path / "trace.json")
    trace.configure(path)
    _, polisher = _polish(dataset, depth=2)
    events = _load_trace(path)
    stats = polisher.stage_stats
    sums = {}
    for ev in events:
        if ev["ph"] == "X" and ev["name"].startswith("pipeline."):
            stage = ev["name"].split(".", 1)[1]
            sums[stage] = sums.get(stage, 0.0) + ev["dur"] / 1e6
    for stage, key in (("pack", "pack_s"), ("device", "device_s"),
                       ("unpack", "unpack_s"), ("fallback", "fallback_s")):
        want = stats[key]
        got = sums.get(stage, 0.0)
        # spans reuse the counters' perf_counter endpoints, so only
        # float/serialization rounding separates them; 5% is the
        # acceptance bound, 1 ms the small-value floor
        assert got == pytest.approx(want, rel=0.05, abs=1e-3), \
            f"{stage}: span sum {got} vs stage counter {want}"


def test_fault_instants_match_counters(dataset, tmp_path, monkeypatch):
    from racon_tpu.resilience.faults import reset_fault_plan

    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", "device:chunk=0:raise")
    reset_fault_plan()
    trace.configure(path)
    try:
        _, polisher = _polish(dataset, depth=2)
    finally:
        monkeypatch.delenv("RACON_TPU_FAULT_PLAN")
        reset_fault_plan()
    stats = polisher.stage_stats
    assert stats["faults"] >= 1
    events = _load_trace(path)
    fired = sum(e["args"]["n"] for e in events
                if e["name"] == "resilience.faults")
    assert fired == stats["faults"]
    for e in events:
        if e["name"].startswith("resilience."):
            assert e["ph"] == "i"


def test_quarantine_instants_match_counters(dataset, tmp_path,
                                            monkeypatch):
    # poison the host POA engine entirely: the chunk fails, the
    # per-window retries fail, every eligible window quarantines — the
    # trace's quarantine instants must equal the counter exactly
    import racon_tpu.ops.poa as poa_mod

    def boom(*a, **kw):
        raise RuntimeError("poisoned poa")

    monkeypatch.setattr(poa_mod, "poa_batch", boom)
    path = str(tmp_path / "trace.json")
    trace.configure(path)
    out, polisher = _polish(dataset, depth=2)
    stats = polisher.stage_stats
    assert stats["quarantined"] > 0
    # the run survived (every window on its draft backbone; with ratio 0
    # the target is dropped from the output, the reference's `ratio > 0`
    # rule — the point is no exception reached us)
    events = _load_trace(path)
    quarantined = sum(e["args"]["n"] for e in events
                      if e["name"] == "resilience.quarantined")
    assert quarantined == stats["quarantined"]


def test_concurrent_pipeline_trace_parseable(tmp_path):
    from racon_tpu.pipeline import DispatchPipeline

    path = str(tmp_path / "trace.json")
    rec = trace.configure(path)
    results = []
    with DispatchPipeline(depth=2, fallback_workers=3) as pl:
        for _ in range(40):
            pl.submit_fallback(lambda: time.sleep(0.0005))
        pl.run(range(60),
               pack=lambda i: i * 2,
               dispatch=lambda i, ops: ops + 1,
               wait=lambda h: h,
               unpack=lambda i, res: results.append(res),
               label="t", describe=lambda i: {"i": i})
        pl.drain_fallback()
    rec.save()
    events = _load_trace(path)  # parseable despite 5+ writer threads
    counts = {}
    for e in events:
        counts[e["name"]] = counts.get(e["name"], 0) + 1
    assert counts["pipeline.pack"] == 60
    assert counts["pipeline.device"] == 120  # dispatch + wait segments
    assert counts["pipeline.unpack"] == 60
    assert counts["pipeline.fallback"] == 40
    assert len(results) == 60


def test_env_armed_trace_nonnegative_ts(dataset, tmp_path, monkeypatch):
    # arm via the env (the documented primary knob): the recorder is
    # created lazily at polisher construction, yet phase spans whose
    # start predates it must still clamp to ts >= 0
    path = str(tmp_path / "trace.json")
    monkeypatch.setenv("RACON_TPU_TRACE", path)
    _polish(dataset, depth=2)
    for ev in _load_trace(path):
        if ev["ph"] != "M":
            assert ev["ts"] >= 0, ev


def test_traced_output_byte_identical(dataset, tmp_path):
    out_plain, _ = _polish(dataset, depth=2)
    trace.configure(str(tmp_path / "trace.json"))
    out_traced, _ = _polish(dataset, depth=2)
    assert out_plain == out_traced


# ---------------------------------------------------------------- metrics
def test_metrics_registry_basics(tmp_path):
    reg = MetricsRegistry()
    reg.register("pipeline", lambda: {"pack_s": 1.5, "chunks": 3})
    reg.register("sched", lambda: {"aligner": {"occupancy_pct": 42.0}})
    snap = reg.snapshot()
    assert snap["pipeline"]["chunks"] == 3
    flat = reg.flat()
    assert flat["pipeline.pack_s"] == 1.5
    assert flat["sched.aligner.occupancy_pct"] == 42.0
    assert "pipeline.pack_s" in reg.table()
    p = str(tmp_path / "m.json")
    reg.dump(p)
    assert json.load(open(p))["pipeline"]["chunks"] == 3
    with pytest.raises(ValueError):
        reg.register("a.b", dict)


def test_polisher_metrics_namespaces(dataset):
    _, polisher = _polish(dataset, depth=2)
    snap = polisher.metrics.snapshot()
    for ns in ("pipeline", "resilience", "sched", "aligner"):
        assert ns in snap
    stats = polisher.stage_stats
    assert snap["pipeline"]["chunks"] == stats["chunks"]
    assert snap["resilience"]["quarantined"] == stats["quarantined"]
    # clean run: the whole resilience namespace is zero
    assert all(not v for v in snap["resilience"].values())
    flat = polisher.metrics.flat()
    assert flat["pipeline.pack_s"] == stats["pack_s"]


def test_metrics_env_dump(dataset, tmp_path, monkeypatch, capsys):
    path = str(tmp_path / "metrics.json")
    monkeypatch.setenv("RACON_TPU_METRICS", path)
    _polish(dataset, depth=2)
    snap = json.load(open(path))
    assert "pipeline" in snap and "resilience" in snap
    err = capsys.readouterr().err
    assert "end-of-run metrics" in err
    assert "pipeline.chunks" in err  # the stderr summary table


# ---------------------------------------------------------------- logging
def test_log_levels(capsys):
    ulog.set_log_level("quiet")
    ulog.log_info("INFO-LINE")
    ulog.log_debug("DEBUG-LINE")
    assert capsys.readouterr().err == ""
    ulog.set_log_level("info")
    ulog.log_info("INFO-LINE")
    ulog.log_debug("DEBUG-LINE")
    assert capsys.readouterr().err == "INFO-LINE\n"
    ulog.set_log_level("debug")
    ulog.log_info("INFO-LINE")
    ulog.log_debug("DEBUG-LINE")
    assert capsys.readouterr().err == "INFO-LINE\nDEBUG-LINE\n"


def test_log_level_env_resolution(monkeypatch):
    monkeypatch.setenv("RACON_TPU_LOG_LEVEL", "quiet")
    ulog.set_log_level(None)
    assert ulog.log_level() == ulog.QUIET
    monkeypatch.setenv("RACON_TPU_LOG_LEVEL", "bogus")
    ulog.set_log_level(None)
    assert ulog.log_level() == ulog.INFO  # typo falls back, never crashes


def test_warn_dedup_suppresses_repeats(capsys):
    ulog.set_log_level("info")
    for i in range(5):
        ulog.warn_dedup("site.key", f"warning text {i}")
    err = capsys.readouterr().err
    assert err == "warning text 0\n"  # first occurrence only
    ulog.flush_dedup()
    err = capsys.readouterr().err
    assert "repeated 4 more times" in err
    # flushed: state cleared, the next run warns afresh
    ulog.warn_dedup("site.key", "again")
    assert capsys.readouterr().err == "again\n"


def test_warn_dedup_debug_shows_all(capsys):
    ulog.set_log_level("debug")
    ulog.warn_dedup("k", "w1")
    ulog.warn_dedup("k", "w2")
    assert capsys.readouterr().err == "w1\nw2\n"
    ulog.flush_dedup()  # nothing suppressed at debug: no summary
    assert capsys.readouterr().err == ""


def test_logger_total_counts_open_section(capsys):
    ulog.set_log_level("info")
    lg = ulog.Logger()
    lg.log()  # open a section, no bar armed
    time.sleep(0.02)
    lg.total("total =")
    line = capsys.readouterr().err.strip()
    seconds = float(line.split()[-2])
    assert seconds >= 0.015  # used to report 0 with no active bar


def test_quiet_run_keeps_timing_totals(dataset, capsys):
    ulog.set_log_level("quiet")
    out, polisher = _polish(dataset, depth=2)
    assert capsys.readouterr().err == ""  # quiet really is silent
    assert out  # and the output is unaffected
    assert polisher.stage_stats["chunks"] >= 1


# -------------------------------------------------------------- CLI / misc
def test_cli_obs_flags_parse():
    from racon_tpu.cli import parse_args

    opts = parse_args(["--tpu-trace", "t.json", "--tpu-metrics=m.json",
                       "--tpu-log-level", "debug",
                       "--tpu-jax-profile", "prof", "a", "b", "c"])
    assert opts["tpu_trace"] == "t.json"
    assert opts["tpu_metrics"] == "m.json"
    assert opts["tpu_log_level"] == "debug"
    assert opts["tpu_jax_profile"] == "prof"
    assert opts["paths"] == ["a", "b", "c"]


def test_cli_obs_flags_in_help(capsys):
    from racon_tpu import cli

    assert cli.main(["--help"]) == 0
    out = capsys.readouterr().out
    for flag in ("--tpu-trace", "--tpu-metrics", "--tpu-log-level",
                 "--tpu-jax-profile"):
        assert flag in out


def test_jax_profile_noop_and_safe(monkeypatch, tmp_path):
    from racon_tpu.obs import jax_profile

    # unset: a null context
    monkeypatch.delenv("RACON_TPU_PROFILE", raising=False)
    with jax_profile("x"):
        pass
    # set but profiler broken: still a silent no-op, never a crash
    monkeypatch.setenv("RACON_TPU_PROFILE", str(tmp_path / "prof"))
    import jax

    def broken(*a, **kw):
        raise RuntimeError("no profiler on this backend")

    monkeypatch.setattr(jax.profiler, "trace", broken)
    with jax_profile("consensus"):
        pass
