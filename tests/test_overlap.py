import numpy as np
import pytest

from racon_tpu import Overlap, RaconError, Sequence
from racon_tpu.utils.cigar import parse_cigar, cigar_from_ops


def test_mhap_ids_one_based_and_strand():
    o = Overlap.from_mhap(3, 7, 0.25, 11, 0, 10, 110, 200, 1, 20, 115, 300)
    assert o.q_id == 2 and o.t_id == 6
    assert o.strand is True  # 0 ^ 1
    assert o.length == max(100, 95)
    assert o.error == pytest.approx(1 - 95 / 100)


def test_paf_fields():
    o = Overlap.from_paf("q", 500, 10, 110, "-", "t", 900, 20, 130, 80, 120, 60)
    assert o.strand is True
    assert o.q_begin == 10 and o.t_end == 130
    assert o.error == pytest.approx(1 - 100 / 110)


def test_sam_cigar_walk_forward():
    # 5S 10M 2I 3D 5M 4H ; pos 100 (1-based)
    o = Overlap.from_sam("q", 0, "t", 100, 60, b"5S10M2I3D5M4H")
    assert o.t_begin == 99
    assert o.q_begin == 5
    assert o.q_end == 5 + 17       # 10M + 2I + 5M
    assert o.q_length == 9 + 17    # clips + aligned
    assert o.t_end == 99 + 18      # 10M + 3D + 5M
    assert o.error == pytest.approx(1 - 17 / 18)


def test_sam_strand_flips_query_coords():
    o = Overlap.from_sam("q", 16, "t", 1, 60, b"5S10M")
    # pre-flip: q_begin=5, q_end=15, q_length=15
    assert o.strand is True
    assert o.q_begin == 0 and o.q_end == 10


def test_sam_unmapped_invalid():
    o = Overlap.from_sam("q", 4, "t", 0, 0, b"*")
    assert not o.is_valid


def test_sam_missing_cigar_fatal():
    with pytest.raises(RaconError, match="missing alignment from SAM"):
        Overlap.from_sam("q", 0, "t", 1, 60, b"*")


def _mk_sequences():
    return [Sequence("r0", b"A" * 100), Sequence("t0", b"C" * 200)]


def test_transmute_by_name():
    seqs = _mk_sequences()
    o = Overlap.from_paf("r0", 100, 0, 50, "+", "t0", 200, 0, 55, 40, 55, 60)
    o.transmute(seqs, {"r0q": 0, "t0t": 1}, {})
    assert o.is_transmuted and o.q_id == 0 and o.t_id == 1


def test_transmute_unknown_name_invalidates():
    seqs = _mk_sequences()
    o = Overlap.from_paf("zz", 100, 0, 50, "+", "t0", 200, 0, 55, 40, 55, 60)
    o.transmute(seqs, {"r0q": 0, "t0t": 1}, {})
    assert not o.is_valid


def test_transmute_length_mismatch_fatal():
    seqs = _mk_sequences()
    o = Overlap.from_paf("r0", 999, 0, 50, "+", "t0", 200, 0, 55, 40, 55, 60)
    with pytest.raises(RaconError, match="unequal lengths"):
        o.transmute(seqs, {"r0q": 0, "t0t": 1}, {})


# ---------------------------------------------------------------------------
# breaking points: vectorized walk vs a literal per-base reimplementation of
# reference overlap.cpp:226-292
# ---------------------------------------------------------------------------

def _reference_walk(cigar, t_begin, t_end, q_start, window_length):
    ops, lens = parse_cigar(cigar)
    window_ends = []
    i = 0
    while i < t_end:
        if i > t_begin:
            window_ends.append(i - 1)
        i += window_length
    window_ends.append(t_end - 1)

    w = 0
    found = False
    first = last = (0, 0)
    q_ptr = q_start - 1
    t_ptr = t_begin - 1
    out = []
    for op, n in zip(ops, lens):
        c = chr(op)
        if c in "M=X":
            for _ in range(int(n)):
                q_ptr += 1
                t_ptr += 1
                if not found:
                    found = True
                    first = (t_ptr, q_ptr)
                last = (t_ptr + 1, q_ptr + 1)
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found:
                        out.append(first)
                        out.append(last)
                    found = False
                    w += 1
        elif c == "I":
            q_ptr += int(n)
        elif c in "DN":
            for _ in range(int(n)):
                t_ptr += 1
                if w < len(window_ends) and t_ptr == window_ends[w]:
                    if found:
                        out.append(first)
                        out.append(last)
                    found = False
                    w += 1
    return np.array(out, dtype=np.int64).reshape(-1, 4) if out else np.empty((0, 4), np.int64)


def _bp_case(cigar, t_begin, q_begin, q_end, q_length, strand, window_length, t_span):
    o = Overlap.from_paf("q", q_length, q_begin, q_end, "-" if strand else "+",
                         "t", 10**6, t_begin, t_begin + t_span, 1, 1, 60)
    o.is_transmuted = True
    o.cigar = cigar
    got = o._breaking_points_from_cigar(window_length)
    q_start = (q_length - q_end) if strand else q_begin
    want = _reference_walk(cigar, t_begin, o.t_end, q_start, window_length)
    np.testing.assert_array_equal(got, want)


def test_breaking_points_simple():
    # 100M spanning two windows of 64
    _bp_case(b"100M", 10, 0, 100, 100, False, 64, 100)


def test_breaking_points_with_indels():
    _bp_case(b"20M5D30M3I47M", 0, 0, 100, 100, False, 50, 102)


def test_breaking_points_deletion_across_boundary():
    _bp_case(b"10M60D30M", 58, 0, 40, 40, False, 64, 100)


def test_breaking_points_strand():
    _bp_case(b"50M", 5, 10, 60, 80, True, 32, 50)


def test_breaking_points_random_fuzz():
    rng = np.random.default_rng(42)
    for _ in range(50):
        runs = []
        q_len = 0
        t_len = 0
        for _ in range(rng.integers(1, 12)):
            op = rng.choice(["M", "I", "D"])
            n = int(rng.integers(1, 40))
            runs.append((n, op))
            if op in "MI":
                q_len += n
            if op in "MD":
                t_len += n
        if not any(op == "M" for _, op in runs):
            runs.append((5, "M"))
            q_len += 5
            t_len += 5
        cigar = cigar_from_ops(runs).encode()
        t_begin = int(rng.integers(0, 100))
        wl = int(rng.integers(10, 80))
        _bp_case(cigar, t_begin, 0, q_len, q_len, False, wl, t_len)
