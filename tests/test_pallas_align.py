"""Pallas wavefront-aligner kernel tests (ops/align_pallas.py),
interpret mode — plus the dtype-shrinking and base-packing identity
pins for the aligner plane.

The kernel must reproduce the XLA banded program EXACTLY — same DP,
same INF clamp, same tie order, same traceback walk (touched-edge flags
and final distance included) — because BatchAligner's rejection
decisions (band-clip -> host realign) ride on them. Fuzzed across
random pairs, band-riding pathological pairs, bucket-filling lengths,
and the int16 envelope, in every (dtype, packed) variant.
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from racon_tpu.ops import align_pallas
from racon_tpu.ops.align import (BatchAligner, _kernel_for, _runs_of,
                                 _traceback, _unpack_bp, band_offsets)
from racon_tpu.ops.dtypes import (aligner_int16_ok, dtype_mode,
                                  poa_int16_ok, resolve_dtype)
from racon_tpu.ops.encode import (encode_padded, pack_2bit, packable,
                                  unpack_2bit_jax)

ACGT = b"ACGT"


def _mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def _xla_decode(pairs, edge, band, dtype="int32"):
    """The XLA reference path: kernel -> host traceback -> (runs,
    touched, dist)."""
    n_waves = 2 * edge + 1
    q_arr, q_lens = encode_padded([p[0] for p in pairs], edge)
    t_arr, t_lens = encode_padded([p[1] for p in pairs], edge)
    offs = np.stack([band_offsets(int(ql), int(tl), band, n_waves)
                     for ql, tl in zip(q_lens, t_lens)])
    fn = _kernel_for(band, n_waves, dtype, False)
    bp, dist = fn(q_arr, t_arr, q_lens.astype(np.int32),
                  t_lens.astype(np.int32), offs)
    runs, touched = _traceback(_unpack_bp(np.asarray(bp)), offs,
                               q_lens, t_lens)
    return (runs, touched, np.asarray(dist).astype(np.int64),
            (q_arr, t_arr, q_lens, t_lens, offs))


def _pallas_decode(operands, edge, band, dtype, packed):
    q_arr, t_arr, q_lens, t_lens, offs = operands
    fn = align_pallas.wavefront_align(edge, band, dtype, packed,
                                      interpret=True)
    qx, tx = align_pallas.build_ext(q_arr, t_arr, band)
    if packed:
        qx, tx = pack_2bit(qx), pack_2bit(tx)
    ops, meta = fn(qx, tx, q_lens.astype(np.int32),
                   t_lens.astype(np.int32), offs)
    ops = np.asarray(ops)
    meta = np.asarray(meta)
    runs = [_runs_of(ops[k, :meta[k, 0]][::-1])
            for k in range(len(q_lens))]
    return runs, meta[:, 2] > 0, meta[:, 1].astype(np.int64)


@pytest.mark.parametrize("dtype", ["int32", "int16"])
@pytest.mark.parametrize("packed", [False, True])
def test_pallas_matches_xla_fuzz(dtype, packed):
    """Random pairs across lengths (bucket-filling included), both
    dtypes, both operand packings: identical runs, touched flags and
    distances."""
    rng = random.Random(17)
    edge, band = 512, 64
    pairs = []
    for _ in range(5):
        t = bytes(rng.choice(ACGT) for _ in range(rng.randint(30, edge)))
        pairs.append((_mutate(rng, t, 0.15)[:edge], t))
    pairs.append((b"A" * edge, b"T" * edge))   # maximal cost, full bucket
    pairs.append((b"A", b"A"))                 # minimal pair (pad lanes)

    runs_x, touched_x, dist_x, operands = _xla_decode(pairs, edge, band,
                                                      dtype)
    runs_p, touched_p, dist_p = _pallas_decode(operands, edge, band,
                                               dtype, packed)
    assert runs_p == runs_x
    assert touched_p.tolist() == touched_x.tolist()
    assert dist_p.tolist() == dist_x.tolist()


def test_pallas_band_edge_cases_match():
    """Pairs whose optimal path rides or crosses the band boundary —
    the rejection signals (touched / suspicious-cost) must agree, since
    they decide which pairs get host-realigned."""
    rng = random.Random(23)
    edge, band = 512, 32
    base = bytes(rng.choice(ACGT) for _ in range(400))
    pairs = [
        (base[100:] + base[:100], base),           # rotation: off-band
        (base[:200] + base[300:], base),           # 100 bp deletion
        (base, base[:150]),                        # very skewed lengths
        (_mutate(rng, base, 0.4)[:edge], base),    # mismatch soup
    ]
    runs_x, touched_x, dist_x, operands = _xla_decode(pairs, edge, band)
    runs_p, touched_p, dist_p = _pallas_decode(operands, edge, band,
                                               "int32", False)
    assert runs_p == runs_x
    assert touched_p.tolist() == touched_x.tolist()
    assert dist_p.tolist() == dist_x.tolist()
    # the cases were chosen to exercise the signal: at least one pair
    # must actually trip it, or this test pins nothing
    assert touched_x.any() or (dist_x > 0.4 * 400).any()


def test_int16_envelope_predicates():
    """The overflow proofs' exact boundaries."""
    # aligner: INF16 = 1<<14 must exceed every real score (<= 2*edge)
    assert aligner_int16_ok(4096)
    assert aligner_int16_ok(8191)
    assert not aligner_int16_ok(8192)
    # POA: (N + L + 2) * mp <= 16383
    assert poa_int16_ok(1024, 1021, 5, -4, -8)        # 16376 <= 16383
    assert not poa_int16_ok(1024, 1022, 5, -4, -8)    # 16384 > 16383
    mp3 = (16383 // 3) - 2
    assert poa_int16_ok(mp3 // 2, mp3 - mp3 // 2, 3, -3, -1)  # == bound
    assert not poa_int16_ok(mp3 // 2 + 1, mp3 - mp3 // 2, 3, -3, -1)
    # the envelope session bucket at default scoring stays int32
    assert not poa_int16_ok(2048, 640, 5, -4, -8)
    assert poa_int16_ok(2048, 640, 3, -5, -4)


def test_int16_bitwise_identical_at_max_cost():
    """int16 vs int32 XLA kernels: RAW outputs (packed backpointers and
    distances) must be bit-identical, including the worst-cost pair the
    bucket can hold (cost == edge, the envelope's score ceiling)."""
    edge, band = 512, 64
    rng = random.Random(3)
    t = bytes(rng.choice(ACGT) for _ in range(edge))
    pairs = [(b"G" * edge, b"C" * edge), (_mutate(rng, t, 0.1)[:edge], t)]
    n_waves = 2 * edge + 1
    q_arr, q_lens = encode_padded([p[0] for p in pairs], edge)
    t_arr, t_lens = encode_padded([p[1] for p in pairs], edge)
    offs = np.stack([band_offsets(int(ql), int(tl), band, n_waves)
                     for ql, tl in zip(q_lens, t_lens)])
    outs = {}
    for dt in ("int32", "int16"):
        bp, dist = _kernel_for(band, n_waves, dt, False)(
            q_arr, t_arr, q_lens.astype(np.int32),
            t_lens.astype(np.int32), offs)
        outs[dt] = (np.asarray(bp), np.asarray(dist).astype(np.int64))
    np.testing.assert_array_equal(outs["int32"][0], outs["int16"][0])
    # finite distances equal; sentinel distances (none here) aside
    np.testing.assert_array_equal(outs["int32"][1], outs["int16"][1])
    assert outs["int32"][1][0] == edge  # the ceiling really was hit


def test_packed_encode_roundtrip():
    codes, lens = encode_padded([b"ACGTACG", b"AC", b"ACGTNACG"], 12)
    assert packable(codes[:2], lens[:2])
    assert not packable(codes, lens)  # the N row
    packed = pack_2bit(codes[:2])
    assert packed.shape == (2, 3)
    back = np.asarray(unpack_2bit_jax(packed, 12, lens[:2]))
    np.testing.assert_array_equal(back, codes[:2])


def test_batch_aligner_pallas_identical_including_rejects():
    """BatchAligner end-to-end: use_pallas=True must produce the SAME
    per-pair result list as the XLA path — accepted runs, band-clip
    rejects (None), unbucketable pairs (None) — across mixed buckets,
    N-containing pairs (packed fallback) and the empty pair."""
    rng = random.Random(31)
    pairs = []
    for n in (100, 500, 600, 1500):
        t = bytes(rng.choice(ACGT) for _ in range(n))
        pairs.append((_mutate(rng, t, 0.1), t))
    t = bytes(rng.choice(ACGT) for _ in range(800))
    pairs.append((t[400:] + t[:400], t))          # rotation: rejected
    pairs.append((b"ACGNNNGT" * 40, b"ACGTACGT" * 40))  # N bases
    pairs.append((b"", b"ACGT"))                  # unbucketable
    pairs.append((b"A" * 99999, b"A" * 99999))    # beyond max bucket

    base = BatchAligner(max_length=2048, use_pallas=False).align(pairs)
    pal = BatchAligner(max_length=2048, use_pallas=True).align(pairs)
    assert pal == base
    assert base[-1] is None and base[-2] is None


def test_batch_aligner_dtype_and_packing_knobs_identical(monkeypatch):
    """RACON_TPU_DTYPE=int32 (the oracle) and RACON_TPU_PACK_BASES=0
    must not change a single result vs the shrunk/packed defaults."""
    rng = random.Random(7)
    pairs = []
    for n in (300, 700, 700):
        t = bytes(rng.choice(ACGT) for _ in range(n))
        pairs.append((_mutate(rng, t, 0.12), t))
    base = BatchAligner().align(pairs)
    monkeypatch.setenv("RACON_TPU_DTYPE", "int32")
    monkeypatch.setenv("RACON_TPU_PACK_BASES", "0")
    wide = BatchAligner().align(pairs)
    assert wide == base
    monkeypatch.setenv("RACON_TPU_DTYPE", "auto")
    monkeypatch.delenv("RACON_TPU_PACK_BASES")
    again = BatchAligner(use_pallas=True).align(pairs)
    assert again == base


def test_dtype_mode_resolution(monkeypatch):
    monkeypatch.delenv("RACON_TPU_DTYPE", raising=False)
    assert dtype_mode() == "auto"
    assert resolve_dtype(True) == "int16"
    assert resolve_dtype(False) == "int32"
    assert resolve_dtype(True, {"dtype": "int32"}) == "int32"
    monkeypatch.setenv("RACON_TPU_DTYPE", "int32")
    assert resolve_dtype(True) == "int32"
    monkeypatch.setenv("RACON_TPU_DTYPE", "int16")
    # forced narrow still respects the proof — and beats the table
    assert resolve_dtype(True, {"dtype": "int32"}) == "int16"
    assert resolve_dtype(False) == "int32"
    monkeypatch.setenv("RACON_TPU_DTYPE", "bogus")
    assert dtype_mode() == "auto"


def test_aligner_fits_vmem_envelope():
    """The aligner kernel's VMEM gate: small buckets resident, the
    giant ones fall back to XLA; int16 widens nothing the proof
    forbids."""
    assert align_pallas.fits_vmem(512, 64)
    assert align_pallas.fits_vmem(1024, 128)
    assert align_pallas.fits_vmem(4096, 512)
    assert not align_pallas.fits_vmem(16384, 1664)
    assert not align_pallas.fits_vmem(65536, 6656)
