"""Pallas window-sweep kernel tests (ops/poa_pallas.py), interpret mode.

The kernel must reproduce the XLA graph_aligner's output EXACTLY — same
DP, same band masking, same tie order — because the engines' consensus
byte-identity contract rests on it. Fuzzed on linear graphs and on real
evolving-graph session jobs (subgraphs, bands, deep layers included).
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack, linear_graph_inputs, mutate

from racon_tpu.native import PoaSession
from racon_tpu.ops.poa_graph import graph_aligner
from racon_tpu.ops.poa_pallas import fits_vmem, window_sweep

ACGT = b"ACGT"


def _nnodes_of(codes):
    return (codes != 5).sum(axis=1).astype(np.int32)


def test_pallas_matches_xla_on_linear_graphs():
    rng = random.Random(11)
    N, L, P = 96, 96, 4
    ts, qs = [], []
    for _ in range(6):
        t = bytes(rng.choice(ACGT) for _ in range(rng.randint(40, N - 8)))
        ts.append(t)
        qs.append(mutate(rng, t, 0.15)[:L])
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)

    xla = graph_aligner(N, L, P, 5, -4, -8)
    pls = window_sweep(N, L, P, 5, -4, -8, interpret=True)
    r_xla = np.asarray(xla(codes, preds, centers, sinks, seqs, lens, band))
    r_pls = np.asarray(pls(codes, preds, centers, sinks, seqs, lens, band,
                           _nnodes_of(codes)))
    np.testing.assert_array_equal(r_pls, r_xla)


def test_pallas_matches_xla_on_banded_linear_graphs():
    rng = random.Random(21)
    N, L, P = 96, 96, 4
    ts, qs = [], []
    for _ in range(4):
        t = bytes(rng.choice(ACGT) for _ in range(80))
        ts.append(t)
        qs.append(mutate(rng, t, 0.1)[:L])
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)
    band[:] = 32  # static band engages the masked recurrence + seed rule

    xla = graph_aligner(N, L, P, 5, -4, -8)
    pls = window_sweep(N, L, P, 5, -4, -8, interpret=True)
    r_xla = np.asarray(xla(codes, preds, centers, sinks, seqs, lens, band))
    r_pls = np.asarray(pls(codes, preds, centers, sinks, seqs, lens, band,
                           _nnodes_of(codes)))
    np.testing.assert_array_equal(r_pls, r_xla)


def test_pallas_matches_xla_on_evolving_session_jobs():
    """Every job a real session produces over whole windows — branching
    graphs, subgraph ranges, band centers — must give identical ranks
    from both kernels. XLA results are committed so the graphs keep
    evolving through the full depth."""
    rng = random.Random(31)
    windows, _ = _make_windows(rng, 5, length=70, depth=5, rate=0.12)
    sub, _ = _make_windows(rng, 3, length=70, depth=4, spanning=False,
                           rate=0.1)
    packed = [_pack(w) for w in windows + sub]
    N, L, P = 192, 128, 8
    session = PoaSession(packed, 3, -5, -4, N, P, L, max_jobs=64)

    xla = graph_aligner(N, L, P, 3, -5, -4)
    pls = window_sweep(N, L, P, 3, -5, -4, interpret=True)
    rounds = 0
    while True:
        jobs = session.prepare()
        if jobs is None:
            break
        n = jobs["n"]
        args = (jobs["codes"][:n, :N], jobs["preds"][:n, :N, :P],
                jobs["centers"][:n, :N], jobs["sinks"][:n, :N],
                jobs["seqs"][:n, :L], jobs["len"][:n], jobs["band"][:n])
        r_xla = np.asarray(xla(*args))
        r_pls = np.asarray(pls(*args, jobs["nnodes"][:n]))
        np.testing.assert_array_equal(r_pls, r_xla,
                                      err_msg=f"round {rounds}")
        session.commit(jobs["win"][:n].copy(), jobs["layer"][:n].copy(),
                       jobs["band"][:n].copy(), r_xla)
        rounds += 1
    assert rounds >= 4  # the loop really exercised evolving graphs
    session.close()


def test_fits_vmem_envelope():
    assert fits_vmem(2048, 640)       # the largest session bucket
    assert fits_vmem(320, 256)
    assert not fits_vmem(4096, 1024)  # beyond the resident budget


def test_pallas_session_engine_byte_identical_to_host():
    """The full device engine with the pallas kernel routed in
    (use_pallas=True) must produce host-identical consensus — the same
    contract the XLA path guarantees."""
    from racon_tpu.native import poa_batch
    from racon_tpu.ops.poa_graph import DeviceGraphPOA

    rng = random.Random(41)
    windows, _ = _make_windows(rng, 6, length=70, depth=5, rate=0.12)
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(3, -5, -4, max_nodes=192, max_len=128,
                         buckets=((192, 128),), batch_rows=8,
                         use_pallas=True)
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)
    assert (statuses == 0).all(), statuses.tolist()
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"window {i}"
        np.testing.assert_array_equal(dcov, hcov)
