"""Pallas window-sweep kernel tests (ops/poa_pallas.py), interpret mode.

The kernel must reproduce the XLA graph_aligner's output EXACTLY — same
DP, same band masking, same tie order — because the engines' consensus
byte-identity contract rests on it. Fuzzed on linear graphs and on real
evolving-graph session jobs (subgraphs, bands, deep layers included).
"""

import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack, linear_graph_inputs, mutate

from racon_tpu.native import PoaSession
from racon_tpu.ops.poa_graph import graph_aligner
from racon_tpu.ops.poa_pallas import fits_vmem, window_sweep

ACGT = b"ACGT"


def _nnodes_of(codes):
    return (codes != 5).sum(axis=1).astype(np.int32)


def test_pallas_matches_xla_on_linear_graphs():
    rng = random.Random(11)
    N, L, P = 96, 96, 4
    ts, qs = [], []
    for _ in range(6):
        t = bytes(rng.choice(ACGT) for _ in range(rng.randint(40, N - 8)))
        ts.append(t)
        qs.append(mutate(rng, t, 0.15)[:L])
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)

    xla = graph_aligner(N, L, P, 5, -4, -8)
    pls = window_sweep(N, L, P, 5, -4, -8, interpret=True)
    r_xla = np.asarray(xla(codes, preds, centers, sinks, seqs, lens, band))
    r_pls = np.asarray(pls(codes, preds, centers, sinks, seqs, lens, band,
                           _nnodes_of(codes)))
    np.testing.assert_array_equal(r_pls, r_xla)


def test_pallas_matches_xla_on_banded_linear_graphs():
    rng = random.Random(21)
    N, L, P = 96, 96, 4
    ts, qs = [], []
    for _ in range(4):
        t = bytes(rng.choice(ACGT) for _ in range(80))
        ts.append(t)
        qs.append(mutate(rng, t, 0.1)[:L])
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)
    band[:] = 32  # static band engages the masked recurrence + seed rule

    xla = graph_aligner(N, L, P, 5, -4, -8)
    pls = window_sweep(N, L, P, 5, -4, -8, interpret=True)
    r_xla = np.asarray(xla(codes, preds, centers, sinks, seqs, lens, band))
    r_pls = np.asarray(pls(codes, preds, centers, sinks, seqs, lens, band,
                           _nnodes_of(codes)))
    np.testing.assert_array_equal(r_pls, r_xla)


def test_pallas_matches_xla_on_evolving_session_jobs():
    """Every job a real session produces over whole windows — branching
    graphs, subgraph ranges, band centers — must give identical ranks
    from both kernels. XLA results are committed so the graphs keep
    evolving through the full depth."""
    rng = random.Random(31)
    windows, _ = _make_windows(rng, 5, length=70, depth=5, rate=0.12)
    sub, _ = _make_windows(rng, 3, length=70, depth=4, spanning=False,
                           rate=0.1)
    packed = [_pack(w) for w in windows + sub]
    N, L, P = 192, 128, 8
    session = PoaSession(packed, 3, -5, -4, N, P, L, max_jobs=64)

    xla = graph_aligner(N, L, P, 3, -5, -4)
    pls = window_sweep(N, L, P, 3, -5, -4, interpret=True)
    rounds = 0
    while True:
        jobs = session.prepare()
        if jobs is None:
            break
        n = jobs["n"]
        args = (jobs["codes"][:n, :N], jobs["preds"][:n, :N, :P],
                jobs["centers"][:n, :N], jobs["sinks"][:n, :N],
                jobs["seqs"][:n, :L], jobs["len"][:n], jobs["band"][:n])
        r_xla = np.asarray(xla(*args))
        r_pls = np.asarray(pls(*args, jobs["nnodes"][:n]))
        np.testing.assert_array_equal(r_pls, r_xla,
                                      err_msg=f"round {rounds}")
        session.commit(jobs["win"][:n].copy(), jobs["layer"][:n].copy(),
                       jobs["band"][:n].copy(), r_xla)
        rounds += 1
    assert rounds >= 4  # the loop really exercised evolving graphs
    session.close()


def test_fits_vmem_envelope():
    assert fits_vmem(2048, 640)       # the largest session bucket
    assert fits_vmem(320, 256)
    assert not fits_vmem(4096, 1024)  # beyond the resident budget
    # the corrected accounting includes the operand blocks: a high
    # in-degree (preds [1, N, P] staged as int32) shrinks the envelope
    assert fits_vmem(2048, 640, max_pred=8)
    assert not fits_vmem(2048, 640, max_pred=1024)
    # int16 H halves the dominant term — a shape the int32 budget
    # rejects fits narrow
    assert not fits_vmem(3072, 896, max_pred=8, score_dtype="int32")
    assert fits_vmem(3072, 896, max_pred=8, score_dtype="int16")


def test_window_sweep_dtype_and_packing_variants_match_oracle():
    """Every (score_dtype, packed) variant of BOTH kernels must equal
    the int32 XLA oracle on the same jobs — the dtype-shrinking and
    base-packing identity contract (params 3,-5,-4: int16-eligible at
    this bucket per ops/dtypes.poa_int16_ok)."""
    from racon_tpu.ops.dtypes import poa_int16_ok
    from racon_tpu.ops.encode import pack_2bit

    rng = random.Random(53)
    N, L, P = 96, 96, 4
    ts, qs = [], []
    for _ in range(5):
        t = bytes(rng.choice(ACGT) for _ in range(rng.randint(40, N - 8)))
        ts.append(t)
        qs.append(mutate(rng, t, 0.15)[:L])
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)
    # one zero-length padding row (nnodes == 0), the batch-pad shape
    codes[-1, :] = 5
    seqs[-1, :] = 5
    lens[-1] = 0
    sinks[-1, :] = 0
    preds[-1, :, :] = -1
    nn = _nnodes_of(codes)
    assert poa_int16_ok(N, L, 3, -5, -4)

    oracle = np.asarray(graph_aligner(N, L, P, 3, -5, -4)(
        codes, preds, centers, sinks, seqs, lens, band))
    for bandw in (0, 32):
        band[:] = bandw
        ref = np.asarray(graph_aligner(N, L, P, 3, -5, -4)(
            codes, preds, centers, sinks, seqs, lens, band))
        if bandw == 0:
            np.testing.assert_array_equal(ref, oracle)
        for dtype in ("int32", "int16"):
            kwargs = {} if dtype == "int32" else {"score_dtype": dtype}
            xla = graph_aligner(N, L, P, 3, -5, -4, **kwargs)
            np.testing.assert_array_equal(
                np.asarray(xla(codes, preds, centers, sinks, seqs, lens,
                               band)), ref, err_msg=f"xla {dtype}")
            xp = graph_aligner(N, L, P, 3, -5, -4, packed_seq=True,
                               **kwargs)
            np.testing.assert_array_equal(
                np.asarray(xp(codes, preds, centers, sinks,
                              pack_2bit(seqs), lens, band)), ref,
                err_msg=f"xla packed {dtype}")
            for packed in (False, True):
                pk = dict(kwargs)
                if packed:
                    pk["packed"] = True
                pls = window_sweep(N, L, P, 3, -5, -4, interpret=True,
                                   **pk)
                c = pack_2bit(codes) if packed else codes
                s = pack_2bit(seqs) if packed else seqs
                np.testing.assert_array_equal(
                    np.asarray(pls(c, preds, centers, sinks, s, lens,
                                   band, nn)), ref,
                    err_msg=f"pallas {dtype} packed={packed} "
                            f"band={bandw}")


def test_int16_identical_at_envelope_boundary_scores():
    """Scores sitting just under the int16 envelope bound: scoring
    params of magnitude 100 put real path scores within ~1% of the
    NEG16 sentinel at this shape — the proof's worst case — and the
    narrow DP must still be bit-identical to int32 (banded AND full
    DP, both kernels)."""
    from racon_tpu.ops.dtypes import poa_int16_ok

    N, L, P = 96, 64, 4
    m, mm, g = 100, -100, -100
    assert poa_int16_ok(N, L, m, mm, g)          # (162)*100 <= 16383
    assert not poa_int16_ok(N + 2, L, m, mm, g)  # one row past the bound

    rng = random.Random(61)
    ts, qs = [], []
    for _ in range(4):
        t = bytes(rng.choice(ACGT) for _ in range(N - 10))
        ts.append(t)
        qs.append(mutate(rng, t, 0.2)[:L])
    qs[0] = b"A" * L if ts[0][:1] != b"A" else b"C" * L  # worst mismatch run
    codes, preds, centers, sinks, seqs, lens, band = linear_graph_inputs(
        ts, qs, N, L, max_pred=P)
    nn = _nnodes_of(codes)
    for bandw in (0, 16):
        band[:] = bandw
        ref = np.asarray(graph_aligner(N, L, P, m, mm, g)(
            codes, preds, centers, sinks, seqs, lens, band))
        narrow = np.asarray(graph_aligner(N, L, P, m, mm, g,
                                          score_dtype="int16")(
            codes, preds, centers, sinks, seqs, lens, band))
        np.testing.assert_array_equal(narrow, ref)
        pls = np.asarray(window_sweep(N, L, P, m, mm, g, interpret=True,
                                      score_dtype="int16")(
            codes, preds, centers, sinks, seqs, lens, band, nn))
        np.testing.assert_array_equal(pls, ref)


def test_pallas_session_engine_byte_identical_to_host():
    """The full device engine with the pallas kernel routed in
    (use_pallas=True) must produce host-identical consensus — the same
    contract the XLA path guarantees."""
    from racon_tpu.native import poa_batch
    from racon_tpu.ops.poa_graph import DeviceGraphPOA

    rng = random.Random(41)
    windows, _ = _make_windows(rng, 6, length=70, depth=5, rate=0.12)
    packed = [_pack(w) for w in windows]

    eng = DeviceGraphPOA(3, -5, -4, max_nodes=192, max_len=128,
                         buckets=((192, 128),), batch_rows=8,
                         use_pallas=True)
    res, statuses = eng.consensus(packed)
    host = poa_batch(packed, 3, -5, -4)
    assert (statuses == 0).all(), statuses.tolist()
    for i, ((dc, dcov), (hc, hcov)) in enumerate(zip(res, host)):
        assert dc == hc, f"window {i}"
        np.testing.assert_array_equal(dcov, hcov)
