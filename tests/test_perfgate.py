"""tools/perfgate.py: the perf regression gate.

The ISSUE's acceptance pair: the gate must PASS on the repo's own
current artifacts and demonstrably FAIL on a synthetic -20% artifact.
Plus the plumbing: artifact-shape extraction (bench wrapper, raw bench
line, servebench), baseline resolution order, and the exit-status
contract (0 pass / 1 regression / 2 broken gate)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perfgate  # noqa: E402


def bench_artifact(value, vs_ratio=None, rc=0, wrapped=True,
                   metric="sample_polish_consensus_throughput_host"):
    inner = {"metric": metric, "value": value, "unit": "windows/sec"}
    if vs_ratio is not None:
        inner["vs_baseline"] = vs_ratio
    return {"n": 1, "rc": rc, "parsed": inner} if wrapped else inner


def write(path, doc):
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return str(path)


# ------------------------------------------------------------- extraction
def test_extract_bench_shapes():
    got = perfgate.extract(bench_artifact(80.0, 1.6))
    assert got["value"] == 80.0 and got["higher_better"]
    assert got["vs_baseline"] == 1.6
    raw = perfgate.extract(bench_artifact(50.0, wrapped=False))
    assert raw["value"] == 50.0


def test_extract_rejects_failed_artifacts():
    with pytest.raises(perfgate.GateError):
        perfgate.extract(bench_artifact(80.0, rc=124))
    with pytest.raises(perfgate.GateError):
        perfgate.extract(bench_artifact(0.0))
    with pytest.raises(perfgate.GateError):
        perfgate.extract(bench_artifact(
            0.0, metric="sample_polish_consensus_throughput_failed"))
    with pytest.raises(perfgate.GateError):
        perfgate.extract({"totally": "unrelated"})


def serve_artifact(p50=0.30, miss_rate=None, p99=None, ttfb=None):
    doc = {"mode": "serve", "warm": {"seq_p50_s": p50},
           "cold": {"p50_s": 0.41}}
    if miss_rate is not None:
        doc["slo"] = {"deadline_hit": 4, "deadline_miss": 0,
                      "expired": 0, "miss_rate": miss_rate}
    if p99 is not None:
        doc["warm"]["p99_s"] = p99
    if ttfb is not None:
        doc["warm"]["ttfb_p50_s"] = ttfb
    return doc


def test_extract_servebench_artifact():
    got = perfgate.extract(serve_artifact())
    assert got["value"] == 0.30
    assert not got["higher_better"]  # p50 seconds: lower is better
    assert "slo_miss_rate" not in got  # legacy artifact: no slo view
    got = perfgate.extract(serve_artifact(miss_rate=0.25))
    assert got["slo_miss_rate"] == 0.25


def test_extract_missing_p50_names_key():
    with pytest.raises(perfgate.GateError, match="warm.seq_p50_s"):
        perfgate.extract({"mode": "serve", "warm": {},
                          "cold": {"p50_s": 0.41}})


# ------------------------------------------------------------- gate math
def test_gate_directions():
    ok, delta = perfgate.gate(95.0, 100.0, 10.0, higher_better=True)
    assert ok and delta == pytest.approx(-5.0)
    ok, _ = perfgate.gate(80.0, 100.0, 10.0, higher_better=True)
    assert not ok  # -20% windows/s
    ok, delta = perfgate.gate(0.33, 0.30, 15.0, higher_better=False)
    assert ok and delta == pytest.approx(-9.09, abs=0.01)
    ok, _ = perfgate.gate(0.40, 0.30, 10.0, higher_better=False)
    assert not ok  # 33% slower p50
    with pytest.raises(perfgate.GateError):
        perfgate.gate(1.0, 0.0, 10.0, higher_better=True)


# ----------------------------------------------------------- end to end
def test_synthetic_minus_20_pct_fails(tmp_path):
    write(tmp_path / "BENCH_r01.json", bench_artifact(100.0, 2.0))
    write(tmp_path / "BENCH_r02.json", bench_artifact(80.0, 1.6))
    # -20% vs the previous round
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", "auto"]) == 1
    # -20% vs the reference-CPU baseline the artifact itself records
    write(tmp_path / "BENCH_r03.json", bench_artifact(40.0, 0.8))
    assert perfgate.main(["--dir", str(tmp_path)]) == 1


def test_within_tolerance_passes(tmp_path):
    write(tmp_path / "BENCH_r01.json", bench_artifact(100.0, 2.0))
    write(tmp_path / "BENCH_r02.json", bench_artifact(95.0, 1.9))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", "auto"]) == 0
    assert perfgate.main(["--dir", str(tmp_path)]) == 0


def test_against_auto_skips_unusable_rounds(tmp_path):
    write(tmp_path / "BENCH_r01.json", bench_artifact(100.0, 2.0))
    write(tmp_path / "BENCH_r02.json", bench_artifact(90.0, rc=124))
    write(tmp_path / "BENCH_r03.json", bench_artifact(95.0, 1.9))
    # r02 timed out: the reference must be r01, and 95 vs 100 passes
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", "auto"]) == 0


def test_baseline_json_published_wins(tmp_path):
    write(tmp_path / "BENCH_r01.json", bench_artifact(80.0, 1.6))
    write(tmp_path / "BASELINE.json",
          {"metric": "x", "published": {"windows_per_sec": 100.0}})
    assert perfgate.main(["--dir", str(tmp_path)]) == 1  # 80 vs 100
    assert perfgate.main(["--dir", str(tmp_path),
                          "--tolerance-pct", "25"]) == 0


def test_explicit_ref_value_and_broken_gate(tmp_path):
    write(tmp_path / "BENCH_r01.json", bench_artifact(80.0))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "80"]) == 0
    # no vs_baseline, no published baseline, no ref: broken gate = 2
    assert perfgate.main(["--dir", str(tmp_path)]) == 2
    assert perfgate.main(["--dir", str(tmp_path / "empty")]) == 2


def test_serve_slo_miss_rate_gated(tmp_path, capsys):
    # miss-free artifact passes with the p50 matching its reference
    write(tmp_path / "BENCH_r01.json", serve_artifact(miss_rate=0.0))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30"]) == 0
    # a deadline-missing wave fails even though the p50 is identical
    write(tmp_path / "BENCH_r02.json", serve_artifact(miss_rate=0.5))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30"]) == 1
    assert "slo miss-rate" in capsys.readouterr().err
    # an explicit laxer limit admits it
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--slo-miss-rate", "0.6"]) == 0


def test_missing_gated_slo_metric_rc2(tmp_path, capsys):
    # legacy serve artifact without an slo view: fine by default...
    write(tmp_path / "BENCH_r01.json", serve_artifact())
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30"]) == 0
    # ...but an EXPLICITLY requested miss-rate gate over it is a broken
    # gate with the dotted key named, not a KeyError traceback
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--slo-miss-rate", "0.0"]) == 2
    assert "slo.miss_rate" in capsys.readouterr().err
    # ...and so is one over a bench artifact, which cannot carry it
    write(tmp_path / "BENCH_r03.json", bench_artifact(100.0, 2.0))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--slo-miss-rate", "0.0"]) == 2
    assert "slo.miss_rate" in capsys.readouterr().err


def test_serve_latency_tail_gated(tmp_path, capsys):
    """p99 / ttfb_p50 gate absolutely via --p99-max / --ttfb-p50-max
    and relatively against the prior round."""
    write(tmp_path / "BENCH_r01.json",
          serve_artifact(p99=2.0, ttfb=0.5))
    # absolute bounds: pass then fail
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--p99-max", "3.0",
                          "--ttfb-p50-max", "1.0"]) == 0
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--p99-max", "1.5"]) == 1
    assert "warm.p99_s" in capsys.readouterr().err
    # relative vs prior round: a 50% worse p99 fails at 10% tolerance
    write(tmp_path / "BENCH_r02.json",
          serve_artifact(p99=3.0, ttfb=0.5))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", "auto"]) == 1
    err = capsys.readouterr().err
    assert "warm.p99_s" in err and "vs prior" in err
    # within tolerance passes both tail metrics
    write(tmp_path / "BENCH_r03.json",
          serve_artifact(p99=3.1, ttfb=0.52))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", "auto"]) == 0


def test_missing_latency_tail_metric_rc2(tmp_path, capsys):
    """The slo.miss_rate convention extends to the new keys: an
    explicitly requested gate over an artifact missing the metric is a
    broken gate naming the dotted key."""
    write(tmp_path / "BENCH_r01.json", serve_artifact())
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--p99-max", "3.0"]) == 2
    assert "warm.p99_s" in capsys.readouterr().err
    assert perfgate.main(["--dir", str(tmp_path),
                          "--ref-value", "0.30",
                          "--ttfb-p50-max", "1.0"]) == 2
    assert "warm.ttfb_p50_s" in capsys.readouterr().err
    # and a bench artifact cannot satisfy a serve latency gate at all
    write(tmp_path / "BENCH_r02.json", bench_artifact(100.0, 2.0))
    assert perfgate.main(["--dir", str(tmp_path),
                          "--p99-max", "3.0"]) == 2
    assert "warm.p99_s" in capsys.readouterr().err


def synth_artifact(wps=6.0):
    return {"mode": "synth",
            "synth": {"windows_per_s": wps, "windows": 20},
            "occupancy": {}}


def test_synth_windows_per_s_floor(tmp_path, capsys):
    art = write(tmp_path / "SYNTH.json", synth_artifact(6.0))
    assert perfgate.main(["--artifact", art,
                          "--windows-per-s-min", "5.0"]) == 0
    assert perfgate.main(["--artifact", art,
                          "--windows-per-s-min", "7.0"]) == 1
    # no floor AND no --against: a synth artifact has no implicit
    # baseline — broken gate, not silent pass
    assert perfgate.main(["--artifact", art]) == 2


def test_synth_relative_vs_prior_round(tmp_path):
    prior = write(tmp_path / "SYNTH_r1.json", synth_artifact(10.0))
    cand = write(tmp_path / "SYNTH_r2.json", synth_artifact(7.0))
    # -30% vs the prior synth round: regression even though the
    # absolute floor passes
    assert perfgate.main(["--artifact", cand, "--against", prior,
                          "--windows-per-s-min", "5.0"]) == 1
    assert perfgate.main(["--artifact", prior, "--against", cand]) == 0


def test_windows_per_s_min_mandatory_names_key(tmp_path, capsys):
    """--windows-per-s-min over an artifact that carries no windows/s
    (a serve artifact) is a BROKEN GATE naming the dotted key — CI must
    distinguish 'artifact changed shape' from 'perf regressed'."""
    art = write(tmp_path / "SERVE.json", serve_artifact(p50=0.30))
    assert perfgate.main(["--artifact", art,
                          "--windows-per-s-min", "5.0"]) == 2
    assert "synth.windows_per_s" in capsys.readouterr().err


def test_fused_host_frac_gated(tmp_path, capsys):
    """Artifacts carrying a `fused` block gate the measured host-
    overhead fraction: default limit whenever the key is present,
    --host-frac-max overriding it; the windows/s floor gates alongside
    (both checks print, either can fail the run)."""
    art = dict(synth_artifact(6.0),
               fused={"mode": "1", "engine": "fused", "launches": 3,
                      "chunks": 3, "device_s": 4.0, "host_s": 1.0,
                      "host_frac": 0.2})
    path = write(tmp_path / "SYNTH.json", art)
    assert perfgate.main(["--artifact", path,
                          "--windows-per-s-min", "5.0"]) == 0
    err = capsys.readouterr().err
    assert "fused.host_frac" in err
    # explicit limit below the measured fraction: regression
    assert perfgate.main(["--artifact", path,
                          "--windows-per-s-min", "5.0",
                          "--host-frac-max", "0.1"]) == 1
    # default gate catches a dispatch loop that went host-bound
    art["fused"]["host_frac"] = 0.9
    path = write(tmp_path / "SYNTH.json", art)
    assert perfgate.main(["--artifact", path,
                          "--windows-per-s-min", "5.0"]) == 1


def test_host_frac_max_mandatory_names_key(tmp_path, capsys):
    """--host-frac-max over an artifact without a fused block is a
    BROKEN GATE naming the dotted key (the slo.miss_rate convention)."""
    art = write(tmp_path / "SYNTH.json", synth_artifact(6.0))
    assert perfgate.main(["--artifact", art,
                          "--windows-per-s-min", "5.0",
                          "--host-frac-max", "0.5"]) == 2
    assert "fused.host_frac" in capsys.readouterr().err


def test_synth_broken_against_stays_broken(tmp_path, capsys):
    """An explicitly requested --against that cannot resolve must stay
    rc 2 even when the absolute floor is also requested — the relative
    comparison was asked for, so it silently not running is a broken
    gate, not a pass."""
    art = write(tmp_path / "SYNTH.json", synth_artifact(6.0))
    missing = str(tmp_path / "nope.json")
    assert perfgate.main(["--artifact", art, "--against", missing,
                          "--windows-per-s-min", "1.0"]) == 2


def test_repo_current_artifacts_pass():
    """The acceptance half: the default invocation against the repo's
    own committed artifacts exits 0."""
    if not perfgate.find_artifacts(REPO):
        pytest.skip("no BENCH artifacts in this checkout")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perfgate.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "[perfgate] PASS" in proc.stderr


# --------------------------------------------------- mesh + scale gates
def scale_artifact(identical=True, balance=1.2, padded=0.05,
                   baseline=0.2, n_devices=8):
    return {
        "mode": "synth",
        "synth": {"windows_per_s": 5.0},
        "mesh": {"n_devices": n_devices, "worker_lanes": 1,
                 "max_devices_env": str(n_devices)},
        "scale": {"identical": identical, "curve": [
            {"n_devices": 1, "windows_per_s": 2.0, "golden_sha": "a"},
            {"n_devices": n_devices, "windows_per_s": 5.0,
             "shard_balance": balance, "padded_frac": padded,
             "padded_frac_full_mesh": baseline, "golden_sha": "a"},
        ]},
    }


def test_cross_mesh_comparison_refused_rc2(tmp_path, capsys):
    """The satellite: a --against reference measured on a different
    mesh is a broken gate naming the mismatched key, never a verdict."""
    ref = bench_artifact(100.0, 2.0)
    ref["parsed"]["mesh"] = {"n_devices": 1, "worker_lanes": 1}
    cand = bench_artifact(95.0, 1.9)
    cand["parsed"]["mesh"] = {"n_devices": 8, "worker_lanes": 1}
    ref_path = write(tmp_path / "BENCH_r01.json", ref)
    write(tmp_path / "BENCH_r02.json", cand)
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", ref_path]) == 2
    assert "mesh.n_devices" in capsys.readouterr().err
    # same n_devices but different serve lane count: also refused
    ref["parsed"]["mesh"] = {"n_devices": 8, "worker_lanes": 2}
    write(tmp_path / "BENCH_r01.json", ref)
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", ref_path]) == 2
    assert "mesh.worker_lanes" in capsys.readouterr().err
    # identical mesh: the comparison proceeds (and passes at -5%)
    ref["parsed"]["mesh"] = {"n_devices": 8, "worker_lanes": 1}
    write(tmp_path / "BENCH_r01.json", ref)
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", ref_path]) == 0


def test_mesh_block_optional_for_legacy_artifacts(tmp_path):
    """Artifacts predating the mesh block still compare (no refusal
    when either side lacks it)."""
    ref = bench_artifact(100.0, 2.0)
    cand = bench_artifact(95.0, 1.9)
    cand["parsed"]["mesh"] = {"n_devices": 8, "worker_lanes": 1}
    ref_path = write(tmp_path / "BENCH_r01.json", ref)
    write(tmp_path / "BENCH_r02.json", cand)
    assert perfgate.main(["--dir", str(tmp_path),
                          "--against", ref_path]) == 0


def test_scale_curve_gates(tmp_path, capsys):
    """Scale artifacts gate shard balance (default 1.5), the strict
    padded-vs-full-mesh-baseline win, and curve byte-identity."""
    art = write(tmp_path / "BENCH_r01.json", scale_artifact())
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0"]) == 0
    # an imbalanced shard split fails the default 1.5 gate
    write(tmp_path / "BENCH_r01.json", scale_artifact(balance=2.0))
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0"]) == 1
    assert "shard_balance" in capsys.readouterr().err
    # ...but passes an explicitly laxer limit
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0",
                          "--scale-balance-max", "2.5"]) == 0
    # padded fraction NOT strictly below the full-mesh baseline fails
    write(tmp_path / "BENCH_r01.json",
          scale_artifact(padded=0.2, baseline=0.2))
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0"]) == 1
    assert "padded_frac" in capsys.readouterr().err
    # diverged FASTA across mesh sizes fails
    write(tmp_path / "BENCH_r01.json", scale_artifact(identical=False))
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0"]) == 1
    assert "scale.identical" in capsys.readouterr().err


def test_scale_balance_max_mandatory_when_requested(tmp_path, capsys):
    """--scale-balance-max over an artifact without a scale block is a
    named-key broken gate (the slo.miss_rate convention)."""
    art = write(tmp_path / "BENCH_r01.json",
                {"mode": "synth", "synth": {"windows_per_s": 5.0}})
    assert perfgate.main(["--dir", str(tmp_path), "--artifact", art,
                          "--windows-per-s-min", "1.0",
                          "--scale-balance-max", "1.5"]) == 2
    assert "scale.curve" in capsys.readouterr().err


def test_audit_overhead_gate(tmp_path, capsys):
    """ISSUE-13 satellite: perfgate gates audit.overhead_pct (default
    2.0 whenever the block is present, --audit-overhead-max mandatory
    rc 2 naming the dotted key) and audit.mismatches == 0."""
    base = ["--ref-value", "1.0", "--tolerance-pct", "50"]

    def audit_artifact(**audit):
        doc = serve_artifact(p50=1.0)
        if audit:
            doc["audit"] = audit
        return doc

    ok = write(tmp_path / "ok.json",
               audit_artifact(overhead_pct=0.7, mismatches=0))
    assert perfgate.main(["--artifact", ok] + base) == 0
    err = capsys.readouterr().err
    assert "audit.overhead_pct" in err and "audit.mismatches" in err
    # over the default 2% budget fails
    slow = write(tmp_path / "slow.json",
                 audit_artifact(overhead_pct=3.4, mismatches=0))
    assert perfgate.main(["--artifact", slow] + base) == 1
    # ANY mismatch on the clean bench workload fails
    corrupt = write(tmp_path / "corrupt.json",
                    audit_artifact(overhead_pct=0.5, mismatches=1))
    assert perfgate.main(["--artifact", corrupt] + base) == 1
    assert "audit.mismatches" in capsys.readouterr().err
    # explicit limit is honored (tighter fails, laxer passes)
    assert perfgate.main(["--artifact", ok,
                          "--audit-overhead-max", "0.5"] + base) == 1
    assert perfgate.main(["--artifact", slow,
                          "--audit-overhead-max", "5.0"] + base) == 0


def test_audit_overhead_max_mandatory_when_requested(tmp_path, capsys):
    """--audit-overhead-max over an artifact without an audit block is
    a named-key broken gate, rc 2 (the slo.miss_rate convention)."""
    plain = write(tmp_path / "plain.json", serve_artifact(p50=1.0))
    assert perfgate.main(["--artifact", plain, "--ref-value", "1.0",
                          "--tolerance-pct", "50",
                          "--audit-overhead-max", "2.0"]) == 2
    assert "audit.overhead_pct" in capsys.readouterr().err


def router_artifact(jobs_per_s=4.0, scaling_x=1.8, identical=True,
                    requeues=0):
    return {"mode": "router", "jobs": 4,
            "router": {"replicas_max": 2, "jobs_per_s": jobs_per_s,
                       "scaling_x": scaling_x, "identical": identical,
                       "requeues": requeues,
                       "curve": [{"replicas": 1, "jobs_per_s": 2.2},
                                 {"replicas": 2,
                                  "jobs_per_s": jobs_per_s}]}}


def test_router_gates(tmp_path, capsys):
    """ISSUE-15 satellite: perfgate gates servebench --router artifacts
    on router.identical and router.requeues == 0 whenever the block is
    present, and on router.scaling_x via --router-scaling-min."""
    ok = write(tmp_path / "ok.json", router_artifact())
    assert perfgate.main(["--artifact", ok]) == 0
    err = capsys.readouterr().err
    assert "router.identical" in err and "router.requeues" in err
    # a diverged merge or a requeue on the healthy bench fleet fails
    diverged = write(tmp_path / "div.json",
                     router_artifact(identical=False))
    assert perfgate.main(["--artifact", diverged]) == 1
    requeued = write(tmp_path / "rq.json", router_artifact(requeues=2))
    assert perfgate.main(["--artifact", requeued]) == 1
    assert "router.requeues" in capsys.readouterr().err
    # the scaling floor gates only when requested, then both ways
    assert perfgate.main(["--artifact", ok,
                          "--router-scaling-min", "1.5"]) == 0
    assert perfgate.main(["--artifact", ok,
                          "--router-scaling-min", "1.9"]) == 1
    assert "router.scaling_x" in capsys.readouterr().err


def test_router_scaling_min_mandatory_when_requested(tmp_path, capsys):
    """--router-scaling-min over an artifact without a router block is
    a named-key broken gate, rc 2 (the slo.miss_rate convention) — and
    so is a router block missing scaling_x."""
    plain = write(tmp_path / "plain.json", serve_artifact(p50=1.0))
    assert perfgate.main(["--artifact", plain, "--ref-value", "1.0",
                          "--tolerance-pct", "50",
                          "--router-scaling-min", "1.5"]) == 2
    assert "router.scaling_x" in capsys.readouterr().err
    doc = router_artifact()
    del doc["router"]["scaling_x"]
    partial = write(tmp_path / "partial.json", doc)
    assert perfgate.main(["--artifact", partial,
                          "--router-scaling-min", "1.5"]) == 2
    assert "router.scaling_x" in capsys.readouterr().err


def test_range_scaling_gate(tmp_path, capsys):
    """ISSUE-18 satellite: the single-job window-range-sharding
    speedup gates via --range-scaling-min (mandatory once requested,
    rc 2 naming the dotted key on an artifact that never
    range-sharded)."""
    doc = router_artifact()
    doc["router"]["range"] = True
    doc["router"]["range_shards"] = 2
    doc["router"]["range_scaling_x"] = 1.7
    ok = write(tmp_path / "range.json", doc)
    assert perfgate.main(["--artifact", ok,
                          "--range-scaling-min", "1.5"]) == 0
    assert "router.range_scaling_x" in capsys.readouterr().err
    assert perfgate.main(["--artifact", ok,
                          "--range-scaling-min", "1.8"]) == 1
    assert "router.range_scaling_x" in capsys.readouterr().err
    # a sweep that never range-sharded carries no key: broken gate
    plain = write(tmp_path / "plain.json", router_artifact())
    assert perfgate.main(["--artifact", plain,
                          "--range-scaling-min", "1.5"]) == 2
    assert "router.range_scaling_x" in capsys.readouterr().err
    # ...and so is the flag over an artifact with no router block
    serve = write(tmp_path / "serve.json", serve_artifact(p50=1.0))
    assert perfgate.main(["--artifact", serve, "--ref-value", "1.0",
                          "--tolerance-pct", "50",
                          "--range-scaling-min", "1.5"]) == 2
    assert "router.range_scaling_x" in capsys.readouterr().err


def ramp_artifact(flat=1.3, jobs_lost=0):
    return {"mode": "ramp", "jobs": 24,
            "autoscale": {"replicas_min": 1, "replicas_max": 4,
                          "jobs": 24, "completed": 24 - jobs_lost,
                          "jobs_lost": jobs_lost,
                          "scale_ups": 3, "scale_downs": 3,
                          "drained_to_min": True,
                          "gold_p99_idle_s": 1.0,
                          "gold_p99_ramp_s": flat,
                          "gold_p99_flat": flat,
                          "replicas_over_time": []}}


def test_ramp_autoscale_gates(tmp_path, capsys):
    """ISSUE-18 satellite: servebench --ramp artifacts gate
    autoscale.jobs_lost == 0 and autoscale.gold_p99_flat (default 2.0
    when the block is present, --ramp-p99-flat-max overriding)."""
    ok = write(tmp_path / "ok.json", ramp_artifact())
    assert perfgate.main(["--artifact", ok]) == 0
    err = capsys.readouterr().err
    assert "autoscale.jobs_lost" in err
    assert "autoscale.gold_p99_flat" in err
    # ANY lost job fails — a scale-event race, never noise
    lossy = write(tmp_path / "lossy.json",
                  ramp_artifact(jobs_lost=1))
    assert perfgate.main(["--artifact", lossy]) == 1
    assert "autoscale.jobs_lost" in capsys.readouterr().err
    # p99 not flat vs the idle floor fails at the default 2.0
    spiky = write(tmp_path / "spiky.json", ramp_artifact(flat=3.5))
    assert perfgate.main(["--artifact", spiky]) == 1
    assert "autoscale.gold_p99_flat" in capsys.readouterr().err
    # explicit limit honored both ways
    assert perfgate.main(["--artifact", spiky,
                          "--ramp-p99-flat-max", "4.0"]) == 0
    assert perfgate.main(["--artifact", ok,
                          "--ramp-p99-flat-max", "1.1"]) == 1


def test_ramp_p99_flat_max_mandatory_when_requested(tmp_path, capsys):
    """--ramp-p99-flat-max over an artifact without an autoscale block
    is a named-key broken gate, rc 2 (the slo.miss_rate convention) —
    and a ramp artifact has no implicit baseline without --against."""
    plain = write(tmp_path / "plain.json", serve_artifact(p50=1.0))
    assert perfgate.main(["--artifact", plain, "--ref-value", "1.0",
                          "--tolerance-pct", "50",
                          "--ramp-p99-flat-max", "2.0"]) == 2
    assert "autoscale.gold_p99_flat" in capsys.readouterr().err
    # a ramp artifact missing the flatness key entirely cannot extract
    with pytest.raises(perfgate.GateError,
                       match="autoscale.gold_p99_flat"):
        perfgate.extract({"mode": "ramp", "autoscale": {}})


def fragment_artifact(identical=True, jobs_per_s=4.0, vs_contig=3.2):
    return {"mode": "fragment", "jobs": 8,
            "fragment": {"identical": identical, "reads": 17,
                         "jobs_per_s": jobs_per_s, "p50_s": 0.4,
                         "p99_s": 0.9, "parts_per_job": 3.0,
                         "vs_contig_x": vs_contig}}


def test_fragment_gates(tmp_path, capsys):
    """ISSUE-20 satellite: servebench --fragment artifacts gate
    fragment.identical (serve bytes == solo kF bytes) and
    fragment.vs_contig_x > 1 whenever the block is present;
    --fragment-jobs-min adds the absolute throughput floor."""
    ok = write(tmp_path / "ok.json", fragment_artifact())
    assert perfgate.main(["--artifact", ok]) == 0
    err = capsys.readouterr().err
    assert "fragment.identical" in err
    assert "fragment.vs_contig_x" in err
    # divergence from the solo bytes fails — serving is a transport,
    # never an answer change
    div = write(tmp_path / "div.json",
                fragment_artifact(identical=False))
    assert perfgate.main(["--artifact", div]) == 1
    assert "fragment.identical" in capsys.readouterr().err
    # a fragment rate at or below the contig wave fails
    slow = write(tmp_path / "slow.json",
                 fragment_artifact(vs_contig=0.8))
    assert perfgate.main(["--artifact", slow]) == 1
    assert "fragment.vs_contig_x" in capsys.readouterr().err
    # explicit floor honored both ways
    assert perfgate.main(["--artifact", ok,
                          "--fragment-jobs-min", "2.0"]) == 0
    assert perfgate.main(["--artifact", ok,
                          "--fragment-jobs-min", "99.0"]) == 1


def test_fragment_jobs_min_mandatory_when_requested(tmp_path, capsys):
    """--fragment-jobs-min over an artifact without a fragment block
    is a named-key broken gate, rc 2 (the slo.miss_rate convention) —
    and a fragment artifact has no implicit baseline without
    --against."""
    plain = write(tmp_path / "plain.json", serve_artifact(p50=1.0))
    assert perfgate.main(["--artifact", plain, "--ref-value", "1.0",
                          "--tolerance-pct", "50",
                          "--fragment-jobs-min", "1.0"]) == 2
    assert "fragment.jobs_per_s" in capsys.readouterr().err
    # a fragment artifact missing the throughput key cannot extract
    with pytest.raises(perfgate.GateError,
                       match="fragment.jobs_per_s"):
        perfgate.extract({"mode": "fragment", "fragment": {}})
