"""Async dispatch pipeline tests (racon_tpu/pipeline).

The pipeline overlaps host pack, device compute, host unpack and
host-fallback work (the stream-overlap role of the reference's per-batch
CUDA streams, cudapolisher.cpp:165-199). The contracts tested here:

  - depth=0 (synchronous bisection path) and depth>=1 (threaded) produce
    BYTE-IDENTICAL output through every integration (fused device engine,
    host POA engine, device aligner, whole polisher);
  - a device chunk that raises mid-pipeline is routed to the host
    fallback, which completes every window (the per-window GPU->CPU
    discipline, cudapolisher.cpp:354-383) — unless RACON_TPU_STRICT;
  - per-stage wall-clock counters accumulate for every stage that ran.
"""

import gzip
import random
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack  # noqa: E402

from racon_tpu.native import nw_cigar_batch, poa_batch  # noqa: E402
from racon_tpu.ops.align import BatchAligner  # noqa: E402
from racon_tpu.ops.poa import BatchPOA  # noqa: E402
from racon_tpu.ops.poa_fused import FusedPOA  # noqa: E402
from racon_tpu.pipeline import DispatchPipeline, PipelineStats  # noqa: E402

ACGT = b"ACGT"


# ------------------------------------------------------------- unit level

@pytest.mark.parametrize("depth", [0, 1, 2, 3])
def test_stage_order_and_stats(depth):
    """Items traverse pack -> dispatch -> wait -> unpack in order at every
    depth; unpack order equals dispatch order (deterministic assembly)."""
    pl = DispatchPipeline(depth=depth)
    seen = []
    pl.run(range(9),
           pack=lambda i: i * 10,
           dispatch=lambda i, ops: ops + 1,
           wait=lambda h: h + 1,
           unpack=lambda i, r: seen.append((i, r)))
    pl.close()
    assert seen == [(i, i * 10 + 2) for i in range(9)]
    s = pl.stats.snapshot()
    assert s["chunks"] == 9 and s["errors"] == 0
    for k in ("pack_s", "device_s", "unpack_s", "fallback_s"):
        assert s[k] >= 0.0


def test_simulated_device_latency_env(monkeypatch):
    """RACON_TPU_DEVICE_LATENCY_S stalls each chunk's result wait by the
    configured round-trip (the device-dominated bench posture), charges
    the stall to device seconds, and strict-parses."""
    monkeypatch.setenv("RACON_TPU_DEVICE_LATENCY_S", "0.05")
    pl = DispatchPipeline(depth=0)
    assert pl.device_latency_s == 0.05
    seen = []
    t0 = time.perf_counter()
    pl.run(range(4), pack=lambda i: i, dispatch=lambda i, ops: ops,
           wait=lambda h: h, unpack=lambda i, r: seen.append(r))
    wall = time.perf_counter() - t0
    pl.close()
    assert seen == [0, 1, 2, 3]  # output untouched, only paced
    assert wall >= 0.2  # 4 chunks x 50 ms
    assert pl.stats.snapshot()["device_s"] >= 0.2

    from racon_tpu.errors import RaconError
    for bad in ("fast", "-1"):
        monkeypatch.setenv("RACON_TPU_DEVICE_LATENCY_S", bad)
        with pytest.raises(RaconError, match="DEVICE_LATENCY_S"):
            DispatchPipeline(depth=0)
    monkeypatch.delenv("RACON_TPU_DEVICE_LATENCY_S")
    assert DispatchPipeline(depth=0).device_latency_s == 0.0

    # the proportional twin: each chunk's dispatch is followed by a
    # sleep of X times its measured duration (a simulated device whose
    # round-trip scales with batch size)
    monkeypatch.setenv("RACON_TPU_DEVICE_LATENCY_X", "4")
    pl = DispatchPipeline(depth=0)
    assert pl.device_latency_x == 4.0
    seen = []
    t0 = time.perf_counter()
    pl.run(range(2), pack=lambda i: i,
           dispatch=lambda i, ops: time.sleep(0.05) or ops,
           wait=lambda h: h, unpack=lambda i, r: seen.append(r))
    wall = time.perf_counter() - t0
    pl.close()
    assert seen == [0, 1]
    assert wall >= 0.4  # 2 chunks x (50 ms dispatch + 4x sleep)
    monkeypatch.setenv("RACON_TPU_DEVICE_LATENCY_X", "no")
    with pytest.raises(RaconError, match="DEVICE_LATENCY_X"):
        DispatchPipeline(depth=0)


@pytest.mark.parametrize("depth", [0, 2])
def test_error_without_handler_propagates(depth):
    pl = DispatchPipeline(depth=depth)

    def bad_dispatch(i, ops):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        pl.run([1, 2], lambda i: i, bad_dispatch, lambda h: h,
               lambda i, r: None)
    pl.close()
    assert pl.stats.snapshot()["errors"] >= 1


@pytest.mark.parametrize("depth", [0, 2])
def test_error_handler_skips_chunk_and_continues(depth):
    pl = DispatchPipeline(depth=depth)
    failed, done = [], []

    def dispatch(i, ops):
        if i == 3:
            raise RuntimeError("chunk 3 died")
        return ops

    pl.run(range(6), lambda i: i, dispatch, lambda h: h,
           lambda i, r: done.append(i),
           on_error=lambda i, exc: failed.append(i))
    pl.close()
    assert failed == [3]
    assert sorted(done) == [0, 1, 2, 4, 5]
    assert pl.stats.snapshot()["errors"] == 1


@pytest.mark.parametrize("depth", [0, 2])
def test_fallback_pool(depth):
    """submit_fallback runs host work concurrently (inline at depth 0);
    drain re-raises the first failure; seconds accumulate."""
    pl = DispatchPipeline(depth=depth)
    futs = [pl.submit_fallback(lambda k=k: k * k) for k in range(4)]
    pl.drain_fallback()
    assert [f.result() for f in futs] == [0, 1, 4, 9]

    bad = pl.submit_fallback(lambda: 1 / 0)
    with pytest.raises(ZeroDivisionError):
        pl.drain_fallback()
    assert bad.exception() is not None
    pl.drain_fallback(ignore_errors=True)  # nothing pending: no-op
    assert pl.stats.snapshot()["fallback_s"] >= 0.0

    # map_fallback: chunked submit half of the reject protocol
    fb = pl.map_fallback(list(range(10)), lambda sub: [i * 2 for i in sub],
                         chunk=4)
    pl.drain_fallback()
    assert [len(sub) for sub, _ in fb] == [4, 4, 2]
    got = [x for sub, fut in fb for x in fut.result()]
    assert got == [i * 2 for i in range(10)]
    pl.close()


def test_base_exception_mid_run_does_not_hang():
    """A BaseException escaping the dispatch loop (the Ctrl-C shape) with
    both bounded queues full must clean up and re-raise promptly instead
    of deadlocking on a worker blocked in a queue put."""
    pl = DispatchPipeline(depth=1)  # tightest queues: worst case

    def dispatch(i, ops):
        if i == 2:
            raise KeyboardInterrupt
        return ops

    t0 = time.perf_counter()
    with pytest.raises(KeyboardInterrupt):
        pl.run(range(50), lambda i: i, dispatch,
               lambda h: time.sleep(0.02), lambda i, r: None)
    assert time.perf_counter() - t0 < 10  # returned, did not hang
    pl.close()


def test_stats_shared_across_pipelines():
    """One PipelineStats instance aggregates several phases' pipelines —
    the polisher wires its align and consensus phases this way."""
    stats = PipelineStats()
    for _ in range(2):
        pl = DispatchPipeline(depth=2, stats=stats)
        pl.run(range(3), lambda i: i, lambda i, o: o, lambda h: h,
               lambda i, r: None)
        pl.close()
    assert stats.snapshot()["chunks"] == 6


def test_overlap_actually_happens():
    """At depth 2 a slow wait must overlap the next item's pack: total
    wall < sum of stage times. (Generous margin — CI boxes are noisy.)"""
    pl = DispatchPipeline(depth=2)
    t0 = time.perf_counter()
    pl.run(range(4),
           pack=lambda i: time.sleep(0.05),
           dispatch=lambda i, ops: i,
           wait=lambda h: time.sleep(0.05),
           unpack=lambda i, r: None)
    wall = time.perf_counter() - t0
    pl.close()
    s = pl.stats.snapshot()
    stage_sum = s["pack_s"] + s["device_s"] + s["unpack_s"]
    assert stage_sum >= 0.35  # 8 x 0.05s of stage work happened
    assert wall < stage_sum * 0.85  # ...in less wall time than its sum


# ------------------------------------------------------ engine integration

@pytest.fixture
def fused_fixture(monkeypatch):
    # one-device mesh so batch_rows=4 is not rounded up to the 8-virtual-
    # device width (chunk/launch counts below assume B=4); sharded-vs-
    # single equivalence is covered by test_fused_sharded_matches_single
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    packed = [_pack(w) for w in windows]
    host = poa_batch(packed, 3, -5, -4, n_threads=2)
    kw = dict(max_nodes=768, max_len=384, batch_rows=4,
              depth_buckets=(4, 8))
    return packed, host, kw


def test_fused_depth0_vs_depth2_byte_identical(fused_fixture):
    packed, host, kw = fused_fixture
    outs = {}
    for depth in (0, 2):
        eng = FusedPOA(3, -5, -4, num_threads=2, **kw)
        with DispatchPipeline(depth=depth) as pl:
            res, st = eng.consensus([list(p) for p in packed], pipeline=pl)
            stats = pl.stats.snapshot()
        assert (st == 0).all(), st.tolist()
        assert stats["chunks"] == 3 and stats["launches"] == 6
        outs[depth] = res
    for (c0, v0), (c2, v2), (ch, vh) in zip(outs[0], outs[2], host):
        assert c0 == c2 == ch
        np.testing.assert_array_equal(v0, v2)
        np.testing.assert_array_equal(v0, vh)


def test_fused_chunk_failure_falls_back_to_host(fused_fixture, monkeypatch,
                                                capsys):
    """A device chunk raising mid-pipeline must not lose windows: the
    fallback pool completes every one, byte-identical to the host engine."""
    packed, host, kw = fused_fixture
    monkeypatch.delenv("RACON_TPU_STRICT", raising=False)
    eng = FusedPOA(3, -5, -4, num_threads=2, **kw)
    calls = {"n": 0}
    orig = eng._call

    def flaky(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 3:  # 2 chained calls per chunk: kill chunk 2
            raise RuntimeError("injected device fault")
        return orig(*args, **kwargs)

    monkeypatch.setattr(eng, "_call", flaky)
    with DispatchPipeline(depth=2) as pl:
        res, st = eng.consensus([list(p) for p in packed], pipeline=pl)
        stats = pl.stats.snapshot()
    assert "device chunk failed" in capsys.readouterr().err
    assert stats["errors"] == 1
    assert (st == 1).sum() == 4  # the failed chunk's windows, host-built
    assert (st == 0).sum() == 6
    assert eng.n_fallback == 4
    for (c, v), (ch, vh) in zip(res, host):  # nothing lost, nothing wrong
        assert c == ch
        np.testing.assert_array_equal(v, vh)


def test_fused_persistent_failure_trips_circuit_breaker(fused_fixture,
                                                        monkeypatch):
    """A device failing EVERY chunk (dead tunnel, OOM) must not burn a
    pack+dispatch attempt per chunk: after 3 consecutive chunk failures
    the device pass aborts — restoring the whole-batch fallback — and
    BatchPOA's non-strict catch still host-polishes every window."""
    from racon_tpu.ops import poa_fused

    packed, host, kw = fused_fixture
    monkeypatch.delenv("RACON_TPU_STRICT", raising=False)
    monkeypatch.setenv("RACON_TPU_ENGINE", "fused")
    monkeypatch.setenv("RACON_TPU_FUSED_FALLBACK", "host")

    calls = {"n": 0}

    class DeadDevice(poa_fused.FusedPOA):
        def __init__(self, *a, **k):
            k.update(kw)
            super().__init__(*a, **k)

        def _call(self, *a, **k):
            calls["n"] += 1
            raise RuntimeError("device gone")

    monkeypatch.setattr(poa_fused, "FusedPOA", DeadDevice)
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    eng = BatchPOA(3, -5, -4, 220, num_threads=2, device_batches=1)
    eng.generate_consensus(windows, trim=False)
    assert calls["n"] == 3  # breaker tripped: not one attempt per chunk
    for w, (hc, _) in zip(windows, host):
        assert w.polished and w.consensus == hc


def test_fused_chunk_failure_strict_raises(fused_fixture, monkeypatch):
    packed, _, kw = fused_fixture
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    eng = FusedPOA(3, -5, -4, num_threads=2, **kw)
    monkeypatch.setattr(
        eng, "_call",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("injected")))
    with DispatchPipeline(depth=2) as pl:
        with pytest.raises(RuntimeError, match="injected"):
            eng.consensus([list(p) for p in packed], pipeline=pl)


def test_host_engine_depth0_vs_depth2_byte_identical():
    """BatchPOA's host chunk loop through the pipeline: same bytes at
    both depths (pack/native-call/trim really did stay independent)."""
    outs = {}
    for depth in (0, 2):
        rng = random.Random(17)
        windows, _ = _make_windows(rng, 12, length=220, depth=6, rate=0.1)
        with DispatchPipeline(depth=depth) as pl:
            eng = BatchPOA(3, -5, -4, 220, num_threads=2, pipeline=pl)
            eng.generate_consensus(windows, trim=False)
            stats = pl.stats.snapshot()
        assert stats["launches"] >= 1 and stats["device_s"] > 0
        outs[depth] = [(w.consensus, w.polished) for w in windows]
    assert outs[0] == outs[2]


def test_aligner_depth0_vs_depth2_with_reject_fallback():
    """BatchAligner through the pipeline: identical accept/reject results
    at both depths, on_reject fires for unbucketable AND band-clipped
    pairs, and the fallback pool host-aligns them concurrently — the
    polisher's exact wiring."""
    rng = np.random.default_rng(7)
    bases = np.frombuffer(ACGT, np.uint8)

    def rand(n):
        return bytes(rng.choice(bases, n))

    def mut(seq):
        out = bytearray()
        for ch in seq:
            r = rng.random()
            if r < 0.03:
                continue
            out.append(int(bases[rng.integers(4)]) if r < 0.08 else ch)
            if rng.random() < 0.03:
                out.append(int(bases[rng.integers(4)]))
        return bytes(out)

    pairs = []
    for _ in range(16):
        t = rand(int(rng.integers(200, 480)))
        pairs.append((mut(t), t))
    pairs.append((rand(900), rand(880)))  # beyond max_length: upfront reject
    long_idx = len(pairs) - 1

    outs = {}
    for depth in (0, 2):
        al = BatchAligner(band_width=64, max_length=512)
        fb = []
        with DispatchPipeline(depth=depth) as pl:
            def on_reject(idxs, pl=pl, fb=fb):
                fb.extend(pl.map_fallback(
                    idxs, lambda sub: nw_cigar_batch(
                        [pairs[i] for i in sub], n_threads=2)))

            runs = al.align(list(pairs), pipeline=pl, on_reject=on_reject)
            pl.drain_fallback()
        rejected = sorted(i for sub, _ in fb for i in sub)
        assert long_idx in rejected
        cigars = {}
        for sub, fut in fb:
            for i, c in zip(sub, fut.result()):
                cigars[i] = c
        # complete coverage: every pair has device runs XOR a fallback CIGAR
        for i in range(len(pairs)):
            assert (runs[i] is not None) != (i in cigars)
            if i in cigars:
                assert cigars[i]
        outs[depth] = (runs, rejected, cigars)
    assert outs[0] == outs[2]


# --------------------------------------------------- polisher end-to-end

def _synth_dataset(tmp_path, rng):
    """Compact ONT-style synthetic polishing job (the test_ngs recipe)."""
    truth = bytes(rng.choice(ACGT) for _ in range(3000))

    def mutate(s, rate):
        out = bytearray()
        for c in s:
            r = rng.random()
            if r < rate / 3:
                continue
            if r < 2 * rate / 3:
                out.append(rng.choice(ACGT))
                out.append(c)
                continue
            if r < rate:
                out.append(rng.choice(ACGT))
                continue
            out.append(c)
        return bytes(out)

    draft = mutate(truth, 0.04)
    reads, paf = [], []
    read_len, step = 700, 120
    for start in range(0, len(truth) - read_len, step):
        read = mutate(truth[start:start + read_len], 0.05)
        name = f"r{start}"
        reads.append((name, read))
        t_begin = min(start, len(draft) - 1)
        t_end = min(start + read_len, len(draft))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t+\tdraft\t"
                   f"{len(draft)}\t{t_begin}\t{t_end}\t{read_len}\t"
                   f"{read_len}\t60")
    reads_path = tmp_path / "reads.fasta.gz"
    with gzip.open(reads_path, "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    paf_path = tmp_path / "ovl.paf.gz"
    with gzip.open(paf_path, "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    draft_path = tmp_path / "draft.fasta.gz"
    with gzip.open(draft_path, "wb") as f:
        f.write(b">draft\n" + draft + b"\n")
    return reads_path, paf_path, draft_path


def test_polisher_depth0_vs_depth2_end_to_end(tmp_path):
    """The whole pipeline (host engine + device aligner + fallback pool)
    at depth 0 vs depth 2: identical FASTA out, and the stage counters
    populated — the acceptance contract, on synthetic data so it runs
    without the sample fixture."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    paths = _synth_dataset(tmp_path, random.Random(23))
    outs, stats = {}, {}
    for depth in (0, 2):
        p = create_polisher(*(str(x) for x in paths), PolisherType.kC,
                            500, -1.0, 0.3, num_threads=2,
                            tpu_aligner_batches=1,
                            tpu_pipeline_depth=depth)
        p.initialize()
        outs[depth] = [(s.name, s.data) for s in p.polish()]
        stats[depth] = p.stage_stats
        assert p.n_aligner_pairs > 0
        assert (p.n_aligner_device + p.n_aligner_host_fallback
                == p.n_aligner_pairs)
    assert outs[0] == outs[2]
    for depth in (0, 2):
        s = stats[depth]
        assert s["launches"] >= 1 and s["chunks"] >= 1
        assert s["device_s"] > 0  # a dead pipeline would read ~0 here


DATA = "/root/reference/test/data/"
sample_data = pytest.mark.skipif(
    not __import__("os").path.isdir(DATA),
    reason="reference sample data not available")


@sample_data
def test_sample_host_depth2_matches_committed_golden(monkeypatch):
    """Acceptance pin on the real sample: the depth-2 pipelined host run
    reproduces the committed synchronous golden byte-for-byte."""
    import os

    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    p = create_polisher(
        DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.paf.gz",
        DATA + "sample_layout.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
        True, 5, -4, -8, num_threads=4, tpu_pipeline_depth=2)
    p.initialize()
    out = bytearray()
    for seq in p.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    golden = os.path.join(os.path.dirname(__file__), "data",
                          "sample_golden.fasta")
    with open(golden, "rb") as fh:
        assert bytes(out) == fh.read()


@sample_data
def test_sample_fused_depth0_vs_depth2(monkeypatch):
    """Fused engine on real data (the 24 shallowest sample windows, the
    affordable slice the default suite already compiles): depth 0 and
    depth 2 must agree byte-for-byte."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    p = create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.paf.gz",
                        DATA + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = sorted((w for w in p.windows if len(w.sequences) >= 3),
                  key=lambda w: len(w.sequences))[:24]
    packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
                w.positions[i][1]) for i in range(len(w.sequences))]
              for w in wins]
    outs = {}
    for depth in (0, 2):
        eng = FusedPOA(5, -4, -8, num_threads=2, batch_rows=8)
        with DispatchPipeline(depth=depth) as pl:
            res, st = eng.consensus([list(p) for p in packed],
                                    fallback=False, pipeline=pl)
        assert (st == 0).all()
        outs[depth] = res
    for (c0, v0), (c2, v2) in zip(outs[0], outs[2]):
        assert c0 == c2
        np.testing.assert_array_equal(v0, v2)
