"""Preemptive QoS tests (queue + batcher + server + router) — the
ISSUE's pinned contracts:

  - a preempted-then-resumed job's polished FASTA is byte-identical to
    the solo path, over a unix socket, TCP, and a 2-replica router
    (window cache on and off) — window withdrawal parks entries with
    their ORIGINAL arrival sequence, so per-window consensus and the
    oldest-window guarantee both survive the round trip;
  - speculative deadline-abort fails a doomed job typed
    (`deadline-doomed`, carrying predicted/remaining seconds) at
    ADMISSION and again MID-RUN at an iteration boundary, and the
    admission check is priority-aware (a gold job is never doomed by a
    lower-class backlog it would pop past);
  - the `cancel` RPC reaches queued jobs (dequeued, typed response
    through the waiting submitter), running jobs (ticket-error
    withdrawal / round-boundary flag), and — through the router — a
    parent cancel or a doomed child fans cancels to the sibling shards;
  - requeued router shards inherit the REMAINING parent deadline
    budget, never a reset one;
  - burst tokens let a tenant briefly exceed its hard quota, refilled
    at its DRR weight;
  - `pack_iteration` never starves the oldest window under
    preempt/resume churn (withdrawn entries keep their original age);
  - with no QoS knob armed, the scrape, journal and stats surfaces are
    byte-identical to the pre-QoS server (no new families, no new
    events, no `qos` block).
"""

from __future__ import annotations

import os
import random
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from obsreport import check_preemptions
from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.obs.journal import check_consistency, read_journal
from racon_tpu.sched import pack_iteration
from racon_tpu.serve import (PolishClient, PolishRouter, PolishServer,
                             make_synth_dataset)
from racon_tpu.serve.client import DeadlineDoomed as ClientDoomed
from racon_tpu.serve.client import JobCancelled, ServeError
from racon_tpu.serve.protocol import ProtocolError, recv_frame, send_frame
from racon_tpu.serve.queue import (DeadlineDoomed, Job, JobQueue,
                                   TenantQuotaExceeded)

QOS_EVENTS = {"preempted", "resumed", "cancelled", "deadline-doomed"}


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    return make_synth_dataset(str(tmp_path_factory.mktemp("qos_data")))


@pytest.fixture(scope="module")
def dataset2(tmp_path_factory):
    """Two independent contigs — enough to shard across 2 replicas."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("qos_data2")),
                              contigs=2)


def polish_solo(paths) -> bytes:
    p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


@pytest.fixture(scope="module")
def solo_bytes(dataset):
    return polish_solo(dataset)


@pytest.fixture(scope="module")
def solo2(dataset2):
    return polish_solo(dataset2)


def _serve_pair(tmp_path_factory, transport, **kw):
    kw.setdefault("warmup", False)
    if transport == "tcp":
        srv = PolishServer(port=0, **kw).start()
        return srv, PolishClient(port=srv.config.port)
    sock = str(tmp_path_factory.mktemp("qos_sock") / "s.sock")
    srv = PolishServer(socket_path=sock, **kw).start()
    return srv, PolishClient(socket_path=sock)


def _wait_for(cond, deadline_s: float = 60.0, msg: str = "condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


def _job(id_: str, priority: int = 0, deadline_s: float | None = None,
         tenant: str = "", trace_id: str | None = None) -> Job:
    return Job(id_, "s.fa", "o.paf", "t.fa", {}, priority=priority,
               deadline_s=deadline_s, tenant=tenant, trace_id=trace_id)


# ------------------------------------------------------- burst-token unit
def test_burst_tokens_admit_over_quota_and_refill_at_weight():
    q = JobQueue(32, workers=1, tenant_quota=1, tenant_burst=2)
    q.submit(_job("j0", tenant="t"))          # the hard quota slot
    q.submit(_job("j1", tenant="t"))          # burst token 1 (bucket
    q.submit(_job("j2", tenant="t"))          # starts FULL), token 2
    assert q.burst_admits == 2
    with pytest.raises(TenantQuotaExceeded):
        q.submit(_job("j3", tenant="t"))      # bucket empty
    # refill rides the tenant's DRR weight (tokens/second), capped at
    # capacity: back-date the bucket 3s -> 3 * weight(1.0) earned, but
    # only `tenant_burst` bankable
    q._burst["t"] = [0.0, time.monotonic() - 3.0]
    q.submit(_job("j4", tenant="t"))
    assert q.burst_admits == 3
    snap = q.snapshot()
    assert snap["burst_admits"] == 3          # armed-only snapshot field
    # an unarmed queue never grows the field (surface identity)
    assert "burst_admits" not in JobQueue(4).snapshot()


# -------------------------------------------------- doomed-admission unit
def test_doomed_admission_is_priority_aware():
    q = JobQueue(32, workers=1, abort_margin=0.0)
    q._ema_service_s = 5.0
    for i in range(3):                        # low-class backlog
        q.submit(_job(f"free{i}", priority=0))
    # gold sees NOTHING at-or-above its class ahead: predicted = one
    # service time = 5s <= 12s remaining -> admitted
    q.submit(_job("gold", priority=5, deadline_s=12.0))
    # the same deadline at priority 0 queues behind 4 jobs:
    # predicted = 5 * 5 / 1 = 25s > 12s -> doomed, typed with both sides
    with pytest.raises(DeadlineDoomed) as exc:
        q.submit(_job("late", priority=0, deadline_s=12.0))
    assert exc.value.phase == "admission"
    assert exc.value.predicted_s == pytest.approx(25.0, rel=0.01)
    assert exc.value.remaining_s <= 12.0
    # unarmed queue admits the identical job (default byte-identity)
    q2 = JobQueue(32, workers=1)
    q2._ema_service_s = 5.0
    for i in range(3):
        q2.submit(_job(f"f{i}", priority=0))
    q2.submit(_job("late2", priority=0, deadline_s=12.0))


# ------------------------------------------------------- queue.cancel unit
def test_queue_cancel_dequeues_and_answers_typed():
    q = JobQueue(8)
    q.submit(_job("keep", trace_id="t-keep"))
    victim = _job("gone", trace_id="t-gone")
    q.submit(victim)
    assert q.cancel(job_id="nope") is None
    got = q.cancel(trace_id="t-gone")
    assert got is victim
    assert got.event.is_set()                 # waiter woken immediately
    assert got.response["type"] == "error"
    assert got.response["code"] == "cancelled"
    assert q.counters["expired"] == 1         # accounted like an expiry
    assert len(q) == 1
    assert q.pop(timeout=0.2).id == "keep"    # survivor still pops


# --------------------------------------------- no-starvation under churn
def test_pack_iteration_never_starves_oldest_under_preempt_churn():
    """Property-style: drive pack_iteration through seeded random
    arrive/withdraw/resume churn — withdrawn items re-enter with their
    ORIGINAL age, exactly what batcher.resume_job restores — and the
    globally-oldest pooled item must ship in EVERY iteration."""
    rng = random.Random(0xC0FFEE)
    next_age = 0
    pool: list[tuple[int, int]] = []          # (shape, age)
    parked: list[tuple[int, int]] = []
    for _ in range(300):
        for _ in range(rng.randrange(0, 6)):  # arrivals
            pool.append((rng.randrange(0, 50), next_age))
            next_age += 1
        if pool and rng.random() < 0.4:       # preempt: park a slice
            k = rng.randrange(1, len(pool) + 1)
            rng.shuffle(pool)
            parked.extend(pool[:k])
            del pool[:k]
        if parked and rng.random() < 0.6:     # resume: original ages
            k = rng.randrange(1, len(parked) + 1)
            pool.extend(parked[:k])
            del parked[:k]
        if not pool:
            continue
        cap = rng.choice([1, 2, 3, 8])
        lanes = rng.choice([1, 1, 2, 4])
        batch, rest = pack_iteration(pool, cap,
                                     shape_key=lambda it: it[0],
                                     age_key=lambda it: it[1],
                                     lane_multiple=lanes)
        assert batch, "non-empty pool must yield a batch"
        oldest = min(pool, key=lambda it: it[1])
        assert oldest in batch, (
            f"oldest item {oldest} starved (cap={cap}, lanes={lanes})")
        assert sorted(batch + rest) == sorted(pool)  # nothing lost
        pool = rest


# ------------------------------------- preempt/resume byte identity (e2e)
@pytest.mark.parametrize("transport,wincache", [("unix", False),
                                                ("unix", True),
                                                ("tcp", False),
                                                ("tcp", True)])
def test_preempt_resume_byte_identity(dataset, solo_bytes,
                                      tmp_path_factory, tmp_path,
                                      transport, wincache):
    """A running free-tenant job preempted by a gold job resumes and
    still produces byte-identical FASTA, on both transports, cache on
    and off; the journal balances every `preempted` with a `resumed`."""
    journal = str(tmp_path / "qos.jsonl")
    srv, cl = _serve_pair(tmp_path_factory, transport, workers=1,
                          preempt=True, wincache=wincache,
                          journal=journal)
    results: dict[str, bytes] = {}
    errors: list[Exception] = []

    def go(tag, **kw):
        try:
            results[tag] = cl.submit(*dataset, tenant=tag, **kw).fasta
        except Exception as exc:  # noqa: BLE001 — asserted below
            errors.append(exc)

    try:
        srv.batcher.hold()
        t_free = threading.Thread(target=go, args=("free",))
        t_free.start()
        _wait_for(lambda: len(srv._running_jobs) >= 1,
                  msg="free job running")
        time.sleep(0.3)  # let its windows pool behind the hold
        t_gold = threading.Thread(target=go, args=("gold",),
                                  kwargs={"priority": 5})
        t_gold.start()
        _wait_for(lambda: srv.qos["preemptions"] >= 1,
                  msg="gold admission preempting the free job")
        srv.batcher.release()
        t_free.join(timeout=120)
        t_gold.join(timeout=120)
        assert not errors, f"submits failed: {errors}"
        assert results["free"] == solo_bytes
        assert results["gold"] == solo_bytes
    finally:
        srv.batcher.release()
        srv.drain(timeout=30)
    entries = read_journal(journal)
    events = [e["event"] for e in entries]
    assert "preempted" in events and "resumed" in events
    assert check_preemptions(entries) == []
    assert check_consistency(entries) == []


def test_preempt_resume_byte_identity_through_router(dataset2, solo2,
                                                     tmp_path):
    """The same contract across a 2-replica router: a gold job's shards
    preempt the free job's shards on BOTH replicas; both merged outputs
    stay byte-identical to the solo run."""
    socks = [str(tmp_path / f"rep{i}.sock") for i in range(2)]
    journals = [str(tmp_path / f"rep{i}.jsonl") for i in range(2)]
    reps = [PolishServer(socket_path=s, workers=1, warmup=False,
                         preempt=True, journal=j).start()
            for s, j in zip(socks, journals)]
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "r.sock"),
                          health_interval_s=0.2).start()
    cl = PolishClient(socket_path=router.config.socket_path)
    results: dict[str, bytes] = {}
    errors: list[Exception] = []

    def go(tag, **kw):
        try:
            results[tag] = cl.submit(*dataset2, tenant=tag, **kw).fasta
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    try:
        for r in reps:
            r.batcher.hold()
        t_free = threading.Thread(target=go, args=("free",))
        t_free.start()
        _wait_for(lambda: sum(len(r._running_jobs) for r in reps) >= 2,
                  msg="free shards running on both replicas")
        time.sleep(0.3)
        t_gold = threading.Thread(target=go, args=("gold",),
                                  kwargs={"priority": 5})
        t_gold.start()
        # the router propagates priority verbatim onto the child
        # frames, so each replica preempts its own free shard
        _wait_for(lambda: sum(r.qos["preemptions"] for r in reps) >= 1,
                  msg="replica-side preemption via routed priority")
        for r in reps:
            r.batcher.release()
        t_free.join(timeout=120)
        t_gold.join(timeout=120)
        assert not errors, f"routed submits failed: {errors}"
        assert results["free"] == solo2
        assert results["gold"] == solo2
    finally:
        for r in reps:
            r.batcher.release()
        router.drain()
        for r in reps:
            r.drain(timeout=30)
    for j in journals:
        assert check_preemptions(read_journal(j)) == []


# --------------------------------------------- deadline-abort (e2e, typed)
def test_doomed_at_admission_typed_to_client(dataset, solo_bytes,
                                             tmp_path_factory):
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1,
                          abort_margin=0.0)
    try:
        srv.queue._ema_service_s = 100.0      # a busy server's EMA
        with pytest.raises(ClientDoomed) as exc:
            cl.submit(*dataset, deadline_s=0.5)
        assert exc.value.predicted_s == pytest.approx(100.0, rel=0.05)
        assert exc.value.remaining_s <= 0.5
        assert srv.qos["aborted_doomed"] >= 1
        assert "aborted_doomed" in cl.scrape()  # armed families render
        srv.queue._ema_service_s = 1.0
        assert cl.submit(*dataset).fasta == solo_bytes  # server survives
    finally:
        srv.drain(timeout=30)


def test_doomed_mid_run_at_iteration_boundary(dataset, solo_bytes,
                                              tmp_path_factory,
                                              tmp_path):
    """An admitted job whose deadline is provably lost dies typed at
    the next iteration boundary (batcher extrapolation), not at job
    completion: hold the feeder past the deadline, then release."""
    journal = str(tmp_path / "midrun.jsonl")
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1,
                          abort_margin=0.0, iteration_windows=2,
                          journal=journal)
    caught: list[Exception] = []

    def go():
        try:
            cl.submit(*dataset, deadline_s=2.5)
        except Exception as exc:  # noqa: BLE001
            caught.append(exc)

    try:
        srv.batcher.hold()
        t = threading.Thread(target=go)
        t.start()
        _wait_for(lambda: len(srv._running_jobs) >= 1,
                  msg="doomed job running")
        time.sleep(3.0)                       # deadline passes held
        srv.batcher.release()
        t.join(timeout=120)
        assert len(caught) == 1 and isinstance(caught[0], ClientDoomed)
        assert srv.qos["aborted_doomed"] >= 1
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.batcher.release()
        srv.drain(timeout=30)
    doomed = [e for e in read_journal(journal)
              if e["event"] == "deadline-doomed"]
    assert doomed and doomed[0]["phase"] == "mid-run"


# ----------------------------------------------------- cancel RPC (e2e)
def test_cancel_queued_job_end_to_end(dataset, solo_bytes,
                                      tmp_path_factory, tmp_path):
    journal = str(tmp_path / "cq.jsonl")
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1,
                          journal=journal)
    caught: list[Exception] = []
    done: dict[str, bytes] = {}

    def runner():
        done["first"] = cl.submit(*dataset).fasta

    def queued():
        try:
            cl.submit(*dataset, trace_id="q-victim")
        except Exception as exc:  # noqa: BLE001
            caught.append(exc)

    try:
        srv.batcher.hold()
        t1 = threading.Thread(target=runner)
        t1.start()
        _wait_for(lambda: len(srv._running_jobs) >= 1,
                  msg="first job running")
        t2 = threading.Thread(target=queued)
        t2.start()
        _wait_for(lambda: len(srv.queue) >= 1, msg="victim queued")
        res = cl.cancel(trace_id="q-victim")
        assert res["cancelled"] == "queued"
        t2.join(timeout=30)
        assert len(caught) == 1 and isinstance(caught[0], JobCancelled)
        srv.batcher.release()
        t1.join(timeout=120)
        assert done["first"] == solo_bytes    # survivor untouched
        assert srv.qos["cancelled"] >= 1
        # unknown handles answer typed, not crash
        with pytest.raises(ServeError) as exc:
            cl.cancel(job_id="no-such-job")
        assert exc.value.code == "unknown-job"
    finally:
        srv.batcher.release()
        srv.drain(timeout=30)
    assert "cancelled" in [e["event"] for e in read_journal(journal)]


def test_cancel_running_job_end_to_end(dataset, solo_bytes,
                                       tmp_path_factory):
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1)
    caught: list[Exception] = []

    def go():
        try:
            cl.submit(*dataset, trace_id="r-victim")
        except Exception as exc:  # noqa: BLE001
            caught.append(exc)

    try:
        srv.batcher.hold()
        t = threading.Thread(target=go)
        t.start()
        _wait_for(lambda: len(srv._running_jobs) >= 1,
                  msg="victim running")
        time.sleep(0.2)
        res = cl.cancel(trace_id="r-victim")
        assert res["cancelled"] == "running"
        t.join(timeout=60)
        assert len(caught) == 1 and isinstance(caught[0], JobCancelled)
        assert srv.qos["cancelled"] >= 1
        srv.batcher.release()
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.batcher.release()
        srv.drain(timeout=30)


def test_client_cancel_on_timeout_frees_the_server(dataset, solo_bytes,
                                                   tmp_path_factory):
    """Satellite (e): a client giving up on its own `timeout` sends a
    cancel for its trace id on a fresh connection, so the abandoned
    job frees its queue/quota slots instead of running to waste."""
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1)
    impatient = PolishClient(socket_path=srv.config.socket_path,
                             timeout=2.0)
    try:
        srv.batcher.hold()
        with pytest.raises(JobCancelled):
            impatient.submit(*dataset, cancel_on_timeout=True)
        _wait_for(lambda: srv.qos["cancelled"] >= 1,
                  msg="server-side cancel accounting")
        srv.batcher.release()
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.batcher.release()
        srv.drain(timeout=30)


# ------------------------------------------------- router QoS propagation
class _RecordingDyingReplica:
    """Protocol-complete fake replica that records each submit frame's
    `deadline_s`, burns ~0.6s of the parent budget, then drops the
    connection — so a requeue's inherited deadline is observable."""

    def __init__(self, sock_path: str):
        self.path = sock_path
        self.deadlines: list = []
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(8)
        self._lst.settimeout(0.2)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        import contextlib
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                rtype = req.get("type")
                if rtype == "healthz":
                    send_frame(conn, {"type": "healthz", "ok": True,
                                      "draining": False})
                elif rtype == "scrape":
                    send_frame(conn, {"type": "metrics", "text": ""})
                elif rtype == "ping":
                    send_frame(conn, {"type": "pong"})
                elif rtype == "submit":
                    self.deadlines.append(req.get("deadline_s"))
                    time.sleep(0.6)
                    with contextlib.suppress(OSError):
                        conn.shutdown(socket.SHUT_RDWR)
                    return
                else:
                    send_frame(conn, {"type": "ok"})
        except (OSError, ProtocolError):
            return
        finally:
            import contextlib
            with contextlib.suppress(OSError):
                conn.close()

    def close(self):
        self._stop.set()
        try:
            self._lst.close()
        except OSError:
            pass


def test_requeued_shard_inherits_remaining_deadline(dataset, tmp_path):
    """The ISSUE's pin: `deadline_t` is the parent's ABSOLUTE deadline,
    so the requeue attempt's child `deadline_s` is the REMAINING
    budget — strictly less than the first attempt's, never a reset."""
    stubs = [_RecordingDyingReplica(str(tmp_path / f"d{i}.sock"))
             for i in range(2)]
    router = PolishRouter(replicas=",".join(s.path for s in stubs),
                          socket_path=str(tmp_path / "r.sock"),
                          health_interval_s=0.2).start()
    try:
        cl = PolishClient(socket_path=router.config.socket_path)
        with pytest.raises(ServeError):
            cl.submit(*dataset, deadline_s=30.0)
        recorded = [d for s in stubs for d in s.deadlines]
        assert len(recorded) >= 2, recorded   # attempt + requeue(s)
        assert all(isinstance(d, float) for d in recorded)
        assert max(recorded) <= 30.0          # never more than granted
        # every attempt burned ~0.6s of the SAME budget: all recorded
        # deadlines are distinct, and first-to-last spans the burn
        assert len(set(recorded)) == len(recorded)
        assert min(recorded) <= max(recorded) - 0.4
    finally:
        router.drain()
        for s in stubs:
            s.close()


def test_doomed_shard_cancels_siblings_through_router(dataset2, solo2,
                                                      tmp_path):
    """A deadline-doomed child fails the parent AND fans cancel RPCs to
    the sibling shards within one iteration — the still-running shard
    on the healthy replica dies cancelled instead of burning device
    time for a result nobody will merge."""
    socks = [str(tmp_path / f"s{i}.sock") for i in range(2)]
    # replica 0 carries the speculative-abort margin (mid-run doom at
    # the first iteration boundary once the deadline is provably
    # lost); replica 1 is a plain server whose shard the fan-out cancel
    # must reach while it is still running
    reps = [PolishServer(socket_path=socks[0], workers=1, warmup=False,
                         abort_margin=0.0, iteration_windows=1).start(),
            PolishServer(socket_path=socks[1], workers=1,
                         warmup=False).start()]
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "r.sock"),
                          journal=journal,
                          health_interval_s=0.2).start()
    cl = PolishClient(socket_path=router.config.socket_path)
    caught: list[Exception] = []

    def go():
        try:
            cl.submit(*dataset2, deadline_s=3.0)
        except Exception as exc:  # noqa: BLE001
            caught.append(exc)

    try:
        for r in reps:
            r.batcher.hold()
        t = threading.Thread(target=go)
        t.start()
        _wait_for(lambda: sum(len(r._running_jobs) for r in reps) >= 2,
                  msg="both shards admitted and running")
        time.sleep(3.5)                       # the parent deadline dies
        reps[0].batcher.release()             # -> mid-run doom there
        _wait_for(lambda: reps[1].qos["cancelled"] >= 1,
                  msg="sibling shard cancelled on the healthy replica")
        t.join(timeout=60)
        assert len(caught) == 1 and isinstance(caught[0], ClientDoomed)
        reps[1].batcher.release()
        assert cl.submit(*dataset2).fasta == solo2  # fabric survives
    finally:
        for r in reps:
            r.batcher.release()
        router.drain()
        for r in reps:
            r.drain(timeout=30)
    events = [e["event"] for e in read_journal(journal)]
    assert "siblings-cancelled" in events


def test_parent_cancel_fans_out_through_router(dataset2, solo2,
                                               tmp_path):
    socks = [str(tmp_path / f"p{i}.sock") for i in range(2)]
    reps = [PolishServer(socket_path=s, workers=1,
                         warmup=False).start() for s in socks]
    journal = str(tmp_path / "rcancel.jsonl")
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "r.sock"),
                          journal=journal,
                          health_interval_s=0.2).start()
    cl = PolishClient(socket_path=router.config.socket_path)
    caught: list[Exception] = []

    def go():
        try:
            cl.submit(*dataset2, trace_id="parent-1")
        except Exception as exc:  # noqa: BLE001
            caught.append(exc)

    try:
        for r in reps:
            r.batcher.hold()
        t = threading.Thread(target=go)
        t.start()
        _wait_for(lambda: sum(len(r._running_jobs) for r in reps) >= 2,
                  msg="both shards running")
        time.sleep(0.5)  # shards' windows pooled -> tickets registered
        res = cl.cancel(trace_id="parent-1")
        assert res["cancelled"] == "running"
        assert res["shards_cancelled"] >= 1
        t.join(timeout=60)
        assert len(caught) == 1 and isinstance(caught[0], JobCancelled)
        for r in reps:
            r.batcher.release()
        assert cl.submit(*dataset2).fasta == solo2
    finally:
        for r in reps:
            r.batcher.release()
        router.drain()
        for r in reps:
            r.drain(timeout=30)
    events = [e["event"] for e in read_journal(journal)]
    assert "cancelled" in events
    assert "siblings-cancelled" in events


# ----------------------------------------------- default-off byte identity
def test_qos_off_surfaces_identical_to_pre_qos(dataset, solo_bytes,
                                               tmp_path_factory,
                                               tmp_path):
    """With no QoS knob armed, the scrape grows no new families, the
    stats body no `qos` block, and a clean job's journal no new event
    types — the pre-QoS surfaces byte-for-byte."""
    journal = str(tmp_path / "plain.jsonl")
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1,
                          journal=journal)
    try:
        assert cl.submit(*dataset).fasta == solo_bytes
        text = cl.scrape()
        for family in ("preempt", "doomed", "burst",
                       "cancelled"):
            assert family not in text, f"QoS-off scrape leaks {family}"
        assert "qos" not in cl.stats()
    finally:
        srv.drain(timeout=30)
    events = {e["event"] for e in read_journal(journal)}
    assert not (events & QOS_EVENTS), events


# -------------------------------------------------- obsreport unit checks
def test_obsreport_preemption_balance_red_and_green():
    base = [{"event": "received", "job": "a"},
            {"event": "preempted", "job": "a"}]
    assert check_preemptions(base + [{"event": "resumed",
                                      "job": "a"}]) == []
    problems = check_preemptions(base)
    assert problems and "1 preempted events vs 0 resumed" in problems[0]
    # a job whose `received` fell out of the rotation window is skipped
    assert check_preemptions([{"event": "preempted", "job": "b"}]) == []


# -------------------------------------------------- cancel CLI surface
def test_cancel_cli_dispatch_and_arg_validation(capsys):
    """`racon_tpu cancel` routes through cli.main; without an id it
    fails typed rc 1 before touching any socket."""
    from racon_tpu.cli import main as cli_main

    assert cli_main(["cancel", "--socket", "/tmp/nope.sock"]) == 1
    err = capsys.readouterr().err
    assert "needs --job-id or --trace-id" in err
    # an unreachable server is a connection error, not a crash
    assert cli_main(["cancel", "--socket", "/tmp/definitely-not-a.sock",
                     "--trace-id", "x"]) == 1
    assert "error" in capsys.readouterr().err
