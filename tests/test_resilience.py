"""Resilience layer tests (racon_tpu/resilience + pipeline wiring).

The contracts:

  - the fault plan grammar parses/rejects deterministically and every
    armed fault is one-shot;
  - the watchdog bounds device-stage calls in time (DeviceTimeout, never
    a hang) and retries with exponential backoff;
  - FAULT MATRIX: for each injection point (pack raise, device raise,
    device hang, unpack corrupt, fallback raise) at pipeline depth 0 and
    2, a full polisher run either produces byte-identical output to the
    clean run (the watchdog/retry/fallback ladder absorbed the fault) or
    reports quarantined windows — and never crashes, never exceeds the
    watchdog budget, never leaves orphaned worker threads;
  - a window whose consensus fails on both device and host is
    QUARANTINED: draft backbone kept as consensus, counted in the
    degradation report, reflected in the XC ratio;
  - truncated/corrupt gzip inputs surface as RaconError naming the file,
    not a traceback;
  - the CLI exposes the posture knobs (--tpu-strict, --tpu-fault-plan,
    --tpu-device-timeout).

tools/faultcheck.py runs the full matrix (including the slow hang cases
excluded from tier-1 via the `slow` marker) as a pass/fail grid.
"""

import gzip
import random
import threading
import time

import pytest

jax = pytest.importorskip("jax")

from racon_tpu.errors import (ChunkCorrupt, DeviceError, DeviceTimeout,  # noqa: E402
                              RaconError)
from racon_tpu.pipeline import DispatchPipeline  # noqa: E402
from racon_tpu.resilience import (FaultPlan, Watchdog,  # noqa: E402
                                  degradation_summary)
from racon_tpu.resilience.faults import reset_fault_plan  # noqa: E402

ACGT = b"ACGT"

RESILIENCE_ENV = ("RACON_TPU_FAULT_PLAN", "RACON_TPU_DEVICE_TIMEOUT",
                  "RACON_TPU_DEVICE_RETRIES", "RACON_TPU_RETRY_BACKOFF",
                  "RACON_TPU_STRICT")


@pytest.fixture(autouse=True)
def _clean_resilience_env(monkeypatch):
    for var in RESILIENCE_ENV:
        monkeypatch.delenv(var, raising=False)
    reset_fault_plan()
    yield
    reset_fault_plan()


def _no_orphan_threads(grace: float = 3.0):
    """No racon-tpu worker thread may outlive the run (abandoned watchdog
    workers get a short grace to notice their cancelled hang)."""
    deadline = time.perf_counter() + grace
    while time.perf_counter() < deadline:
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("racon-tpu")]
        if not alive:
            return
        time.sleep(0.05)
    raise AssertionError(f"orphaned worker threads: {alive}")


# ----------------------------------------------------------- fault plan

def test_fault_plan_parses_the_documented_spec():
    plan = FaultPlan.parse(
        "device:chunk=3:raise,device:chunk=7:hang=5,unpack:chunk=2:corrupt")
    assert len(plan.unfired) == 3
    stages = sorted(f.stage for f in plan.unfired)
    assert stages == ["device", "device", "unpack"]


@pytest.mark.parametrize("bad", [
    "device:3:raise",            # missing chunk=
    "gpu:chunk=1:raise",         # unknown stage
    "device:chunk=x:raise",      # non-integer chunk
    "device:chunk=1:explode",    # unknown action
    "device:chunk=1:hang",       # hang without duration
    "device:chunk=1:hang=-2",    # non-positive duration
    "device:chunk=1:raise=3",    # raise takes no argument
    "",                          # empty plan
])
def test_fault_plan_rejects_bad_specs(bad):
    with pytest.raises(RaconError, match="FaultPlan"):
        FaultPlan.parse(bad)


def test_faults_are_one_shot_and_typed():
    plan = FaultPlan.parse("device:chunk=1:raise,unpack:chunk=0:corrupt")
    plan.fire("device", 0)  # no fault armed there
    with pytest.raises(DeviceError):
        plan.fire("device", 1)
    plan.fire("device", 1)  # consumed: the retry succeeds
    with pytest.raises(ChunkCorrupt):
        plan.fire("unpack", 0)
    assert plan.unfired == []


def test_injected_hang_is_cancellable():
    plan = FaultPlan.parse("device:chunk=0:hang=30")
    t = threading.Thread(target=lambda: plan.fire("device", 0))
    t0 = time.perf_counter()
    t.start()
    time.sleep(0.15)
    plan.cancel_hangs()
    t.join(timeout=5)
    assert not t.is_alive()
    assert time.perf_counter() - t0 < 5


# ------------------------------------------------------------- watchdog

def test_watchdog_deadline_raises_device_timeout():
    wd = Watchdog(timeout=0.2, retries=0)
    release = threading.Event()  # lets the abandoned worker exit promptly
    t0 = time.perf_counter()
    try:
        with pytest.raises(DeviceTimeout):
            wd.call(lambda: release.wait(30))
        assert time.perf_counter() - t0 < 2
    finally:
        release.set()


def test_watchdog_retries_with_exponential_backoff():
    from racon_tpu.pipeline import PipelineStats

    stats = PipelineStats()
    wd = Watchdog(timeout=0.0, retries=2, backoff=0.01)
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert wd.call(flaky, stats=stats) == "ok"
    s = stats.snapshot()
    assert len(attempts) == 3
    assert s["retries"] == 2
    assert s["backoff_s"] == pytest.approx(0.01 + 0.02)

    # exhausted retries re-raise the final error
    with pytest.raises(RuntimeError, match="persistent"):
        wd.call(lambda: (_ for _ in ()).throw(RuntimeError("persistent")))


def test_stale_cancel_does_not_void_next_hang():
    """A cancel with no sleeper (a real slow call tripped the watchdog)
    must not make a later armed hang return instantly."""
    plan = FaultPlan.parse("device:chunk=0:hang=0.4")
    plan.cancel_hangs()  # stale: nothing is sleeping
    t0 = time.perf_counter()
    plan.fire("device", 0)
    assert time.perf_counter() - t0 >= 0.3  # the stall still happened


def test_watchdog_from_env_rejects_garbage(monkeypatch):
    monkeypatch.setenv("RACON_TPU_DEVICE_TIMEOUT", "5s")
    with pytest.raises(RaconError, match="RACON_TPU_DEVICE_TIMEOUT"):
        Watchdog.from_env()
    monkeypatch.delenv("RACON_TPU_DEVICE_TIMEOUT")
    monkeypatch.setenv("RACON_TPU_DEVICE_RETRIES", "two")
    with pytest.raises(RaconError, match="RACON_TPU_DEVICE_RETRIES"):
        Watchdog.from_env()


def test_watchdog_from_env(monkeypatch):
    assert Watchdog.from_env() is None  # nothing configured: no overhead
    monkeypatch.setenv("RACON_TPU_DEVICE_TIMEOUT", "1.5")
    wd = Watchdog.from_env()
    assert wd is not None and wd.timeout == 1.5
    assert wd.retries == 1  # default once the watchdog is on
    monkeypatch.setenv("RACON_TPU_DEVICE_RETRIES", "3")
    assert Watchdog.from_env().retries == 3
    # explicit (CLI) timeout wins over the env
    assert Watchdog.from_env(timeout=0.7).timeout == 0.7


# ----------------------------------------------- pipeline-level injection

@pytest.mark.parametrize("depth", [0, 2])
def test_injected_device_raise_absorbed_by_retry(monkeypatch, depth):
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", "device:chunk=1:raise")
    monkeypatch.setenv("RACON_TPU_DEVICE_RETRIES", "1")
    monkeypatch.setenv("RACON_TPU_RETRY_BACKOFF", "0.01")
    reset_fault_plan()
    pl = DispatchPipeline(depth=depth)
    seen = []
    pl.run(range(4), lambda i: i * 10, lambda i, o: o + 1, lambda h: h + 1,
           lambda i, r: seen.append((i, r)))
    pl.close()
    assert seen == [(i, i * 10 + 2) for i in range(4)]  # nothing lost
    s = pl.stats.snapshot()
    assert s["faults"] == 1 and s["retries"] == 1 and s["errors"] == 0


@pytest.mark.parametrize("depth", [0, 2])
def test_injected_corrupt_routes_chunk_to_on_error(monkeypatch, depth):
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", "unpack:chunk=1:corrupt")
    reset_fault_plan()
    pl = DispatchPipeline(depth=depth)
    failed = []
    pl.run(range(3), lambda i: i, lambda i, o: o, lambda h: h,
           lambda i, r: None,
           on_error=lambda i, exc: failed.append((i, exc)))
    pl.close()
    assert [i for i, _ in failed] == [1]
    assert isinstance(failed[0][1], ChunkCorrupt)  # typed, not stringly


def test_cancel_fallback_cancels_pending_and_drains_running():
    pl = DispatchPipeline(depth=2, fallback_workers=1)
    started = threading.Event()

    def slow():
        started.set()
        time.sleep(0.3)
        return "done"

    futs = [pl.submit_fallback(slow) for _ in range(4)]
    started.wait(2)
    cancelled, drained = pl.cancel_fallback()
    assert cancelled >= 2 and drained >= 1
    assert cancelled + drained == 4
    assert pl.stats.snapshot()["cancelled"] == cancelled
    assert all(f.cancelled() or f.done() for f in futs)
    assert pl._futures == []  # drain_fallback later is a no-op
    pl.close()


def test_aligner_circuit_breaker_trips(monkeypatch):
    """A device failing every aligner chunk must not burn a fault/retry
    per chunk forever: after 3 consecutive chunk failures the pass
    aborts with a DeviceError (the polisher then host-aligns the whole
    phase), and the trip is counted."""
    from racon_tpu.ops.align import BatchAligner

    monkeypatch.setenv(
        "RACON_TPU_FAULT_PLAN",
        ",".join(f"device:chunk={i}:raise" for i in range(4)))
    reset_fault_plan()
    rng = random.Random(5)
    # three length buckets -> three device chunks
    pairs = []
    for length in (300, 800, 1500, 300, 800, 1500):
        s = bytes(rng.choice(ACGT) for _ in range(length))
        pairs.append((s, s))
    rejected = []
    al = BatchAligner(band_width=64)
    with DispatchPipeline(depth=0) as pl:
        with pytest.raises(DeviceError, match="consecutive"):
            al.align(pairs, pipeline=pl, on_reject=rejected.extend)
        assert pl.stats.snapshot()["breaker_trips"] == 1


def test_consensus_degrade_cancels_prefall_futures(monkeypatch):
    """When the device consensus pass dies mid-flight, queued fallback
    futures on the shared pipeline are cancelled/drained before the host
    pass reruns those windows (no duplicated work, no stale futures)."""
    from test_device_poa import _make_windows

    from racon_tpu.ops import poa as poa_mod

    rng = random.Random(3)
    windows, _ = _make_windows(rng, 5, length=160, depth=5, rate=0.1)
    pl = DispatchPipeline(depth=2, fallback_workers=1)

    def dead_device(self, todo, trim):
        # a prefall-shaped job is in flight when the device pass dies
        pl.submit_fallback(time.sleep, 0.2)
        pl.submit_fallback(time.sleep, 0.2)
        raise DeviceError("FusedPOA", "3 consecutive device chunk "
                          "failures; aborting the device pass")

    monkeypatch.setattr(poa_mod.BatchPOA, "_device_consensus", dead_device)
    with pl:
        eng = poa_mod.BatchPOA(3, -5, -4, 160, num_threads=2,
                               device_batches=1, pipeline=pl)
        eng.generate_consensus(windows, trim=False)
        stats = pl.stats.snapshot()
    assert pl._futures == []  # nothing stale left on the pipeline
    assert stats["cancelled"] >= 1
    for w in windows:
        assert w.polished and w.consensus  # host pass completed everything


# ----------------------------------------------------- polisher matrix

def _dataset(tmp_path, rng):
    """Small synthetic polishing job with MIXED read lengths so the
    device aligner path has both bucketable pairs (device chunks) and
    overlength pairs (host-fallback jobs) once ALIGNER_MAXLEN=1024."""
    truth = bytes(rng.choice(ACGT) for _ in range(2000))

    def mutate(s, rate):
        out = bytearray()
        for c in s:
            r = rng.random()
            if r < rate / 3:
                continue
            if r < 2 * rate / 3:
                out.append(rng.choice(ACGT))
                out.append(c)
                continue
            if r < rate:
                out.append(rng.choice(ACGT))
                continue
            out.append(c)
        return bytes(out)

    draft = mutate(truth, 0.04)
    reads, paf = [], []
    jobs = [(start, 400) for start in range(0, len(truth) - 400, 100)]
    jobs += [(0, 1300), (600, 1300)]  # overlength: reject -> fallback pool
    for k, (start, read_len) in enumerate(jobs):
        read = mutate(truth[start:start + read_len], 0.05)
        name = f"r{k}"
        reads.append((name, read))
        t_begin = min(start, len(draft) - 1)
        t_end = min(start + read_len, len(draft))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t+\tdraft\t"
                   f"{len(draft)}\t{t_begin}\t{t_end}\t{read_len}\t"
                   f"{read_len}\t60")
    reads_path = tmp_path / "reads.fasta.gz"
    with gzip.open(reads_path, "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    paf_path = tmp_path / "ovl.paf.gz"
    with gzip.open(paf_path, "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    draft_path = tmp_path / "draft.fasta.gz"
    with gzip.open(draft_path, "wb") as f:
        f.write(b">draft\n" + draft + b"\n")
    return reads_path, paf_path, draft_path


@pytest.fixture(scope="module")
def matrix_paths(tmp_path_factory):
    return _dataset(tmp_path_factory.mktemp("faultmx"),
                    random.Random(11))


def _polish(paths, depth, aligner, timeout=0.0):
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*(str(x) for x in paths), PolisherType.kC,
                        500, -1.0, 0.3, num_threads=2,
                        tpu_aligner_batches=aligner,
                        tpu_pipeline_depth=depth,
                        tpu_device_timeout=timeout)
    p.initialize()
    out = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                   for s in p.polish())
    return out, p.stage_stats


_CLEAN: dict = {}


def _clean_run(matrix_paths, depth, aligner, monkeypatch):
    key = (depth, aligner)
    if key not in _CLEAN:
        monkeypatch.setenv("RACON_TPU_ALIGNER_MAXLEN", "1024")
        out, stats = _polish(matrix_paths, depth, aligner)
        assert stats["faults"] == 0 and stats["quarantined"] == 0
        _CLEAN[key] = out
    return _CLEAN[key]


# the matrix: every injection point, absorbed by the retry/fallback
# ladder. aligner=1 arms the alignment phase's pipeline (it runs first);
# aligner=0 arms the consensus phase's host loop. Hang cases (below,
# marked slow) exercise the watchdog deadline the same way.
MATRIX = [
    ("align-pack-raise", 1, "pack:chunk=0:raise"),
    ("align-device-raise", 1, "device:chunk=0:raise"),
    ("align-unpack-corrupt", 1, "unpack:chunk=0:corrupt"),
    ("align-fallback-raise", 1, "fallback:chunk=0:raise"),
    ("consensus-pack-raise", 0, "pack:chunk=0:raise"),
    ("consensus-device-raise", 0, "device:chunk=0:raise"),
    ("consensus-unpack-corrupt", 0, "unpack:chunk=0:corrupt"),
    # persistent device failure: retry cannot absorb it (two armed
    # faults vs one retry); the chunk degrades to the per-window host
    # pass, which still reproduces the clean bytes
    ("consensus-device-persistent", 0,
     "device:chunk=0:raise,device:chunk=0:raise"),
]


@pytest.mark.faults
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("name,aligner,spec",
                         MATRIX, ids=[m[0] for m in MATRIX])
def test_fault_matrix_absorbed(matrix_paths, monkeypatch, depth, name,
                               aligner, spec):
    clean = _clean_run(matrix_paths, depth, aligner, monkeypatch)
    monkeypatch.setenv("RACON_TPU_ALIGNER_MAXLEN", "1024")
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", spec)
    monkeypatch.setenv("RACON_TPU_DEVICE_RETRIES", "1")
    monkeypatch.setenv("RACON_TPU_RETRY_BACKOFF", "0.01")
    reset_fault_plan()
    out, stats = _polish(matrix_paths, depth, aligner)
    assert stats["faults"] >= 1, "armed fault never fired"
    assert out == clean or stats["quarantined"] > 0
    _no_orphan_threads()


HANGS = [
    ("align-device-hang", 1, "device:chunk=0:hang=5"),
    ("consensus-device-hang", 0, "device:chunk=0:hang=5"),
]


@pytest.mark.faults
@pytest.mark.slow
@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("name,aligner,spec",
                         HANGS, ids=[h[0] for h in HANGS])
def test_fault_matrix_hang_bounded_by_watchdog(matrix_paths, monkeypatch,
                                               depth, name, aligner, spec):
    """A 5 s injected stall under a 0.5 s deadline: the run must finish
    well inside the hang duration (DeviceTimeout -> retry absorbed it),
    byte-identical, with no abandoned worker left behind."""
    clean = _clean_run(matrix_paths, depth, aligner, monkeypatch)
    monkeypatch.setenv("RACON_TPU_ALIGNER_MAXLEN", "1024")
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", spec)
    monkeypatch.setenv("RACON_TPU_RETRY_BACKOFF", "0.01")
    reset_fault_plan()
    t0 = time.perf_counter()
    out, stats = _polish(matrix_paths, depth, aligner, timeout=0.5)
    wall = time.perf_counter() - t0
    assert stats["faults"] >= 1 and stats["timeouts"] >= 1
    assert out == clean or stats["quarantined"] > 0
    assert wall < 60  # bounded: nowhere near a wedged run
    _no_orphan_threads()


def test_clean_run_reports_nothing(matrix_paths, monkeypatch):
    """No fault plan, no timeout: the degradation report is empty and
    the resilience counters all zero — the hooks cost nothing."""
    clean = _clean_run(matrix_paths, 2, 1, monkeypatch)
    assert clean  # produced output
    out, stats = _polish(matrix_paths, 2, 1)
    assert out == clean
    for key in ("faults", "retries", "timeouts", "breaker_trips",
                "quarantined", "cancelled"):
        assert stats[key] == 0
    assert stats["backoff_s"] == 0.0
    assert degradation_summary(stats) is None


# ---------------------------------------------------------- quarantine

def test_quarantined_window_keeps_backbone(monkeypatch):
    """A window whose consensus fails on the whole-chunk pass AND on its
    individual retry keeps the draft backbone, counts as unpolished and
    bumps the quarantine counter; its neighbours still polish."""
    from test_device_poa import _make_windows

    from racon_tpu.ops import poa as poa_mod

    rng = random.Random(3)
    windows, _ = _make_windows(rng, 6, length=160, depth=5, rate=0.1)
    poison = windows[2].sequences[0]
    real_poa_batch = poa_mod.poa_batch

    def sabotaged(packed, *args, **kwargs):
        if any(win[0][0] == poison for win in packed):
            raise RuntimeError("poisoned window")
        return real_poa_batch(packed, *args, **kwargs)

    monkeypatch.setattr(poa_mod, "poa_batch", sabotaged)
    with DispatchPipeline(depth=2) as pl:
        eng = poa_mod.BatchPOA(3, -5, -4, 160, num_threads=2, pipeline=pl)
        eng.generate_consensus(windows, trim=False)
        stats = pl.stats.snapshot()
    assert stats["quarantined"] == 1
    assert windows[2].consensus == poison  # draft backbone kept
    assert not windows[2].polished
    for w in windows[:2] + windows[3:]:
        assert w.polished and w.consensus


def test_quarantine_strict_mode_raises(monkeypatch):
    from test_device_poa import _make_windows

    from racon_tpu.ops import poa as poa_mod

    rng = random.Random(3)
    windows, _ = _make_windows(rng, 4, length=160, depth=5, rate=0.1)
    monkeypatch.setenv("RACON_TPU_STRICT", "1")
    monkeypatch.setattr(
        poa_mod, "poa_batch",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("dead")))
    with DispatchPipeline(depth=0) as pl:
        eng = poa_mod.BatchPOA(3, -5, -4, 160, num_threads=1, pipeline=pl)
        with pytest.raises(RuntimeError, match="dead"):
            eng.generate_consensus(windows, trim=False)


def test_quarantine_xc_ratio_reflects_unpolished(matrix_paths, monkeypatch):
    """Every window quarantined -> the stitched sequence's XC ratio is 0,
    its data is the concatenated draft backbones, and with the default
    drop-unpolished policy the sequence is dropped entirely — the
    reference's `ratio > 0` discipline (polisher.cpp:515) applied to
    failure-time quarantine."""
    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.ops import poa as poa_mod

    monkeypatch.setattr(
        poa_mod, "poa_batch",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("dead engine")))

    def run(drop):
        p = create_polisher(*(str(x) for x in matrix_paths),
                            PolisherType.kC, 500, -1.0, 0.3,
                            num_threads=1, tpu_pipeline_depth=0)
        p.initialize()
        draft = p.sequences[0].data
        # only windows deep enough for POA can fail into quarantine;
        # sub-3-sequence windows keep their backbone by design already
        n_q = sum(1 for w in p.windows if len(w.sequences) >= 3)
        return p.polish(drop), p.stage_stats, draft, n_q

    polished, stats, draft, n_q = run(drop=True)
    assert n_q > 0 and stats["quarantined"] == n_q
    assert polished == []  # ratio 0: dropped, not crashed

    polished, stats, draft, n_q = run(drop=False)
    assert len(polished) == 1
    assert "XC:f:0.000000" in polished[0].name
    assert polished[0].data == draft  # every window kept its backbone
    _no_orphan_threads()


# ------------------------------------------------------- corrupt inputs

def test_truncated_gzip_overlaps_is_racon_error(tmp_path, matrix_paths):
    reads, paf, draft = matrix_paths
    blob = paf.read_bytes()
    bad = tmp_path / "trunc.paf.gz"
    bad.write_bytes(blob[:len(blob) // 2])

    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(str(reads), str(bad), str(draft),
                        PolisherType.kC, 500, -1.0, 0.3)
    with pytest.raises(RaconError, match="trunc.paf.gz"):
        p.initialize()


def test_corrupt_gzip_fasta_is_racon_error(tmp_path):
    from racon_tpu.io.parsers import FastaParser

    blob = bytearray(gzip.compress(b">s\n" + b"ACGT" * 3000 + b"\n"))
    blob[len(blob) // 2] ^= 0xFF  # flip a byte mid-stream
    bad = tmp_path / "corrupt.fasta.gz"
    bad.write_bytes(bytes(blob))
    with pytest.raises(RaconError, match="corrupt.fasta.gz"):
        FastaParser(str(bad)).parse([], -1)


def test_truncated_gzip_cli_exits_cleanly(tmp_path, matrix_paths, capsys):
    """Through the CLI: stderr carries the [racon_tpu::...] error line
    and the exit status is 1 — no traceback."""
    from racon_tpu.cli import main

    reads, paf, draft = matrix_paths
    blob = paf.read_bytes()
    bad = tmp_path / "trunc.paf.gz"
    bad.write_bytes(blob[:len(blob) // 2])
    rc = main([str(reads), str(bad), str(draft)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "error:" in err and "trunc.paf.gz" in err
    assert "Traceback" not in err


# -------------------------------------------------------------- CLI

def test_cli_resilience_flags_parse():
    from racon_tpu.cli import parse_args

    opts = parse_args(["--tpu-strict", "--tpu-device-timeout", "2.5",
                       "--tpu-fault-plan", "device:chunk=0:raise",
                       "a.fasta", "b.paf", "c.fasta"])
    assert opts["tpu_strict"] is True
    assert opts["tpu_device_timeout"] == 2.5
    assert opts["tpu_fault_plan"] == "device:chunk=0:raise"


def test_cli_strict_flag_in_help(capsys):
    from racon_tpu.cli import parse_args

    assert parse_args(["--help"]) is None
    out = capsys.readouterr().out
    for flag in ("--tpu-strict", "--tpu-fault-plan",
                 "--tpu-device-timeout"):
        assert flag in out


def test_cli_bad_fault_plan_exits_cleanly(capsys):
    from racon_tpu.cli import main

    rc = main(["--tpu-fault-plan", "bogus-spec",
               "a.fasta", "b.paf", "c.fasta"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "FaultPlan" in err and "error:" in err
