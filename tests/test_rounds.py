"""Serve-native polishing rounds + content-addressed window cache.

The ISSUE-16 acceptance pins, in dependency order:

  - a `rounds=N` submit's FASTA is BYTE-IDENTICAL to N chained solo
    runs through `Polisher.redraft` — unix socket, TCP, and through
    the shard-aware router at 2 replicas, with the window cache off
    AND on (the cache is a dispatch skip, never an answer change);
  - the response's `rounds` accounting block (requested / completed /
    per-round walls), the journal's balanced `round-started` /
    `round-finished` pairs, and the armed-only scrape families;
  - the cache invalidates on winner-table demotion and lane
    quarantine, and the identity-audit sentinel catches a DELIBERATELY
    POISONED cache entry: the entry is evicted + its key quarantined
    (no engine demotion, no lane quarantine — the device never
    produced the bytes), the window repaired with oracle bytes, and
    the job output still byte-identical;
  - unit pins for core/remap.py (the in-process re-overlap mapper),
    serve/wincache.py (LRU bound, quarantine, strict env parsing),
    sched/autotune.posture_key, and the perfgate / obsreport /
    servetop / fleet satellite surfaces.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import types

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.core.remap import (DEFAULT_K, build_index, remap_overlaps,
                                  remap_read, revcomp, write_fasta,
                                  write_paf)
from racon_tpu.core.window import WindowType, create_window
from racon_tpu.errors import RaconError
from racon_tpu.obs.journal import read_journal
from racon_tpu.sched.autotune import posture_key
from racon_tpu.serve import (PolishClient, PolishRouter, PolishServer,
                             make_synth_dataset)
from racon_tpu.serve.client import ServeError
from racon_tpu.serve.wincache import (WindowCache, window_content_digest,
                                      wincache_from_env)

N_ROUNDS = 3


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    """Two independent contigs, so the router test shards 2 ways."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("rounds_data")),
                              contigs=2)


def chained_solo(paths, n: int) -> bytes:
    """N polishing rounds the reference way: polish, re-draft through
    Polisher.redraft (the SAME entry the serve rounds loop calls),
    polish again — the byte-identity oracle for every rounds pin."""
    with tempfile.TemporaryDirectory(prefix="rounds_chain_") as wd:
        p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                            num_threads=2)
        p.initialize()
        polished = None
        for rnd in range(1, n + 1):
            polished = p.polish(True)
            if rnd < n:
                p.redraft(polished, wd, tag=f"r{rnd}")
                p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in polished)


@pytest.fixture(scope="module")
def chained3(dataset):
    return chained_solo(dataset, N_ROUNDS)


# --------------------------------------------------- rounds byte identity
def test_rounds_identity_unix_cache_off(dataset, chained3, tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=2, warmup=False).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        res = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res.fasta == chained3
        block = res.rounds
        assert block["requested"] == N_ROUNDS
        assert block["completed"] == N_ROUNDS
        assert [p["round"] for p in block["per_round"]] == [1, 2, 3]
        for p in block["per_round"]:
            assert p["wall_s"] >= 0.0 and p["sequences"] >= 1
            assert "cache" not in p  # cache off: no cache accounting
        assert "cache" not in block
        # rounds=1 is the single-pass result; a plain submit carries
        # no rounds block at all (response shape unchanged)
        r1 = cli.submit(*dataset, rounds=1)
        plain = cli.submit(*dataset)
        assert r1.fasta == plain.fasta
        assert plain.rounds == {}
        assert r1.rounds["completed"] == 1
        # cache off: the scrape exposes NO wincache families (the
        # armed-only discipline — byte-identical to pre-cache)
        assert "wincache" not in cli.scrape()
    finally:
        srv.drain(timeout=15)


def test_rounds_identity_cached_and_resubmit(dataset, chained3,
                                             tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=2, warmup=False, wincache=True).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        res = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res.fasta == chained3
        cache = res.rounds["cache"]
        assert cache["hits"] + cache["misses"] > 0
        # converged later rounds hit entries round 1 populated
        assert cache["hits"] > 0
        # identical resubmit: EVERY window hits — zero device work
        res2 = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res2.fasta == chained3
        assert res2.rounds["cache"]["misses"] == 0
        assert res2.rounds["cache"]["hits"] > 0
        snap = srv.batcher.wincache.snapshot()
        assert snap["entries"] > 0 and snap["hit_rate"] > 0.0
        # armed families in the scrape
        text = cli.scrape()
        assert "racon_tpu_serve_wincache_ops_total" in text
        assert 'op="hit"' in text
        assert "racon_tpu_serve_rounds_inflight 0" in text
        assert "racon_tpu_serve_rounds_jobs_total 2" in text
        assert ("racon_tpu_serve_rounds_completed_total "
                f"{2 * N_ROUNDS}") in text
    finally:
        srv.drain(timeout=15)


def test_rounds_identity_tcp(dataset, chained3):
    srv = PolishServer(port=0, workers=2, warmup=False,
                       wincache=True).start()
    try:
        cli = PolishClient(port=srv.config.port)
        res = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res.fasta == chained3
        assert res.rounds["completed"] == N_ROUNDS
    finally:
        srv.drain(timeout=15)


@pytest.mark.parametrize("cached", [False, True])
def test_rounds_identity_through_router(dataset, chained3, tmp_path,
                                        cached):
    """2-replica router: each shard runs its own rounds over its
    contig subset; the merge is byte-identical to the chained solo
    bytes and carries the aggregated rounds block (no per_round — the
    shard walls overlap in time)."""
    kw = dict(workers=2, warmup=False)
    if cached:
        kw["wincache"] = True
    reps = [PolishServer(socket_path=str(tmp_path / f"rep{i}.sock"),
                         **kw).start() for i in range(2)]
    router = PolishRouter(
        replicas=",".join(r.config.socket_path for r in reps),
        socket_path=str(tmp_path / "router.sock"),
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        res = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res.fasta == chained3
        assert res.rounds["requested"] == N_ROUNDS
        assert res.rounds["completed"] == N_ROUNDS
        assert "per_round" not in res.rounds
        if cached:
            assert res.rounds["cache"]["hits"] >= 0  # summed block
        else:
            assert "cache" not in res.rounds
    finally:
        router.drain()
        for r in reps:
            r.drain(timeout=15)


def test_rounds_validation(dataset, tmp_path):
    """A typo'd rounds value is a typed bad-request, not a queued job
    that fails later — and booleans don't sneak in as integers."""
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=1, warmup=False).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        for bad in (0, 65, -1):
            with pytest.raises(ServeError) as exc_info:
                cli.submit(*dataset, rounds=bad)
            assert exc_info.value.code == "bad-request"
        for bad in (True, "two", 1.5):
            with pytest.raises(ServeError) as exc_info:
                cli.request({"type": "submit",
                             "sequences": dataset[0],
                             "overlaps": dataset[1],
                             "target": dataset[2], "rounds": bad})
            assert exc_info.value.code == "bad-request"
    finally:
        srv.drain(timeout=15)


def test_rounds_journal_boundaries(dataset, tmp_path):
    """Each round journals a started/finished pair; obsreport's
    check_rounds sees them balanced and --check stays rc 0."""
    import obsreport

    jpath = str(tmp_path / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=1, warmup=False, journal=jpath).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        cli.submit(*dataset, rounds=N_ROUNDS)
    finally:
        srv.drain(timeout=15)
    entries = read_journal(jpath)
    started = [e for e in entries if e.get("event") == "round-started"]
    finished = [e for e in entries
                if e.get("event") == "round-finished"]
    assert len(started) == N_ROUNDS and len(finished) == N_ROUNDS
    assert [e["round"] for e in started] == [1, 2, 3]
    assert all(e["of"] == N_ROUNDS for e in started)
    assert all(e["wall_s"] >= 0.0 for e in finished)
    recv = next(e for e in entries if e.get("event") == "received")
    assert recv["rounds"] == N_ROUNDS
    assert obsreport.main(["--journal", jpath, "--check",
                           "--flight-dir",
                           str(tmp_path / "none")]) == 0
    assert obsreport.check_rounds(entries) == []


# ------------------------------------------------- cache invalidation
def test_cache_invalidated_on_quarantine_and_demotion(dataset,
                                                      chained3,
                                                      tmp_path):
    """Lane quarantine and winner-table demotion both flush the cache
    (the producer's identity is no longer trusted) — and polishing
    after the flush still reproduces the chained bytes."""
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=2, warmup=False, wincache=True).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        cli.submit(*dataset, rounds=N_ROUNDS)
        wc = srv.batcher.wincache
        assert wc.snapshot()["entries"] > 0
        srv.batcher.flush_lane_engines()  # what a demotion calls
        snap = wc.snapshot()
        assert snap["entries"] == 0 and snap["invalidations"] == 1
        cli.submit(*dataset)  # repopulate
        assert wc.snapshot()["entries"] > 0
        srv.batcher.quarantine_lane(0)
        snap = wc.snapshot()
        assert snap["entries"] == 0 and snap["invalidations"] == 2
        res = cli.submit(*dataset, rounds=N_ROUNDS)
        assert res.fasta == chained3
    finally:
        srv.drain(timeout=15)


# ------------------------------------------- audit catches poisoned entry
def test_audit_catches_poisoned_cache_entry(dataset, tmp_path):
    """THE cache-safety pin: corrupt every cached consensus behind the
    server's back, resubmit with the sentinel at rate 1.0 — each hit's
    shadow re-execution catches the divergence, quarantines + evicts
    the ENTRY (no engine demotion, no lane quarantine: the device
    never produced those bytes), repairs the window with oracle bytes,
    and the job's FASTA is byte-identical to the clean run."""
    jpath = str(tmp_path / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       workers=1, warmup=False, wincache=True,
                       audit_rate=1.0, journal=jpath).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        clean = cli.submit(*dataset)
        assert srv.auditor.snapshot()["mismatches"] == 0
        wc = srv.batcher.wincache
        with wc._lock:
            assert wc._entries
            for key, (cons, pol) in list(wc._entries.items()):
                flip = b"T" if cons[:1] != b"T" else b"A"
                wc._entries[key] = (flip + cons[1:], pol)
        res = cli.submit(*dataset)
        # repaired: output unharmed despite the poisoned entries
        assert res.fasta == clean.fasta
        audit = srv.auditor.snapshot()
        assert audit["mismatches"] > 0
        assert audit["repaired"] >= audit["mismatches"]
        assert audit["demotions"] == 0  # the entry took the blame
        snap = srv.batcher.snapshot()
        assert all(l["health"] == 1.0 and not l["quarantined"]
                   for l in snap["lanes"])
        cache = wc.snapshot()
        assert cache["quarantined"] >= audit["mismatches"]
        # the journal carries the typed verdict, lane-labeled "cache"
        mism = [e for e in read_journal(jpath)
                if e.get("event") == "audit-mismatch"]
        assert mism and all(e["lane"] == "cache"
                            and e["cache"] == "entry-quarantined"
                            for e in mism)
        # a condemned key stays refused: the same content re-dispatches
        res3 = cli.submit(*dataset)
        assert res3.fasta == clean.fasta
        assert srv.auditor.snapshot()["mismatches"] == \
            audit["mismatches"]
    finally:
        srv.drain(timeout=15)


# --------------------------------------------------------- wincache units
def _win(seed: int = 0, length: int = 40, type_=WindowType.kNGS):
    import random

    rng = random.Random(seed)
    bb = "".join(rng.choice("ACGT") for _ in range(length))
    w = create_window(0, seed, type_, bb.encode(), b"!" * length)
    w.add_layer(bb.encode(), None, 0, length - 1)
    return w


def test_content_digest_keys_content_and_type():
    assert window_content_digest(_win(1)) == window_content_digest(
        _win(1))
    assert window_content_digest(_win(1)) != window_content_digest(
        _win(2))
    assert window_content_digest(_win(1)) != window_content_digest(
        _win(1, type_=WindowType.kTGS))


def test_posture_key_shape_and_stability():
    key = posture_key()
    assert isinstance(key, tuple) and len(key) == 5
    assert key == posture_key()
    # a different posture must produce a different cache key for the
    # same content under the same engine parameters
    w, ek = _win(3), ("engine", 1)
    k1 = WindowCache.key(w, ek, posture=("0", "auto", "0", True, "cpu"))
    k2 = WindowCache.key(w, ek, posture=("1", "auto", "0", True, "cpu"))
    assert k1 != k2
    assert WindowCache.key(w, ("engine", 2), k1[2]) != k1


def test_wincache_lru_eviction_and_counters():
    wc = WindowCache(max_bytes=600)  # ~2 entries of 100B + overhead
    for i in range(3):
        wc.store((i,), bytes(100), True)
    snap = wc.snapshot()
    assert snap["entries"] == 2 and snap["evictions"] == 1
    assert wc.lookup((0,)) is None          # evicted oldest
    assert wc.lookup((1,)) is not None
    assert wc.lookup((2,)) is not None
    wc.lookup((1,))  # refreshes recency: (2,) is now the LRU entry
    wc.store((3,), bytes(100), True)
    assert wc.lookup((2,)) is None and wc.lookup((1,)) is not None
    snap = wc.snapshot()
    assert snap["hits"] == 4 and snap["misses"] == 2
    assert snap["hit_bytes"] == 400
    assert snap["bytes"] <= wc.max_bytes


def test_wincache_quarantine_refuses_restore():
    wc = WindowCache()
    wc.store(("k",), b"bytes", True)
    wc.quarantine(("k",))
    assert wc.lookup(("k",)) is None
    wc.store(("k",), b"bytes", True)        # a poisoned producer retries
    assert wc.lookup(("k",)) is None
    assert wc.quarantined(("k",))
    snap = wc.snapshot()
    assert snap["quarantined"] == 1 and snap["entries"] == 0
    # invalidate_all drops entries but keeps the condemnation
    wc.store(("ok",), b"x", True)
    assert wc.invalidate_all("test") == 1
    assert wc.snapshot()["entries"] == 0
    wc.store(("k",), b"bytes", True)
    assert wc.lookup(("k",)) is None


def test_wincache_env_strict(monkeypatch):
    monkeypatch.delenv("RACON_TPU_WINCACHE", raising=False)
    monkeypatch.delenv("RACON_TPU_WINCACHE_MAX_BYTES", raising=False)
    assert wincache_from_env() is None
    monkeypatch.setenv("RACON_TPU_WINCACHE", "0")
    assert wincache_from_env() is None
    monkeypatch.setenv("RACON_TPU_WINCACHE", "1")
    wc = wincache_from_env()
    assert isinstance(wc, WindowCache)
    monkeypatch.setenv("RACON_TPU_WINCACHE_MAX_BYTES", "4096")
    assert wincache_from_env().max_bytes == 4096
    # strict: a typo fails loudly, naming the variable — never a
    # silently uncached server
    monkeypatch.setenv("RACON_TPU_WINCACHE", "yes")
    with pytest.raises(RaconError, match="RACON_TPU_WINCACHE"):
        wincache_from_env()
    monkeypatch.setenv("RACON_TPU_WINCACHE", "1")
    for bad in ("64MiB", "0", "-1"):
        monkeypatch.setenv("RACON_TPU_WINCACHE_MAX_BYTES", bad)
        with pytest.raises(RaconError,
                           match="RACON_TPU_WINCACHE_MAX_BYTES"):
            wincache_from_env()


# ------------------------------------------------------------ remap units
def _seq(name: str, data: bytes):
    return types.SimpleNamespace(name=name, data=data)


def _genome(seed: int = 7, n: int = 600) -> bytes:
    import random

    rng = random.Random(seed)
    return bytes(rng.choice(b"ACGT") for _ in range(n))


def test_revcomp():
    assert revcomp(b"AAACCC") == b"GGGTTT"
    assert revcomp(b"ACGTN") == b"NACGT"
    assert revcomp(revcomp(b"GATTACA")) == b"GATTACA"


def test_remap_read_forward_and_tagged_name():
    g = _genome()
    target = _seq("ctg1 LN:i:600 RC:i:12 XC:f:0.99", g)
    index = build_index([target])
    read = _seq("r0", g[100:300])
    row = remap_read(read, [target], index)
    assert row is not None
    f = row.split("\t")
    # PAF target name must be the TAG-STRIPPED first token (a FASTA
    # re-parse keeps only that; a tagged name would drop every row)
    assert f[5] == "ctg1"
    assert f[0] == "r0" and f[4] == "+"
    q_len, q0, q1 = int(f[1]), int(f[2]), int(f[3])
    t_len, t0, t1 = int(f[6]), int(f[7]), int(f[8])
    assert q_len == 200 and t_len == 600
    assert 0 <= q0 < q1 <= q_len
    assert 100 <= t0 < t1 <= 300  # anchors on the true diagonal
    assert int(f[9]) <= int(f[10])  # matches <= alignment length


def test_remap_read_reverse_strand_coordinates():
    g = _genome()
    target = _seq("ctg1", g)
    index = build_index([target])
    read = _seq("r1", revcomp(g[250:450]))
    row = remap_read(read, [target], index)
    assert row is not None
    f = row.split("\t")
    assert f[4] == "-"
    # '-' rows carry query coordinates in the FORWARD read frame
    q_len, q0, q1 = int(f[1]), int(f[2]), int(f[3])
    t0, t1 = int(f[7]), int(f[8])
    assert 0 <= q0 < q1 <= q_len
    assert 250 <= t0 < t1 <= 450


def test_remap_overlaps_deterministic_and_drops_unanchored():
    g = _genome()
    targets = [_seq("a", g[:300]), _seq("b", g[300:])]
    reads = [_seq("r0", g[50:250]),
             _seq("r1", g[350:550]),
             _seq("junk", _genome(seed=99, n=200))]  # anchors nowhere
    rows = remap_overlaps(reads, targets)
    assert rows == remap_overlaps(reads, targets)  # deterministic
    names = [r.split("\t")[0] for r in rows]
    assert names == ["r0", "r1"]
    assert rows[0].split("\t")[5] == "a"
    assert rows[1].split("\t")[5] == "b"


def test_remap_write_helpers(tmp_path):
    paf = write_paf(["a\t1", "b\t2"], str(tmp_path / "o.paf"))
    assert open(paf).read() == "a\t1\nb\t2\n"
    fa = write_fasta([_seq("c1 LN:i:4", b"ACGT")],
                     str(tmp_path / "d.fasta"))
    assert open(fa, "rb").read() == b">c1 LN:i:4\nACGT\n"


def test_repeat_filter_drops_flooded_kmers():
    poly = _seq("t", b"A" * 200)
    index = build_index([poly], max_occ=16)
    assert index == {}  # one k-mer, 200-15+1 occurrences: dropped
    read = _seq("r", b"A" * 60)
    assert remap_read(read, [poly], index) is None


# ------------------------------------------------------- perfgate pins
def _write(path, doc):
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return str(path)


def rounds_artifact(speedup=2.0, identical=True, hit=0.4, resub=1.0,
                    mismatches=0):
    art = {"mode": "rounds", "jobs": 3,
           "rounds": {"requested": 4, "completed": 4,
                      "round2_speedup_x": speedup},
           "cache": {"identical": identical, "hit_rate": hit,
                     "hits": 20, "misses": 30,
                     "resubmit": {"hit_rate": resub,
                                  "speedup_x": 3.0}},
           "audit": {"rate": 0.05, "mismatches": mismatches,
                     "repaired": mismatches},
           "pass": True}
    return art


def test_perfgate_rounds_gates(tmp_path, capsys):
    import perfgate

    art = _write(tmp_path / "R.json", rounds_artifact(speedup=2.0))
    # absolute cache gates alone carry the verdict (no implicit
    # baseline needed), and the explicit floor gates alongside
    assert perfgate.main(["--artifact", art]) == 0
    assert perfgate.main(["--artifact", art,
                          "--round2-speedup-min", "1.5"]) == 0
    err = capsys.readouterr().err
    assert "cache.identical" in err and "rounds.round2_speedup_x" in err
    assert perfgate.main(["--artifact", art,
                          "--round2-speedup-min", "2.5"]) == 1


def test_perfgate_rounds_identity_and_hit_rate_fail(tmp_path):
    import perfgate

    art = _write(tmp_path / "R.json",
                 rounds_artifact(identical=False))
    assert perfgate.main(["--artifact", art]) == 1
    art = _write(tmp_path / "R2.json",
                 rounds_artifact(hit=0.0, resub=0.0))
    assert perfgate.main(["--artifact", art]) == 1
    art = _write(tmp_path / "R3.json", rounds_artifact(mismatches=2))
    assert perfgate.main(["--artifact", art]) == 1
    # first cached pass near zero is fine when the resubmit proves the
    # cache engaged
    art = _write(tmp_path / "R4.json",
                 rounds_artifact(hit=0.0, resub=1.0))
    assert perfgate.main(["--artifact", art]) == 0


def test_perfgate_round2_min_mandatory_names_key(tmp_path, capsys):
    import perfgate

    # an artifact without the gated key is a BROKEN gate naming it
    art = rounds_artifact()
    del art["rounds"]["round2_speedup_x"]
    path = _write(tmp_path / "R.json", art)
    assert perfgate.main(["--artifact", path]) == 2
    assert "rounds.round2_speedup_x" in capsys.readouterr().err
    # ... and so is requesting the floor over a non-rounds artifact
    synth = _write(tmp_path / "S.json",
                   {"mode": "synth",
                    "synth": {"windows_per_s": 6.0}})
    assert perfgate.main(["--artifact", synth,
                          "--windows-per-s-min", "5.0",
                          "--round2-speedup-min", "1.0"]) == 2
    assert "rounds.round2_speedup_x" in capsys.readouterr().err


def test_repo_rounds_artifact_passes():
    """Acceptance half: the committed rounds artifact gates green with
    the speedup floor the CI invocation uses."""
    import subprocess

    art = os.path.join(REPO, "SERVEBENCH_rounds_r16.json")
    if not os.path.isfile(art):
        pytest.skip("no SERVEBENCH_rounds artifact in this checkout")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perfgate.py"),
         "--artifact", art, "--round2-speedup-min", "1.0"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    doc = json.load(open(art))
    assert doc["pass"] and doc["cache"]["identical"]
    assert max(doc["cache"]["hit_rate"],
               doc["cache"]["resubmit"]["hit_rate"]) > 0.0


# ------------------------------------------------------- obsreport pins
def _journal(tmp_path, events):
    path = tmp_path / "j.jsonl"
    t = time.time()
    with open(path, "w") as fh:
        for i, e in enumerate(events):
            fh.write(json.dumps(dict(e, t=t + i * 0.01)) + "\n")
    return str(path)


def _lifecycle(job, rounds_events):
    return ([{"event": "received", "job": job},
             {"event": "admitted", "job": job},
             {"event": "started", "job": job}]
            + rounds_events
            + [{"event": "finished", "job": job, "sequences": 0}])


def test_obsreport_unbalanced_rounds_is_red(tmp_path, capsys):
    import obsreport

    path = _journal(tmp_path, _lifecycle("j1", [
        {"event": "round-started", "job": "j1", "round": 1, "of": 2},
        {"event": "round-finished", "job": "j1", "round": 1, "of": 2},
        {"event": "round-started", "job": "j1", "round": 2, "of": 2},
    ]))
    rc = obsreport.main(["--journal", path, "--check",
                         "--flight-dir", str(tmp_path / "none")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "2 round-started events vs 1 round-finished" in out


def test_obsreport_rotation_window_tolerated():
    import obsreport

    # round lines whose `received` fell out of the rotation window are
    # history loss, not a lifecycle bug — same tolerance as the other
    # checks
    entries = [{"event": "round-finished", "job": "old", "round": 3,
                "of": 3}]
    assert obsreport.check_rounds(entries) == []
    entries = [{"event": "received", "job": "j"},
               {"event": "round-started", "job": "j", "round": 1},
               {"event": "round-finished", "job": "j", "round": 1}]
    assert obsreport.check_rounds(entries) == []


# ------------------------------------------- servetop + fleet satellite
def _wincache_scrape():
    from racon_tpu.obs import prom

    return prom.render(
        {"serve.batch.iterations": 5,
         "serve.wincache.ops": prom.Labeled(
             [({"op": "eviction"}, 2), ({"op": "hit"}, 30),
              ({"op": "invalidation"}, 1), ({"op": "miss"}, 10),
              ({"op": "put"}, 12), ({"op": "quarantined"}, 1)]),
         "serve.wincache.hit_bytes": 8192,
         "serve.rounds_jobs": 4, "serve.rounds_completed": 12},
        {"serve.queue_depth": 0, "serve.inflight": 1,
         "serve.worker_lanes": 1,
         "serve.wincache.bytes": 4096, "serve.wincache.entries": 9,
         "serve.wincache.max_bytes": 1 << 26,
         "serve.rounds_inflight": 1})


def test_servetop_cache_cell_and_rounds_suffix():
    import servetop

    from racon_tpu.obs import prom

    parsed = prom.parse(_wincache_scrape())
    cell = servetop.cache_cell(parsed)
    assert cell == {"hit_pct": 75.0, "hits": 30, "bytes": 4096,
                    "entries": 9, "evictions": 2, "quarantined": 1}
    # a cache-off replica renders no cell
    plain = prom.parse(prom.render({"serve.batch.iterations": 5}, {}))
    assert servetop.cache_cell(plain) is None

    class _RS:
        endpoint = "r0"
        ok = True
        draining = False
        error = None
        scrape_s = 0.001

    rs = _RS()
    rs.parsed = parsed
    row = servetop.replica_row(rs, {}, 0.0)
    assert row["cache"]["hits"] == 30

    class _Snap:
        replicas = [rs]
        poll_s = 0.01
        counters = parsed.counters
        gauges = parsed.gauges
        counter_series = parsed.counter_series
        gauge_series = parsed.gauge_series

    screen = servetop.render_screen(_Snap(), {}, [row], {}, 0.0)
    assert "wincache" in screen and "hit%" in screen
    line = servetop.fleet_line(_Snap(), {}, {}, 0.0)
    assert "rounds 1 infl (12r/4j)" in line
    # no rounds job seen anywhere -> no suffix (armed-only)
    class _Plain:
        replicas = []
        poll_s = 0.01
        counters = plain.counters
        gauges = plain.gauges
        counter_series = plain.counter_series
        gauge_series = plain.gauge_series

    assert "rounds" not in servetop.fleet_line(_Plain(), {}, {}, 0.0)


def test_fleet_federates_wincache_families():
    from racon_tpu.obs import prom
    from racon_tpu.obs.fleet import (FleetAggregator, FleetSnapshot,
                                     ReplicaSample)

    snap = FleetSnapshot()
    for k in range(2):
        rs = ReplicaSample(f"r{k}")
        rs.parsed = prom.parse(_wincache_scrape())
        rs.ok = True
        snap.replicas.append(rs)
    FleetAggregator._merge(snap)
    series = snap.counter_series["racon_tpu_serve_wincache_ops_total"]
    by_op = {labels["op"]: v for labels, v in series.values()}
    assert by_op["hit"] == 60 and by_op["miss"] == 20
    assert snap.counters[
        "racon_tpu_serve_wincache_hit_bytes_total"] == 16384
    assert snap.counters["racon_tpu_serve_rounds_jobs_total"] == 8
    assert snap.gauges["racon_tpu_serve_rounds_inflight"] == 2
    assert snap.gauges["racon_tpu_serve_wincache_bytes"] == 8192
