"""Replicated serve fabric tests (serve/router.py) — the ISSUE's three
pinned contracts plus the satellite units:

  - byte-identity: 1 router over {1, 2, 4} warm replicas produces the
    SAME polished FASTA as a solo PolishServer run (which is itself
    pinned byte-identical to the one-shot path), including a
    multi-contig job with streamed parts — contig-sharded fan-out plus
    contig-order merge is invisible to the client;
  - failover: a replica that streams part of its shard and then dies
    (connection drop — what kill -9 looks like from the router) gets
    the shard re-dispatched to a healthy replica, the already-streamed
    contig deduped by the journal-backed ledger, output byte-identical
    with each contig EXACTLY once, `requeued` + `replica-down` in the
    router journal and the journal still lifecycle-consistent;
  - rolling restart: drain -> restart -> rejoin of each replica in turn
    while a wave of jobs runs loses zero jobs, and the router's healthz
    tracks the routable count throughout;
  - client retry jitter bounds (`_retry_delay`), and journal fsync mode
    surviving rotation plus a torn final line.
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import socket
import sys
import threading
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.errors import RaconError
from racon_tpu.obs.journal import Journal, check_consistency, read_journal
from racon_tpu.serve import (PolishClient, PolishRouter, PolishServer,
                             RouterConfig, make_synth_dataset)
from racon_tpu.serve.client import RETRY_DELAY_CAP_S, _retry_delay
from racon_tpu.serve.protocol import ProtocolError, recv_frame, send_frame
from racon_tpu.serve.router import _JobMerge, router_main


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset4(tmp_path_factory):
    """Four independent contigs — enough to shard 1/2/4 ways."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("router_data")),
                              contigs=4)


def polish_solo(paths) -> bytes:
    p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


@pytest.fixture(scope="module")
def solo4(dataset4):
    return polish_solo(dataset4)


@pytest.fixture(scope="module")
def replicas4(tmp_path_factory):
    d = tmp_path_factory.mktemp("router_reps")
    socks = [str(d / f"rep{i}.sock") for i in range(4)]
    servers = [PolishServer(socket_path=s, workers=2).start()
               for s in socks]
    yield socks
    for srv in servers:
        srv.drain(timeout=10)


def _wait_routable(cli: PolishClient, want: int, deadline_s: float = 30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with contextlib.suppress(Exception):
            hz = cli.request({"type": "healthz"})
            if hz.get("routable") == want:
                return hz
        time.sleep(0.1)
    raise AssertionError(f"router never reached routable == {want}")


# ------------------------------------------------------ merge ledger unit
def test_merge_dedupes_requeued_parts_and_keeps_order():
    emitted = []
    m = _JobMerge(2, emit_part=lambda k, i, n, f: emitted.append((i, n)))
    m.on_part(1, {"name": "c", "fasta": "C"})  # later shard buffers
    assert emitted == []
    m.on_part(0, {"name": "a", "fasta": "A"})
    m.requeue(0)  # replica died after streaming "a"
    m.on_part(0, {"name": "a", "fasta": "A"})  # re-run replays: deduped
    m.on_part(0, {"name": "b", "fasta": "B"})
    m.shard_done(0, {})
    m.shard_done(1, {})
    assert [name for _i, name in emitted] == ["a", "b", "c"]
    assert [i for i, _name in emitted] == [0, 1, 2]
    assert m.fasta() == "ABC"
    assert m.total_routed == 3


# ------------------------------------------------------------- byte pins
def test_router_byte_identity_1_2_4_replicas(dataset4, solo4, replicas4,
                                             tmp_path):
    for n in (1, 2, 4):
        router = PolishRouter(replicas=",".join(replicas4[:n]),
                              socket_path=str(tmp_path / f"r{n}.sock"),
                              health_interval_s=0.2).start()
        try:
            cli = PolishClient(socket_path=router.config.socket_path)
            raw = cli.request({"type": "submit",
                               "sequences": dataset4[0],
                               "overlaps": dataset4[1],
                               "target": dataset4[2]})
            assert raw["fasta"].encode("latin-1") == solo4
            assert raw["router"]["shards"] == min(n, 4)
            assert raw["router"]["requeues"] == 0
            # streamed multi-contig: parts arrive globally renumbered
            # in contig order and concatenate byte-identical
            parts = []
            res = cli.submit(*dataset4, stream=True,
                             on_part=lambda f: parts.append(f))
            assert res.fasta == solo4
            assert [p["part"] for p in parts] == list(range(len(parts)))
            assert len(parts) == 4  # one per contig, each exactly once
        finally:
            router.drain()


def test_router_metrics_and_healthz_http(dataset4, replicas4, tmp_path):
    router = PolishRouter(replicas=",".join(replicas4[:2]),
                          socket_path=str(tmp_path / "rm.sock"),
                          metrics_port=0,
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        cli.submit(*dataset4)
        base = f"http://127.0.0.1:{router.config.metrics_port}"
        body = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        assert "racon_tpu_router_replicas 2" in body
        assert "racon_tpu_router_replicas_routable 2" in body
        assert "racon_tpu_router_jobs_completed_total 1" in body
        assert "racon_tpu_router_requeued_outstanding 0" in body
        # federated replica families ride the same body (fleet merge)
        assert "racon_tpu_fleet_replicas 2" in body
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["ok"] and doc["routable"] == 2 and doc["router"]
    finally:
        router.drain()


# ------------------------------------------------------------- failover
class _DyingReplica:
    """Protocol-complete fake replica: healthy to every probe, but a
    submit streams the TRUE first polished contig of its shard and then
    drops the connection — exactly what kill -9 after one result_part
    looks like from the router's side, made deterministic."""

    def __init__(self, sock_path: str, polished_records: dict):
        self.path = sock_path
        self.polished = polished_records  # contig name -> record text
        self.submits = 0
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(8)
        self._lst.settimeout(0.2)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                rtype = req.get("type")
                if rtype == "healthz":
                    send_frame(conn, {"type": "healthz", "ok": True,
                                      "draining": False})
                elif rtype == "scrape":
                    send_frame(conn, {"type": "metrics", "text": ""})
                elif rtype == "ping":
                    send_frame(conn, {"type": "pong"})
                elif rtype == "submit":
                    self.submits += 1
                    from racon_tpu.io.parsers import \
                        create_sequence_parser
                    contigs: list = []
                    create_sequence_parser(req["target"],
                                           "test").parse(contigs, -1)
                    name = contigs[0].name
                    send_frame(conn, {"type": "result_part",
                                      "job_id": "stub", "part": 0,
                                      "name": name,
                                      "fasta": self.polished[name]})
                    with contextlib.suppress(OSError):
                        conn.shutdown(socket.SHUT_RDWR)
                    return
                else:
                    send_frame(conn, {"type": "ok"})
        except (OSError, ProtocolError):
            return
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def close(self):
        self._stop.set()
        with contextlib.suppress(OSError):
            self._lst.close()


def _records_by_name(fasta: bytes) -> dict:
    """Polished records keyed by contig name (first header token — the
    polisher appends LN/RC/XC tags after it)."""
    out = {}
    for chunk in fasta.split(b">")[1:]:
        header, _, _body = chunk.partition(b"\n")
        out[header.split()[0].decode()] = (b">" + chunk).decode("latin-1")
    return out


def test_failover_requeues_with_ledger_dedupe(dataset4, solo4, tmp_path):
    stub = _DyingReplica(str(tmp_path / "stub.sock"),
                         _records_by_name(solo4))
    real = PolishServer(socket_path=str(tmp_path / "real.sock"),
                        workers=2).start()
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(
        replicas=f"{stub.path},{real.config.socket_path}",
        socket_path=str(tmp_path / "r.sock"), journal=journal,
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        parts: list[dict] = []
        res = cli.submit(*dataset4, stream=True,
                         on_part=lambda f: parts.append(f))
        assert res.fasta == solo4
        # each contig streamed to the client EXACTLY once despite the
        # re-run replaying the stub's already-routed part
        assert len(parts) == 4
        assert len({p["name"] for p in parts}) == 4
        headers = [ln for ln in res.fasta.split(b"\n")
                   if ln.startswith(b">")]
        assert len(headers) == 4 and len(set(headers)) == 4
        assert stub.submits >= 1  # the dying replica really got a shard
        hz = cli.request({"type": "healthz"})
        assert hz["requeued_outstanding"] == 0  # settled after requeue
    finally:
        router.drain()
        stub.close()
        real.drain(timeout=10)
    entries = read_journal(journal)
    events = [e["event"] for e in entries]
    assert "replica-down" in events
    assert "requeued" in events
    # every client-visible contig was ledgered exactly once
    routed = [e for e in entries if e["event"] == "part-routed"]
    assert len(routed) == 4
    assert len({(e["job"], e["part"]) for e in routed}) == 4
    assert check_consistency(entries) == []


# ------------------------------------------------------- rolling restart
def test_rolling_restart_loses_no_jobs(dataset4, solo4, tmp_path):
    socks = [str(tmp_path / "a.sock"), str(tmp_path / "b.sock")]
    servers = {s: PolishServer(socket_path=s, workers=2).start()
               for s in socks}
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "r.sock"),
                          health_interval_s=0.2,
                          replica_wait_s=30.0).start()
    cli = PolishClient(socket_path=router.config.socket_path)
    stop = threading.Event()
    results: list[bytes] = []
    errors: list[Exception] = []

    def wave():
        w = PolishClient(socket_path=router.config.socket_path)
        while not stop.is_set():
            try:
                results.append(w.submit(*dataset4).fasta)
            except Exception as exc:  # noqa: BLE001 — the assertion
                errors.append(exc)
                return

    threads = [threading.Thread(target=wave, daemon=True)
               for _ in range(2)]
    try:
        for t in threads:
            t.start()
        for s in socks:  # drain -> restart -> rejoin, each in turn
            servers[s].drain(timeout=20)
            hz = _wait_routable(cli, 1)
            assert hz["ok"]  # one replica down, still serving
            servers[s] = PolishServer(socket_path=s, workers=2).start()
            _wait_routable(cli, 2)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not errors, f"wave lost jobs: {errors!r}"
        assert len(results) >= 2
        assert all(b == solo4 for b in results)
    finally:
        stop.set()
        router.drain()
        for srv in servers.values():
            srv.drain(timeout=10)


# ---------------------------------------------------------- config + CLI
def test_router_config_validation(monkeypatch):
    monkeypatch.delenv("RACON_TPU_ROUTER_REPLICAS", raising=False)
    with pytest.raises(RaconError, match="no replicas"):
        RouterConfig()
    with pytest.raises(RaconError, match="metrics base"):
        RouterConfig(replicas="http://x:9100/metrics")
    with pytest.raises(RaconError, match="localhost"):
        RouterConfig(replicas="10.1.2.3:4000")
    with pytest.raises(RaconError, match="unknown router option"):
        RouterConfig(replicas="/tmp/a.sock", bogus=1)
    monkeypatch.setenv("RACON_TPU_ROUTER_HEALTH_INTERVAL", "nope")
    with pytest.raises(RaconError, match="HEALTH_INTERVAL"):
        RouterConfig(replicas="/tmp/a.sock")


def test_router_cli_rejects_bad_config(capsys):
    assert router_main(["--replicas", ""]) == 1
    assert "error" in capsys.readouterr().err


# ------------------------------------------------- satellite: jitter
def test_retry_delay_jitter_bounds():
    rng = random.Random(7)
    for hint in (0.0, 0.2, 1.0, 5.0):
        for _ in range(300):
            d = _retry_delay(hint, rng=rng)
            assert 0.0 <= d <= RETRY_DELAY_CAP_S
            assert 0.75 * hint - 1e-9 <= d <= 1.25 * hint + 1e-9
    # cap: a hostile/huge hint can never park the client past the cap
    for _ in range(300):
        assert _retry_delay(1e9, rng=rng) <= RETRY_DELAY_CAP_S
    assert _retry_delay(-5.0, rng=rng) == 0.0
    # jitter actually spreads (anti-thundering-herd is the point)
    spread = {round(_retry_delay(1.0, rng=rng), 3) for _ in range(50)}
    assert len(spread) > 10


# ----------------------------------------- satellite: journal durability
def test_journal_fsync_rotation_and_torn_tail(tmp_path, monkeypatch):
    monkeypatch.setenv("RACON_TPU_JOURNAL_FSYNC", "1")
    path = str(tmp_path / "ledger.jsonl")
    j = Journal(path, max_bytes=512)
    assert j.fsync  # env opt-in picked up
    for i in range(40):  # far past max_bytes: forces rotation
        j.record("received", job=f"j{i}")
        j.record("started", job=f"j{i}")
        j.record("finished", job=f"j{i}")
    assert os.path.isfile(path + ".1")  # rotation really happened
    assert j.dropped == 0
    j.close()
    # mid-write crash: a torn, unterminated final line on disk
    with open(path, "a", encoding="utf-8") as fh:
        fh.write('{"t":1.0,"event":"recei')
    entries = read_journal(path)
    assert all(isinstance(e, dict) and "event" in e for e in entries)
    # at most the final (torn) line is lost — every recorded line that
    # survived rotation parses; only the two live generations count
    # (rotation discards older ones by design)
    with open(path, encoding="utf-8") as fh:
        live_main = sum(1 for ln in fh if ln.endswith("\n"))
    with open(path + ".1", encoding="utf-8") as fh:
        live_rotated = sum(1 for ln in fh)
    assert len(entries) == live_main + live_rotated
    finished = [e for e in entries if e["event"] == "finished"]
    assert finished  # the tail generation is readable, not garbage
    # explicit override beats the env knob
    j2 = Journal(str(tmp_path / "nofsync.jsonl"), fsync=False)
    assert not j2.fsync
    j2.close()


# -------------------------------------------------------- servetop suffix
def test_servetop_fleet_line_router_suffix(replicas4, tmp_path):
    """Satellite pin: servetop's fleet line grows a router suffix when
    a polled endpoint is the shard-aware router — routable vs
    configured replica counts and outstanding requeued shards, read
    from the racon_tpu_router_* gauges the router's scrape federates —
    and stays suffix-free against a plain replica."""
    import servetop

    from racon_tpu.obs.fleet import FleetAggregator

    router = PolishRouter(replicas=replicas4[:2],
                          socket_path=str(tmp_path / "rt.sock"),
                          health_interval_s=0.2).start()
    try:
        _wait_routable(
            PolishClient(socket_path=router.config.socket_path), 2)
        agg = FleetAggregator([router.config.socket_path])
        snap = agg.poll()
        agg.close()
        line = servetop.fleet_line(snap, {}, {}, 0.0)
        assert "router 2/2 routable" in line
        assert "requeued 0" in line
        assert "[REQUEUED]" not in line
    finally:
        router.drain(timeout=10)
    agg = FleetAggregator([replicas4[0]])
    snap = agg.poll()
    agg.close()
    assert servetop._fleet_router(snap) == ""
