"""Router fragment read-range sharding tests (serve/router.py third
planner) — the ISSUE 20 pinned contracts:

  - byte-identity: a fragment job through the router over {1, 2, 4}
    replicas produces the SAME corrected-reads FASTA as a solo kF run
    — at 2 and 4 the job really read-range-sharded (`router.fragment`
    / `frag_shards`), children carried contiguous ascending
    [frag_lo, frag_hi) slices, and the merged `reads` accounting
    matches the output record count;
  - streamed surface: group frames relay through the router in global
    read order, and their concatenation is the whole job;
  - failover: a replica that drops its fragment shard's connection
    gets the [frag_lo, frag_hi) slice re-dispatched to a survivor —
    output byte-identical, `frag-plan` and `requeued` in the journal;
  - mid-stream kill: a replica that dies AFTER streaming some read
    groups triggers a requeue whose re-streamed duplicates are dropped
    at read-GROUP granularity (the merge ledger), so the journal's
    `part-routed` frag receipts still tile [0, n_reads) exactly once
    and `obsreport --check` stays green.
"""

from __future__ import annotations

import contextlib
import os
import socket
import sys
import threading
import time

import pytest

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.obs.journal import read_journal
from racon_tpu.serve import PolishClient, PolishRouter, PolishServer
from racon_tpu.serve.protocol import ProtocolError, recv_frame, send_frame
from racon_tpu.serve.server import make_fragment_dataset

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

N_READS = 17


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    return make_fragment_dataset(
        str(tmp_path_factory.mktemp("rfrag_data")))


@pytest.fixture(scope="module")
def solo_bytes(dataset):
    p = create_polisher(*dataset, PolisherType.kF, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish(True))


@pytest.fixture(scope="module")
def replicas(tmp_path_factory):
    d = tmp_path_factory.mktemp("rfrag_reps")
    socks = [str(d / f"rep{i}.sock") for i in range(4)]
    servers = [PolishServer(socket_path=s, workers=2,
                            warmup=False).start() for s in socks]
    yield socks
    for srv in servers:
        srv.drain(timeout=10)


def _wait_routable(cli: PolishClient, want: int, deadline_s: float = 30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with contextlib.suppress(Exception):
            hz = cli.request({"type": "healthz"})
            if hz.get("routable") == want:
                return hz
        time.sleep(0.1)
    raise AssertionError(f"router never reached routable == {want}")


# ------------------------------------------------------------- byte pins
def test_fragment_byte_identity_1_2_4_replicas(dataset, solo_bytes,
                                               replicas, tmp_path):
    for n in (1, 2, 4):
        router = PolishRouter(replicas=",".join(replicas[:n]),
                              socket_path=str(tmp_path / f"rf{n}.sock"),
                              health_interval_s=0.2).start()
        try:
            cli = PolishClient(socket_path=router.config.socket_path)
            _wait_routable(cli, n)
            r = cli.submit(*dataset, fragment=True)
            assert r.fasta == solo_bytes
            assert r.router["fragment"] is True
            assert r.router["frag_shards"] == n
            assert r.router["requeues"] == 0
            assert r.router["reads"] == solo_bytes.count(b">")
            # streamed surface: group frames relay in global read order
            parts: list[dict] = []
            res = cli.submit(*dataset, fragment=True,
                             on_part=parts.append)
            assert res.fasta == solo_bytes
            assert b"".join(p["fasta"].encode("latin-1")
                            for p in parts) == solo_bytes
        finally:
            router.drain()


# ------------------------------------------------------------- failover
class _StubReplica:
    """Protocol-complete fake replica: healthy to every probe, submit
    behavior injectable (see tests/test_router_range.py)."""

    def __init__(self, sock_path: str, on_submit):
        self.path = sock_path
        self.on_submit = on_submit
        self.submits = 0
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(8)
        self._lst.settimeout(0.2)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                rtype = req.get("type")
                if rtype == "healthz":
                    send_frame(conn, {"type": "healthz", "ok": True,
                                      "draining": False})
                elif rtype == "scrape":
                    send_frame(conn, {"type": "metrics", "text": ""})
                elif rtype == "submit":
                    self.submits += 1
                    self.on_submit(conn, req)
                    return
                else:
                    send_frame(conn, {"type": "ok"})
        except (OSError, ProtocolError):
            return
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def close(self):
        self._stop.set()
        with contextlib.suppress(OSError):
            self._lst.close()


def test_fragment_shard_requeues_to_survivor(dataset, solo_bytes,
                                             tmp_path):
    """A replica dropping the connection the moment its fragment shard
    lands: the [frag_lo, frag_hi) slice re-dispatches to the survivor
    and the merged output stays byte-identical."""
    def drop(conn, _req):
        with contextlib.suppress(OSError):
            conn.shutdown(socket.SHUT_RDWR)

    stub = _StubReplica(str(tmp_path / "stub.sock"), drop)
    real = PolishServer(socket_path=str(tmp_path / "real.sock"),
                        workers=2, warmup=False).start()
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(
        replicas=f"{stub.path},{real.config.socket_path}",
        socket_path=str(tmp_path / "r.sock"), journal=journal,
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        r = cli.submit(*dataset, fragment=True)
        assert r.fasta == solo_bytes
        assert r.router["fragment"] is True
        assert r.router["requeues"] >= 1
        assert stub.submits >= 1  # the dying replica really got a slice
    finally:
        router.drain()
        stub.close()
        real.drain(timeout=10)
    events = [e["event"] for e in read_journal(journal)]
    assert "frag-plan" in events
    assert "requeued" in events


def test_fragment_midstream_kill_group_granularity_dedupe(
        dataset, solo_bytes, tmp_path):
    """The read-GROUP granularity requeue acceptance: a replica streams
    the FIRST read group of its shard, then dies. The survivor re-runs
    the whole [frag_lo, frag_hi) slice with the SAME group size (a
    homogeneous fleet, the decomposition contract in protocol.py), so
    the merge ledger drops the re-streamed duplicate of the accepted
    group — output byte-identical, and the journal's `part-routed`
    frag receipts tile [0, n_reads) exactly once, green under
    `obsreport --check`."""
    import obsreport

    # shard 0 of 2 over 17 reads is [0, 8); with frag_group=4 the real
    # replica decomposes it into groups [0,4) and [4,8). The stub
    # pre-streams the exact [0,4) frame the survivor would produce.
    records = solo_bytes.split(b"\n>")
    records = [records[0]] + [b">" + r for r in records[1:]]
    records = [r if r.endswith(b"\n") else r + b"\n" for r in records]
    assert len(records) == N_READS
    first_group = b"".join(records[:4])

    def stream_then_die(conn, req):
        assert req.get("frag_lo") == 0 and req.get("frag_hi") == 8
        with contextlib.suppress(OSError):
            send_frame(conn, {"type": "result_part",
                              "job_id": "stub-child", "part": 1,
                              "reads": 4, "frag": [0, 4],
                              "fasta": first_group.decode("latin-1")})
            conn.shutdown(socket.SHUT_RDWR)

    stub = _StubReplica(str(tmp_path / "stub.sock"), stream_then_die)
    real = PolishServer(socket_path=str(tmp_path / "real.sock"),
                        workers=2, warmup=False, frag_group=4).start()
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(
        replicas=f"{stub.path},{real.config.socket_path}",
        socket_path=str(tmp_path / "r.sock"), journal=journal,
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        r = cli.submit(*dataset, fragment=True)
        assert r.fasta == solo_bytes
        assert r.router["requeues"] >= 1
        assert r.router["reads"] == solo_bytes.count(b">")
    finally:
        router.drain()
        stub.close()
        real.drain(timeout=10)
    entries = read_journal(journal)
    routed = [e for e in entries if e.get("event") == "part-routed"]
    receipts = sorted((e["frag_lo"], e["frag_hi"]) for e in routed)
    # exactly-once tiling of the read axis, no duplicate for the
    # pre-streamed group
    expect = 0
    for lo, hi in receipts:
        assert lo == expect and hi > lo
        expect = hi
    assert expect == N_READS
    rc = obsreport.main(["--journal", journal,
                         "--flight-dir", str(tmp_path / "none"),
                         "--check"])
    assert rc == 0
