"""Sub-contig window-range sharding tests (serve/router.py +
serve/server.py) — the ISSUE's pinned contracts:

  - plan unit: `_plan_ranges` splits contigs at window-grid boundaries
    only (every lo/hi a multiple of the window length), gapless and
    non-overlapping per contig, never more shards than windows, extra
    budget to the most-windowed contig;
  - byte-identity: a ONE-contig job through the router over {1, 2, 4}
    replicas produces the SAME polished FASTA as a solo run — at 2 and
    4 the job really range-sharded (`router.range` / `range_shards`),
    and the streamed surface still ships exactly one whole-contig part;
  - window cache on: range shards against wincache-armed replicas stay
    byte-identical (cold and warm);
  - failover: a replica that drops its range shard's connection gets
    the (contig, [lo,hi)) slice re-dispatched to a survivor — output
    byte-identical, `requeued` in the journal (kill -9 with a partial
    segment stream is `tools/faultcheck.py --match range`);
  - compat: a pre-range replica that answers a range child with an
    unsegmented part fails the job typed `replica-incompatible` rather
    than corrupting the merge;
  - server validation: malformed `range_lo`/`range_hi` and the
    rounds+range combination answer typed `bad-request`; and the child
    wire contract — raw segments + `seg` stitch accounting over a full
    grid partition reassemble the solo body exactly.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time

import pytest

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.obs.journal import read_journal
from racon_tpu.serve import (PolishClient, PolishRouter, PolishServer,
                             make_synth_dataset)
from racon_tpu.serve.client import ServeError
from racon_tpu.serve.protocol import ProtocolError, recv_frame, send_frame


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset1(tmp_path_factory):
    """ONE contig (4 polish windows at wl=500) — the workload contig
    sharding cannot split past a single replica."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("range_data")))


def _polish_solo(paths) -> bytes:
    p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


@pytest.fixture(scope="module")
def solo1(dataset1):
    return _polish_solo(dataset1)


@pytest.fixture(scope="module")
def range_replicas(tmp_path_factory):
    d = tmp_path_factory.mktemp("range_reps")
    socks = [str(d / f"rep{i}.sock") for i in range(4)]
    servers = [PolishServer(socket_path=s, workers=2).start()
               for s in socks]
    yield socks
    for srv in servers:
        srv.drain(timeout=10)


def _wait_routable(cli: PolishClient, want: int, deadline_s: float = 30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with contextlib.suppress(Exception):
            hz = cli.request({"type": "healthz"})
            if hz.get("routable") == want:
                return hz
        time.sleep(0.1)
    raise AssertionError(f"router never reached routable == {want}")


# ------------------------------------------------------------- plan unit
class _C:
    def __init__(self, n: int):
        self.data = b"A" * n


def test_plan_ranges_grid_aligned_and_budgeted():
    wl = 500
    contigs = [_C(5000), _C(1200), _C(300)]  # 10 / 3 / 1 windows
    plan = PolishRouter._plan_ranges(contigs, cap=6, wl=wl)
    assert len(plan) == 6  # the whole budget lands
    by_c: dict[int, list] = {}
    for ci, lo, hi in plan:
        assert lo % wl == 0 and hi % wl == 0 and hi > lo
        by_c.setdefault(ci, []).append((lo, hi))
    assert set(by_c) == {0, 1, 2}  # every contig >= 1 shard
    for ci, spans in by_c.items():
        w = max(1, (len(contigs[ci].data) + wl - 1) // wl)
        spans.sort()
        assert spans[0][0] == 0 and spans[-1][1] == w * wl
        for (_alo, ahi), (blo, _bhi) in zip(spans, spans[1:]):
            assert ahi == blo  # gapless, non-overlapping
    # extra budget flows to the most-windowed contig
    assert len(by_c[0]) > len(by_c[1]) >= len(by_c[2]) == 1
    # a contig never splits past its window count
    assert PolishRouter._plan_ranges([_C(300)], cap=8, wl=wl) \
        == [(0, 0, wl)]


# ------------------------------------------------------------- byte pins
def test_range_byte_identity_1_2_4_replicas(dataset1, solo1,
                                            range_replicas, tmp_path):
    for n in (1, 2, 4):
        router = PolishRouter(replicas=",".join(range_replicas[:n]),
                              socket_path=str(tmp_path / f"rr{n}.sock"),
                              health_interval_s=0.2).start()
        try:
            cli = PolishClient(socket_path=router.config.socket_path)
            _wait_routable(cli, n)
            raw = cli.request({"type": "submit",
                               "sequences": dataset1[0],
                               "overlaps": dataset1[1],
                               "target": dataset1[2]})
            assert raw["fasta"].encode("latin-1") == solo1
            assert raw["router"]["requeues"] == 0
            if n == 1:
                assert not raw["router"].get("range")
            else:
                assert raw["router"]["range"] is True
                assert raw["router"]["range_shards"] == n
            # streamed surface: segments are router-internal — the
            # client still gets exactly ONE whole-contig part
            parts: list[dict] = []
            res = cli.submit(*dataset1, stream=True,
                             on_part=lambda f: parts.append(f))
            assert res.fasta == solo1
            assert len(parts) == 1 and parts[0]["part"] == 0
        finally:
            router.drain()


def test_range_wincache_byte_identity(dataset1, solo1, tmp_path):
    socks = [str(tmp_path / f"wc{i}.sock") for i in range(2)]
    servers = [PolishServer(socket_path=s, workers=2,
                            wincache=True).start() for s in socks]
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "rwc.sock"),
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        for _ in range(2):  # second run replays warm cache entries
            raw = cli.request({"type": "submit",
                               "sequences": dataset1[0],
                               "overlaps": dataset1[1],
                               "target": dataset1[2]})
            assert raw["fasta"].encode("latin-1") == solo1
            assert raw["router"].get("range") is True
    finally:
        router.drain()
        for srv in servers:
            srv.drain(timeout=10)


# ------------------------------------------------------------- failover
class _StubReplica:
    """Protocol-complete fake replica: healthy to every probe, submit
    behavior injectable — drop the connection (a replica dying the
    moment its range shard lands) or answer like a PRE-RANGE replica
    that ignored range_lo/range_hi."""

    def __init__(self, sock_path: str, on_submit):
        self.path = sock_path
        self.on_submit = on_submit
        self.submits = 0
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(8)
        self._lst.settimeout(0.2)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                rtype = req.get("type")
                if rtype == "healthz":
                    send_frame(conn, {"type": "healthz", "ok": True,
                                      "draining": False})
                elif rtype == "scrape":
                    send_frame(conn, {"type": "metrics", "text": ""})
                elif rtype == "submit":
                    self.submits += 1
                    self.on_submit(conn, req)
                    return
                else:
                    send_frame(conn, {"type": "ok"})
        except (OSError, ProtocolError):
            return
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def close(self):
        self._stop.set()
        with contextlib.suppress(OSError):
            self._lst.close()


def test_range_shard_requeues_to_survivor(dataset1, solo1, tmp_path):
    def drop(conn, _req):  # connection drop before any segment
        with contextlib.suppress(OSError):
            conn.shutdown(socket.SHUT_RDWR)

    stub = _StubReplica(str(tmp_path / "stub.sock"), drop)
    real = PolishServer(socket_path=str(tmp_path / "real.sock"),
                        workers=2).start()
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(
        replicas=f"{stub.path},{real.config.socket_path}",
        socket_path=str(tmp_path / "r.sock"), journal=journal,
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        raw = cli.request({"type": "submit",
                           "sequences": dataset1[0],
                           "overlaps": dataset1[1],
                           "target": dataset1[2]})
        assert raw["fasta"].encode("latin-1") == solo1
        assert raw["router"]["range"] is True
        assert raw["router"]["requeues"] >= 1
        assert stub.submits >= 1  # the dying replica really got a slice
    finally:
        router.drain()
        stub.close()
        real.drain(timeout=10)
    events = [e["event"] for e in read_journal(journal)]
    assert "range-plan" in events
    assert "requeued" in events


def test_pre_range_replica_fails_typed(dataset1, solo1, tmp_path):
    def unsegmented(conn, req):  # a part WITHOUT `seg`: whole-contig
        send_frame(conn, {"type": "result_part", "job_id": "stub",
                          "part": 1, "name": "draft",
                          "fasta": ">draft\nACGT\n"})
        send_frame(conn, {"type": "result", "job_id": "stub",
                          "fasta": ""})

    stub = _StubReplica(str(tmp_path / "old.sock"), unsegmented)
    real = PolishServer(socket_path=str(tmp_path / "real2.sock"),
                        workers=2).start()
    router = PolishRouter(
        replicas=f"{stub.path},{real.config.socket_path}",
        socket_path=str(tmp_path / "r2.sock"),
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        with pytest.raises(ServeError) as exc_info:
            cli.request({"type": "submit",
                         "sequences": dataset1[0],
                         "overlaps": dataset1[1],
                         "target": dataset1[2]})
        assert exc_info.value.code == "replica-incompatible"
    finally:
        router.drain()
        stub.close()
        real.drain(timeout=10)


# ------------------------------------------------- server-side contract
def test_server_rejects_malformed_range(dataset1, tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "v.sock"),
                       workers=1).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        base = {"type": "submit", "sequences": dataset1[0],
                "overlaps": dataset1[1], "target": dataset1[2]}
        for bad in ({"range_lo": "0", "range_hi": 500},
                    {"range_lo": 0, "range_hi": 0},
                    {"range_lo": -500, "range_hi": 500},
                    {"range_lo": True, "range_hi": 500},
                    {"range_lo": 500}):
            with pytest.raises(ServeError) as exc_info:
                cli.request({**base, **bad})
            assert exc_info.value.code == "bad-request"
        with pytest.raises(ServeError) as exc_info:
            cli.request({**base, "range_lo": 0, "range_hi": 500,
                         "rounds": 2})
        assert exc_info.value.code == "bad-request"
        assert "rounds" in str(exc_info.value)
    finally:
        srv.drain(timeout=10)


def test_range_child_segments_reassemble_solo_body(dataset1, solo1,
                                                   tmp_path):
    """The child wire contract, driven directly: raw segments + `seg`
    stitch accounting over a full grid partition concatenate to the
    solo body, and the accounting sums to the solo XC inputs."""
    from racon_tpu.io.parsers import create_sequence_parser

    contigs: list = []
    create_sequence_parser(dataset1[2], "range_test").parse(contigs, -1)
    plan = PolishRouter._plan_ranges(contigs, cap=2, wl=500)
    assert len(plan) == 2
    srv = PolishServer(socket_path=str(tmp_path / "c.sock"),
                       workers=1).start()
    try:
        cli = PolishClient(socket_path=srv.config.socket_path)
        segs = []
        for _ci, lo, hi in plan:
            parts: list[dict] = []
            cli.request({"type": "submit", "sequences": dataset1[0],
                         "overlaps": dataset1[1], "target": dataset1[2],
                         "range_lo": lo, "range_hi": hi,
                         "stream": True},
                        on_part=lambda f: parts.append(f))
            assert len(parts) == 1
            seg = parts[0]["seg"]
            assert seg["lo"] == lo and seg["hi"] == hi
            assert parts[0]["name"] == "draft"  # bare, no solo tags
            segs.append((seg["lo"], parts[0]["fasta"], seg))
        segs.sort(key=lambda s: s[0])
        body = "".join(f for _lo, f, _s in segs)
        solo_header, _, solo_rest = solo1.partition(b"\n")
        assert body.encode("latin-1") == solo_rest.rstrip(b"\n")
        # the accounting re-derives the solo tags exactly
        total = segs[0][2]["total_windows"]
        assert all(s["total_windows"] == total for _l, _f, s in segs)
        polished = sum(s["polished"] for _l, _f, s in segs)
        assert f"XC:f:{polished / total:.6f}".encode() in solo_header
        assert f"LN:i:{len(body)}".encode() in solo_header
    finally:
        srv.drain(timeout=10)
