"""Occupancy-aware batch scheduler tests (racon_tpu/sched).

The scheduler's contract has three legs, each pinned here:

  - CORRECTNESS: adaptive ladders and sorted packing change only WHICH
    static shapes are compiled and HOW jobs are ordered into chunks —
    output is byte-identical with the scheduler on vs off, for all three
    device engines (aligner, session POA, fused POA) and end-to-end
    through the polisher at pipeline depths 0 and 2.
  - OPTIMALITY: the ladder DPs are exact under their cost models
    (checked against brute force on small histograms) and adaptive
    occupancy is >= static occupancy on skewed inputs.
  - ACCOUNTING: per-bucket occupancy counters sum to exactly the cells
    the device was asked to process, and the resilience layer's
    per-chunk fault hooks still route repacked chunks to
    fallback/quarantine correctly.
"""

import itertools
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from test_device_poa import _make_windows, _pack  # noqa: E402

from racon_tpu.native import nw_cigar_batch, poa_batch  # noqa: E402
from racon_tpu.ops.align import BatchAligner  # noqa: E402
from racon_tpu.ops.poa_graph import DeviceGraphPOA  # noqa: E402
from racon_tpu.ops.poa_fused import FusedPOA  # noqa: E402
from racon_tpu.pipeline import DispatchPipeline  # noqa: E402
from racon_tpu.sched import (BatchScheduler, OccupancyStats,  # noqa: E402
                             ladder_1d, ladder_2d, padded_cost_1d)

ACGT = b"ACGT"


# ------------------------------------------------------------ ladder DPs

def test_ladder_1d_exact_vs_brute_force():
    rng = random.Random(0)
    for _ in range(60):
        vals = [rng.randrange(1, 40) for _ in range(rng.randrange(1, 10))]
        k = rng.randrange(1, 5)
        edges = ladder_1d(vals, k)
        assert 1 <= len(edges) <= k
        assert max(edges) >= max(vals)  # every job covered
        got = padded_cost_1d(vals, edges)
        uniq = sorted(set(vals))
        best = min(
            padded_cost_1d(vals, comb)
            for r in range(1, min(k, len(uniq)) + 1)
            for comb in itertools.combinations(uniq, r)
            if comb[-1] == uniq[-1])
        assert got == pytest.approx(best)


def test_ladder_1d_quantum_and_empty():
    edges = ladder_1d([100, 600, 601, 4000], 3, quantum=256)
    assert all(e % 256 == 0 for e in edges)
    assert max(edges) >= 4000
    assert ladder_1d([], 4) == []


def test_ladder_2d_covers_and_beats_envelope():
    # bimodal: many small graphs, few envelope-sized ones — the adaptive
    # grid must cover everything with <= k shapes and cost far less than
    # one worst-case envelope for all
    shapes = [(300, 200)] * 50 + [(2000, 640)] * 5
    grid = ladder_2d(shapes, 4, quantum_a=64, quantum_b=64)
    assert 1 <= len(grid) <= 4
    for a, b in shapes:
        assert any(ga >= a and gb >= b for ga, gb in grid)
    cost = sum(min(ga * gb for ga, gb in grid if ga >= a and gb >= b)
               for a, b in shapes)
    assert cost < len(shapes) * 2048 * 640 / 3


def test_ladder_2d_splits_equal_a_runs():
    # jobs sharing `a` but split in `b` may belong to different buckets:
    # the low-b majority must not inherit the tall outlier's b edge
    shapes = [(100, 10)] * 30 + [(100, 500)]
    grid = ladder_2d(shapes, 2)
    assert (100, 10) in grid


# ------------------------------------------------- occupancy accounting

def _noisy_pairs(rng, n=18, lo=150, hi=700):
    bases = np.frombuffer(ACGT, np.uint8)

    def rand(m):
        return bytes(rng.choice(bases, m))

    def mut(seq):
        out = bytearray()
        for ch in seq:
            r = rng.random()
            if r < 0.03:
                continue
            out.append(int(bases[rng.integers(4)]) if r < 0.08 else ch)
            if rng.random() < 0.03:
                out.append(int(bases[rng.integers(4)]))
        return bytes(out)

    pairs = []
    for _ in range(n):
        t = rand(int(rng.integers(lo, hi)))
        pairs.append((mut(t), t))
    return pairs


@pytest.mark.parametrize("adaptive", [False, True])
def test_aligner_occupancy_counters_sum_to_job_cells(adaptive):
    """useful + padded == lanes * bucket capacity, and useful equals the
    independently recomputed per-pair DP cells — the counters account
    for every cell the device was asked to process."""
    rng = np.random.default_rng(3)
    pairs = _noisy_pairs(rng)
    sched = BatchScheduler(adaptive=adaptive)
    al = BatchAligner(band_width=64, max_length=1024, scheduler=sched)
    al.align(list(pairs))
    snap = sched.stats.snapshot()["aligner"]
    assert snap["buckets"], "no batches recorded"
    # band_width=64 is explicit: quantized to 64 for every bucket
    band = 64
    total_useful = sum(b["useful_cells"] for b in snap["buckets"].values())
    expect_useful = sum((len(q) + len(t) + 1) * band for q, t in pairs)
    assert total_useful == expect_useful
    total_jobs = sum(b["jobs"] for b in snap["buckets"].values())
    assert total_jobs == len(pairs)
    import ast

    for bucket_s, b in snap["buckets"].items():
        edge, bucket_band = ast.literal_eval(bucket_s)
        assert bucket_band == band
        capacity = (2 * edge + 1) * band  # n_waves * band per lane
        assert (b["useful_cells"] + b["padded_cells"]
                == b["lanes"] * capacity)
        assert 0 < b["occupancy_pct"] <= 100.0
    if adaptive:
        # data-derived shapes are new to this process: compile telemetry
        # must have charged them
        assert snap.get("compiles", 0) >= 1


def test_aligner_adaptive_occupancy_not_worse_and_results_identical():
    """Adaptive ladders on a skewed length histogram: occupancy >= the
    static ladder's, per-pair results identical and in input order."""
    rng = np.random.default_rng(11)
    pairs = _noisy_pairs(rng, n=24, lo=150, hi=500)
    pairs += _noisy_pairs(rng, n=2, lo=3000, hi=3500)
    rng_order = np.random.default_rng(1)
    rng_order.shuffle(pairs)  # arrival order decorrelated from length

    occ, res = {}, {}
    for adaptive in (False, True):
        sched = BatchScheduler(adaptive=adaptive)
        al = BatchAligner(band_width=64, scheduler=sched)
        res[adaptive] = al.align(list(pairs))
        occ[adaptive] = sched.stats.snapshot()["aligner"]["occupancy_pct"]
    # order restoration: identical per-index results despite shape-sorted
    # packing rebuilding every chunk in a different order
    assert res[False] == res[True]
    assert occ[True] >= occ[False]


def test_aligner_adaptive_reuse_matches_static_and_bounds_compiles():
    """A reused adaptive aligner must start every align() from the
    static ladder again (no state leaks between batches), and each call
    derives at most len(BUCKETS) compiled (edge, band) combos."""
    rng = np.random.default_rng(5)
    batches = [_noisy_pairs(rng, n=10, lo=150, hi=400),
               _noisy_pairs(rng, n=10, lo=300, hi=900)]
    static = BatchAligner(band_width=64,
                          scheduler=BatchScheduler(adaptive=False))
    expect = [static.align(list(b)) for b in batches]
    ad = BatchAligner(band_width=64,
                      scheduler=BatchScheduler(adaptive=True))
    got = [ad.align(list(b)) for b in batches]
    assert got == expect
    snap = ad.sched.stats.snapshot()["aligner"]
    assert len(snap["buckets"]) <= 2 * len(BatchAligner.BUCKETS)


# -------------------------------------------- per-engine byte identity

def test_session_adaptive_vs_static_byte_identical():
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 12, length=80, depth=6)
    windows += _make_windows(rng, 6, length=90, depth=5,
                             spanning=False)[0]
    packed = [_pack(w) for w in windows]
    host = poa_batch(packed, 3, -5, -4, n_threads=2)
    outs = {}
    for adaptive in (False, True):
        eng = DeviceGraphPOA(3, -5, -4, num_threads=2, max_nodes=192,
                             max_len=128, buckets=((96, 96), (192, 128)),
                             batch_rows=8,
                             scheduler=BatchScheduler(adaptive=adaptive))
        dev, st = eng.consensus(packed)
        assert (st == 0).all(), st.tolist()
        outs[adaptive] = dev
        snap = eng.sched.stats.snapshot()["session"]
        for bucket_s, b in snap["buckets"].items():
            assert b["useful_cells"] + b["padded_cells"] > 0
            assert 0 < b["occupancy_pct"] <= 100.0
    for (c0, v0), (c1, v1), (ch, vh) in zip(outs[False], outs[True], host):
        assert c0 == c1 == ch  # adaptive == static == host engine
        np.testing.assert_array_equal(v0, v1)
        np.testing.assert_array_equal(v0, vh)


@pytest.fixture
def fused_setup(monkeypatch):
    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    rng = random.Random(5)
    windows, _ = _make_windows(rng, 10, length=220, depth=7, rate=0.12)
    packed = [_pack(w) for w in windows]
    host = poa_batch(packed, 3, -5, -4, n_threads=2)
    kw = dict(max_nodes=768, max_len=384, batch_rows=4,
              depth_buckets=(4, 8))
    return packed, host, kw


def test_fused_adaptive_vs_static_depth0_and_depth2(fused_setup):
    """Fused engine, scheduler on/off x pipeline depth 0/2: all four runs
    byte-identical to the host engine. The adaptive depth ladder derives
    from the actual chunk-max depths (7 here), replacing the (4, 8)
    static chain."""
    packed, host, kw = fused_setup
    outs = {}
    for adaptive in (False, True):
        for depth in (0, 2):
            eng = FusedPOA(3, -5, -4, num_threads=2,
                           scheduler=BatchScheduler(adaptive=adaptive),
                           **kw)
            if adaptive:
                # precompile-style pre-adaptation must be idempotent:
                # consensus()'s own derivation keeps the same ladder, so
                # warmed programs are the dispatched programs
                eng.adapt([list(p) for p in packed])
                assert eng.depth_buckets == (7,)
            with DispatchPipeline(depth=depth) as pl:
                res, st = eng.consensus([list(p) for p in packed],
                                        pipeline=pl)
            assert (st == 0).all(), st.tolist()
            outs[adaptive, depth] = res
            if adaptive:
                assert eng.depth_buckets == (7,)
                snap = eng.sched.stats.snapshot()["fused"]
                # layer accounting: useful layers == the windows' real
                # depth total; padded layers fill the rest of each call
                useful = sum(b["useful_cells"]
                             for b in snap["buckets"].values())
                assert useful == sum(len(p) - 1 for p in packed)
    ref = outs[False, 0]
    for key, res in outs.items():
        for (c, v), (cr, vr), (ch, vh) in zip(res, ref, host):
            assert c == cr == ch, key
            np.testing.assert_array_equal(v, vr)


# ------------------------------------------------ polisher end-to-end

def test_polisher_fasta_identical_sched_on_off_depth0_and_depth2(
        tmp_path, monkeypatch):
    """The acceptance pin: polished FASTA byte-identical with the
    scheduler on vs off, at pipeline depths 0 and 2, with the device
    aligner armed (the full pack -> dispatch -> unpack -> fallback
    path)."""
    from test_pipeline import _synth_dataset

    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    paths = _synth_dataset(tmp_path, random.Random(23))
    outs = {}
    for adaptive in (False, True):
        for depth in (0, 2):
            p = create_polisher(*(str(x) for x in paths), PolisherType.kC,
                                500, -1.0, 0.3, num_threads=2,
                                tpu_aligner_batches=1,
                                tpu_pipeline_depth=depth,
                                tpu_adaptive_buckets=adaptive)
            p.initialize()
            outs[adaptive, depth] = [(s.name, s.data) for s in p.polish()]
            occ = p.occupancy_stats
            assert "aligner" in occ and occ["aligner"]["buckets"]
            assert p.scheduler.adaptive == adaptive
    ref = outs[False, 0]
    for key, out in outs.items():
        assert out == ref, f"FASTA diverged for sched/depth {key}"


# --------------------------------------- resilience interplay (repacked
# chunks still route through the per-chunk fault hooks)

def test_repacked_chunk_fault_still_falls_back(monkeypatch, capsys):
    """With adaptive buckets + sorted packing armed, an injected device-
    stage fault on a repacked chunk must still route its pairs to the
    host fallback — every pair aligned, none lost."""
    from racon_tpu.resilience import reset_fault_plan

    monkeypatch.delenv("RACON_TPU_STRICT", raising=False)
    monkeypatch.setenv("RACON_TPU_FAULT_PLAN", "device:chunk=0:raise")
    reset_fault_plan()
    try:
        rng = np.random.default_rng(7)
        pairs = _noisy_pairs(rng, n=12)
        sched = BatchScheduler(adaptive=True)
        al = BatchAligner(band_width=64, scheduler=sched)
        fb = []
        with DispatchPipeline(depth=2) as pl:
            def on_reject(idxs, pl=pl, fb=fb):
                fb.extend(pl.map_fallback(
                    idxs, lambda sub: nw_cigar_batch(
                        [pairs[i] for i in sub], n_threads=2)))

            runs = al.align(list(pairs), pipeline=pl, on_reject=on_reject)
            pl.drain_fallback()
            stats = pl.stats.snapshot()
    finally:
        monkeypatch.delenv("RACON_TPU_FAULT_PLAN", raising=False)
        reset_fault_plan()
    assert stats["faults"] >= 1 and stats["errors"] >= 1
    cigars = {i: c for sub, fut in fb for i, c in zip(sub, fut.result())}
    for i in range(len(pairs)):  # complete coverage: device XOR fallback
        assert (runs[i] is not None) != (i in cigars)


def test_repacked_chunk_quarantine_still_works(monkeypatch):
    """Scheduler armed end-to-end: a window that fails consensus on the
    chunk pass AND its individual retry still quarantines (draft
    backbone kept, counter bumped) — the failure ladder is unaffected
    by repacking."""
    from racon_tpu.ops import poa as poa_mod

    monkeypatch.delenv("RACON_TPU_STRICT", raising=False)
    rng = random.Random(3)
    windows, _ = _make_windows(rng, 6, length=160, depth=5, rate=0.1)
    poison = windows[2].sequences[0]
    real_poa_batch = poa_mod.poa_batch

    def sabotaged(packed, *args, **kwargs):
        if any(win[0][0] == poison for win in packed):
            raise RuntimeError("poisoned window")
        return real_poa_batch(packed, *args, **kwargs)

    monkeypatch.setattr(poa_mod, "poa_batch", sabotaged)
    with DispatchPipeline(depth=2) as pl:
        eng = poa_mod.BatchPOA(3, -5, -4, 160, num_threads=2, pipeline=pl,
                               scheduler=BatchScheduler(adaptive=True))
        eng.generate_consensus(windows, trim=False)
        stats = pl.stats.snapshot()
    assert stats["quarantined"] == 1
    assert windows[2].consensus == poison and not windows[2].polished
    for w in windows[:2] + windows[3:]:
        assert w.polished and w.consensus


# --------------------------------------------------- compile cache knob

def test_enable_compile_cache_configures_jax(tmp_path, monkeypatch):
    from racon_tpu.sched import enable_compile_cache

    import os

    prev = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    cache = tmp_path / "xla-cache"
    try:
        enable_compile_cache(str(cache))
        assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(cache)
        assert jax.config.jax_compilation_cache_dir == str(cache)

        # a fresh-shaped jit compile must land an entry in the cache dir
        import jax.numpy as jnp

        @jax.jit
        def probe(x):
            return (x * 1.5 + jnp.arange(17, dtype=jnp.float32)).sum()

        probe(np.ones(17, np.float32)).block_until_ready()
        assert cache.is_dir() and any(cache.iterdir())
    finally:
        # restore: the suite's shared persistent cache must keep working
        # for the tests that follow
        if prev is not None:
            enable_compile_cache(prev)


def test_scheduler_from_env(monkeypatch):
    monkeypatch.delenv("RACON_TPU_ADAPTIVE_BUCKETS", raising=False)
    assert not BatchScheduler.from_env().adaptive
    monkeypatch.setenv("RACON_TPU_ADAPTIVE_BUCKETS", "1")
    assert BatchScheduler.from_env().adaptive
    # explicit argument (the CLI flag) wins over the environment
    assert not BatchScheduler.from_env(adaptive=False).adaptive


def test_occupancy_stats_snapshot_shape():
    st = OccupancyStats()
    st.record("eng", (64, 32), jobs=3, lanes=4, useful_cells=600,
              total_cells=1000)
    st.record("eng", (64, 32), jobs=1, lanes=4, useful_cells=100,
              total_cells=1000)
    st.record_compile("eng", 1.25)
    snap = st.snapshot()
    b = snap["eng"]["buckets"]["(64, 32)"]
    assert b == {"jobs": 4, "batches": 2, "lanes": 8, "useful_cells": 700,
                 "padded_cells": 1300, "occupancy_pct": 35.0}
    assert snap["eng"]["occupancy_pct"] == 35.0
    assert snap["eng"]["compiles"] == 1
    assert snap["eng"]["compile_s"] == 1.25
    assert st.summary() and "eng" in st.summary()


# --------------------------------------------------- lambda sample pin

DATA = "/root/reference/test/data/"
sample_data = pytest.mark.skipif(
    not __import__("os").path.isdir(DATA),
    reason="reference sample data not available")


@sample_data
def test_sample_adaptive_vs_static_all_engines(monkeypatch):
    """Lambda-fixture pin: on a real-data window slice, scheduler on vs
    off is byte-identical for the session and fused engines, and the
    device aligner's accepted/rejected results match pair-for-pair."""
    from racon_tpu.core.polisher import PolisherType, create_polisher

    monkeypatch.setenv("RACON_TPU_MAX_DEVICES", "1")
    p = create_polisher(DATA + "sample_reads.fastq.gz",
                        DATA + "sample_overlaps.paf.gz",
                        DATA + "sample_layout.fasta.gz", PolisherType.kC,
                        500, 10.0, 0.3, True, 5, -4, -8, num_threads=2)
    p.initialize()
    wins = sorted((w for w in p.windows if len(w.sequences) >= 3),
                  key=lambda w: len(w.sequences))[:24]
    packed = [_pack(w) for w in wins]
    for Engine, kw in ((FusedPOA, dict(batch_rows=8)),
                       (DeviceGraphPOA, dict())):
        outs = {}
        for adaptive in (False, True):
            eng = Engine(5, -4, -8, num_threads=2,
                         scheduler=BatchScheduler(adaptive=adaptive), **kw)
            if Engine is FusedPOA:
                res, st = eng.consensus([list(q) for q in packed],
                                        fallback=False)
            else:
                res, st = eng.consensus(packed)
            outs[adaptive] = (res, st.tolist())
        assert outs[False][1] == outs[True][1]
        for (c0, v0), (c1, v1) in zip(outs[False][0], outs[True][0]):
            if c0 is None or c1 is None:
                assert c0 is c1
                continue
            assert c0 == c1
            np.testing.assert_array_equal(v0, v1)


def test_pack_iteration_slab_contains_oldest():
    """The continuous feeder's incremental packing: shape-sorted slab,
    bounded by cap, always containing the oldest item."""
    from racon_tpu.sched import pack_iteration

    # (age, shape): oldest item has an extreme shape, so a naive
    # head-of-sorted slab would miss it
    items = [(age, shape) for age, shape in
             [(5, 10), (6, 11), (7, 12), (0, 99), (8, 13), (9, 98)]]
    batch, rest = pack_iteration(items, 2,
                                 shape_key=lambda e: e[1],
                                 age_key=lambda e: e[0])
    assert len(batch) == 2
    assert (0, 99) in batch  # the oldest always ships
    # the slab is contiguous in shape order: 99's neighbour is 98
    assert batch == [(9, 98), (0, 99)]
    assert sorted(batch + rest) == sorted(items)
    # cap larger than the pool: everything in one batch
    batch, rest = pack_iteration(items, 100,
                                 shape_key=lambda e: e[1],
                                 age_key=lambda e: e[0])
    assert len(batch) == 6 and not rest
    assert pack_iteration([], 4, shape_key=lambda e: e,
                          age_key=lambda e: e) == ([], [])
