from racon_tpu import Sequence, create_sequence


def test_uppercase_on_ingest():
    s = Sequence("r1", b"acgtn")
    assert s.data == b"ACGTN"


def test_all_zero_quality_dropped():
    s = Sequence("r1", b"ACGT", b"!!!!")
    assert s.quality == b""
    s2 = Sequence("r1", b"ACGT", b"!!#!")
    assert s2.quality == b"!!#!"


def test_reverse_complement_lazy():
    s = Sequence("r1", b"AACGTN", b"##$%&'")
    assert s._reverse_complement is None
    assert s.reverse_complement == b"NACGTT"
    assert s.reverse_quality == b"'&%$##"


def test_non_acgt_untouched_by_complement():
    s = Sequence("r1", b"ANRA")
    assert s.reverse_complement == b"TRNT"


def test_transmute_frees_fields():
    s = Sequence("r1", b"ACGT", b"##!!")
    s.transmute(has_name=False, has_data=False, has_reverse_data=True)
    assert s.name == ""
    assert s.data == b""
    assert s.quality == b""
    assert s._reverse_complement == b"ACGT"


def test_create_sequence_verbatim():
    s = create_sequence("out", "acgt")
    assert s.data == b"acgt"  # no uppercase for output records
