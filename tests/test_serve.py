"""Serve-layer tests: frame protocol, job queue, cross-job batching
identity, per-job failure isolation, graceful drain, warm polisher
reuse, and the TTY-aware progress bars.

The load-bearing contracts, in the order the ISSUE states them:

  - a submitted job's polished FASTA is byte-identical to the one-shot
    path, INCLUDING when a second concurrent job shares its device
    batches (per-window consensus is batch-composition-independent);
  - malformed frames (truncated / oversized / garbage) produce typed
    error responses and never take the server or the connection down;
  - full-queue admission rejects carry `retry_after`; deadline-expired
    jobs are cancelled and counted;
  - a fault-plan-poisoned job fails with a typed error while the server
    survives and completes a subsequent clean job;
  - drain finishes in-flight jobs (the SIGTERM path is exercised in a
    real subprocess, marked slow).
"""

from __future__ import annotations

import io
import os
import socket
import struct
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.serve import (PolishClient, PolishServer, WindowBatcher,
                             make_synth_dataset)
from racon_tpu.serve.client import JobFailed, ServeError
from racon_tpu.serve.protocol import (MAGIC, FrameGarbage, FrameTooLarge,
                                      FrameTruncated, recv_frame,
                                      send_frame)
from racon_tpu.serve.queue import Draining, Job, JobQueue, QueueFull


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    return make_synth_dataset(str(tmp_path_factory.mktemp("serve_data")))


def polish_solo(paths, **kw) -> bytes:
    p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2, **kw)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


@pytest.fixture(scope="module")
def solo_bytes(dataset):
    return polish_solo(dataset)


@pytest.fixture(scope="module")
def server(dataset, tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("serve_sock") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2).start()
    yield srv
    srv.drain(timeout=10)


@pytest.fixture(scope="module")
def client(server):
    return PolishClient(socket_path=server.config.socket_path)


# --------------------------------------------------------- frame protocol
def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_roundtrip():
    a, b = _pair()
    try:
        send_frame(a, {"type": "ping", "blob": "é" * 10})
        assert recv_frame(b) == {"type": "ping", "blob": "é" * 10}
        a.close()
        assert recv_frame(b) is None  # clean EOF between frames
    finally:
        b.close()


def test_frame_truncated_mid_payload():
    a, b = _pair()
    try:
        a.sendall(struct.pack(">4sI", MAGIC, 100) + b"only-ten..")
        a.close()
        with pytest.raises(FrameTruncated):
            recv_frame(b)
    finally:
        b.close()


def test_frame_truncated_mid_header():
    a, b = _pair()
    try:
        a.sendall(b"RT")
        a.close()
        with pytest.raises(FrameTruncated):
            recv_frame(b)
    finally:
        b.close()


def test_frame_oversized_drains_and_stream_survives():
    a, b = _pair()
    try:
        big = b"x" * 4096
        a.sendall(struct.pack(">4sI", MAGIC, len(big)) + big)
        send_frame(a, {"type": "ping"})
        with pytest.raises(FrameTooLarge):
            recv_frame(b, max_frame=1024)
        # the oversized payload was drained: the next frame parses
        assert recv_frame(b, max_frame=1024) == {"type": "ping"}
    finally:
        a.close()
        b.close()


def test_frame_garbage_payload_keeps_stream():
    a, b = _pair()
    try:
        bad = b"{this is not json"
        a.sendall(struct.pack(">4sI", MAGIC, len(bad)) + bad)
        send_frame(a, {"ok": 1})
        with pytest.raises(FrameGarbage) as exc_info:
            recv_frame(b)
        assert exc_info.value.resync
        assert recv_frame(b) == {"ok": 1}
    finally:
        a.close()
        b.close()


def test_frame_bad_magic_desyncs():
    a, b = _pair()
    try:
        a.sendall(b"GET / HTTP/1.1\r\n\r\n" + b" " * 16)
        with pytest.raises(FrameGarbage) as exc_info:
            recv_frame(b)
        assert not exc_info.value.resync
    finally:
        a.close()
        b.close()


def test_frame_non_object_payload_rejected():
    a, b = _pair()
    try:
        payload = b"[1,2,3]"
        a.sendall(struct.pack(">4sI", MAGIC, len(payload)) + payload)
        with pytest.raises(FrameGarbage):
            recv_frame(b)
    finally:
        a.close()
        b.close()


# -------------------------------------------------------------- job queue
def _job(i, priority=0, deadline_s=None):
    return Job(f"j{i}", "s", "o", "t", {}, priority=priority,
               deadline_s=deadline_s)


def test_queue_full_reject_carries_retry_after():
    q = JobQueue(maxsize=2, workers=1)
    q.submit(_job(0))
    q.submit(_job(1))
    with pytest.raises(QueueFull) as exc_info:
        q.submit(_job(2))
    assert exc_info.value.retry_after > 0
    assert q.counters["rejected_full"] == 1
    assert q.counters["admitted"] == 2


def test_queue_fifo_within_priority():
    q = JobQueue(maxsize=8)
    q.submit(_job(0, priority=0))
    q.submit(_job(1, priority=0))
    q.submit(_job(2, priority=5))
    q.submit(_job(3, priority=5))
    order = [q.pop(timeout=0.1).id for _ in range(4)]
    assert order == ["j2", "j3", "j0", "j1"]


def test_queue_deadline_expired_cancelled_and_counted():
    q = JobQueue(maxsize=8)
    expired = _job(0, deadline_s=0.01)
    q.submit(expired)
    q.submit(_job(1))
    time.sleep(0.05)
    job = q.pop(timeout=0.5)
    assert job.id == "j1"  # the expired job was consumed, not returned
    assert q.counters["expired"] == 1
    assert expired.event.is_set()
    assert expired.response["code"] == "deadline-expired"


def test_queue_drain_stops_admission():
    q = JobQueue(maxsize=8)
    q.submit(_job(0))
    q.drain()
    with pytest.raises(Draining):
        q.submit(_job(1))
    # queued work still flows out
    assert q.pop(timeout=0.1).id == "j0"
    assert q.counters["rejected_draining"] == 1


# ----------------------------------------------- continuous batching
def _pool_jobs(srv, cl, dataset, n, admitted_before=0, **submit_kw):
    """Submit `n` jobs with the feeder HELD so all their windows pool,
    then release — every job's windows share the next iteration(s).
    Returns the joined results."""
    srv.batcher.hold()
    try:
        results = [None] * n

        def go(i):
            results[i] = cl.submit(*dataset, **submit_kw)

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while (srv.queue.counters["admitted"] < admitted_before + n
               and time.monotonic() < deadline):
            time.sleep(0.005)
        # admitted != pooled: give the workers a beat to run initialize
        # and enqueue their windows behind the held feeder
        time.sleep(0.5)
    finally:
        srv.batcher.release()
    for t in threads:
        t.join(timeout=60)
    return results


def test_cross_job_iteration_byte_identical(dataset, solo_bytes,
                                            tmp_path_factory):
    """Two concurrent jobs' windows merged into SHARED device
    iterations produce exactly the solo-run bytes each (the feeder is
    held until both jobs pooled, making the merge deterministic)."""
    sock = str(tmp_path_factory.mktemp("merge") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2,
                       warmup=False).start()
    try:
        cl = PolishClient(socket_path=sock)
        results = _pool_jobs(srv, cl, dataset, 2)
        for r in results:
            assert r is not None
            assert r.fasta == solo_bytes
            assert r.serve["batch"]["shared_iterations"] >= 1
            assert not r.serve["batch"]["solo"]
        assert srv.batcher.counters["shared_iterations"] >= 1
        assert srv.batcher.counters["max_jobs_in_iteration"] == 2
    finally:
        srv.drain(timeout=10)


def test_late_job_joins_next_iteration_not_a_round(dataset, solo_bytes,
                                                   tmp_path_factory):
    """The round barrier is gone: with a small iteration bound, one
    job's windows spread over SEVERAL iterations — the continuous
    feeder dispatches bounded batches instead of one all-or-nothing
    round, which is exactly what lets a late job join mid-flight."""
    sock = str(tmp_path_factory.mktemp("iter") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2, warmup=False,
                       iteration_windows=2).start()
    try:
        cl = PolishClient(socket_path=sock)
        r = cl.submit(*dataset)
        assert r.fasta == solo_bytes
        assert r.serve["batch"]["iterations"] >= 2
        assert len(r.serve["batch"]["iteration_ids"]) == \
            r.serve["batch"]["iterations"]
    finally:
        srv.drain(timeout=10)


def test_cross_job_identity_worker_lanes2(dataset, solo_bytes,
                                          tmp_path_factory):
    """THE worker-lanes acceptance pin (serve half): a --worker-lanes 2
    server — device list partitioned into two sub-mesh lanes, each with
    its own feeder — still produces exactly the solo-run bytes for
    concurrent jobs, streamed parts included."""
    sock = str(tmp_path_factory.mktemp("lanes") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2, worker_lanes=2,
                       warmup=False).start()
    try:
        assert srv.batcher.worker_lanes == 2
        cl = PolishClient(socket_path=sock)
        results = _pool_jobs(srv, cl, dataset, 2)
        for r in results:
            assert r is not None
            assert r.fasta == solo_bytes
        # streamed submit on the lanes server: parts concat identical
        parts: list = []
        assert cl.submit(*dataset,
                         on_part=parts.append).fasta == solo_bytes
        assert b"".join(p["fasta"].encode("latin-1")
                        for p in parts) == solo_bytes
        snap = srv.batcher.snapshot()
        assert snap["worker_lanes"] == 2
        assert len(snap["lanes"]) == 2
        assert {ln["n_devices"] for ln in snap["lanes"]} == {4}
        assert sum(ln["iterations"] for ln in snap["lanes"]) == \
            snap["iterations"]
    finally:
        srv.drain(timeout=10)


def test_worker_lanes_isolation_job_fails_alone(dataset, solo_bytes,
                                                tmp_path_factory):
    """Lane-level fault isolation: a strict fault-plan job runs SOLO on
    one lane and fails typed, while a concurrent clean job (on the
    other lane) returns byte-identical output and the server survives."""
    sock = str(tmp_path_factory.mktemp("lanefault") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2, worker_lanes=2,
                       warmup=False).start()
    try:
        cl = PolishClient(socket_path=sock)
        clean: dict = {}

        def clean_job():
            clean["r"] = cl.submit(*dataset, retries=3)

        t = threading.Thread(target=clean_job)
        t.start()
        with pytest.raises(JobFailed) as exc_info:
            # consensus-phase poison (host loop pack stage — the shape
            # the existing poisoned-job gate uses); strict, so the
            # isolation path runs it SOLO on one lane
            cl.submit(*dataset, strict=True,
                      fault_plan="pack:chunk=0:raise")
        assert exc_info.value.error_type == "DeviceError"
        t.join(60)
        assert clean["r"].fasta == solo_bytes
        # and the server still serves after the poisoned job
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)


def test_tenant_quota_rejects_typed_with_retry_after():
    """Hard per-tenant admission quota (unit level): the tenant at its
    queued cap gets a typed reject with retry_after while OTHER tenants
    still admit; popped jobs free quota slots."""
    from racon_tpu.serve.queue import TenantQuotaExceeded

    q = JobQueue(maxsize=8, tenant_quota=2)
    q.submit(Job("a1", "s", "o", "t", {}, tenant="heavy"))
    q.submit(Job("a2", "s", "o", "t", {}, tenant="heavy"))
    with pytest.raises(TenantQuotaExceeded) as exc_info:
        q.submit(Job("a3", "s", "o", "t", {}, tenant="heavy"))
    assert exc_info.value.retry_after > 0
    assert "heavy" in str(exc_info.value)
    assert q.counters["rejected_quota"] == 1
    # another tenant is unaffected by heavy's cap
    q.submit(Job("b1", "s", "o", "t", {}, tenant="light"))
    # popping one of heavy's jobs frees a slot
    assert q.pop(timeout=0.5) is not None
    q.submit(Job("a4", "s", "o", "t", {}, tenant="heavy"))
    assert q.counters["admitted"] == 4


def test_tenant_quota_end_to_end(dataset, tmp_path_factory):
    """The quota over the wire: with RACON_TPU_SERVE_TENANT_QUOTA=1 a
    tenant's second QUEUED job answers `tenant-quota` with retry_after
    while a different tenant still admits."""
    from racon_tpu.serve import TenantQuota

    sock = str(tmp_path_factory.mktemp("quota") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=1, tenant_quota=1,
                       warmup=False).start()
    try:
        cl = PolishClient(socket_path=sock)
        srv.batcher.hold()  # keep the first job in flight
        try:
            outcomes: dict = {}

            def submit(key, tenant):
                try:
                    outcomes[key] = cl.submit(*dataset, tenant=tenant)
                except Exception as exc:  # noqa: BLE001 — asserted below
                    outcomes[key] = exc

            def wait_until(cond, what):
                deadline = time.monotonic() + 30
                while not cond():
                    assert time.monotonic() < deadline, what
                    time.sleep(0.01)

            t = threading.Thread(target=submit, args=("j1", "gold"))
            t.start()
            # job 1 must have been POPPED by the (single) worker — the
            # quota counts QUEUED jobs only, so its slot must be free
            wait_until(lambda: srv.queue.counters["admitted"] == 1
                       and len(srv.queue) == 0,
                       "job 1 never reached the worker")
            # job 2 queues (worker busy behind the held feeder)
            t2 = threading.Thread(target=submit, args=("j2", "gold"))
            t2.start()
            wait_until(lambda: len(srv.queue) == 1,
                       "job 2 never queued")
            # job 3 hits gold's quota of 1 queued job
            with pytest.raises(TenantQuota) as exc_info:
                cl.submit(*dataset, tenant="gold")
            assert exc_info.value.code == "tenant-quota"
            assert exc_info.value.retry_after > 0
            # a different tenant still admits past gold's cap
            t3 = threading.Thread(target=submit, args=("j3", "free"))
            t3.start()
            wait_until(lambda: len(srv.queue) == 2,
                       "free-tenant job never queued")
            assert srv.queue.counters["rejected_quota"] == 1
        finally:
            srv.batcher.release()
        for thread in (t, t2, t3):
            thread.join(60)
        for key in ("j1", "j2", "j3"):
            assert not isinstance(outcomes.get(key), Exception), \
                (key, outcomes.get(key))
    finally:
        srv.drain(timeout=10)


def test_batcher_mixed_params_do_not_merge(dataset):
    """Jobs whose engine parameters differ must not share an iteration
    — and both must still match their own solo bytes."""
    batcher = WindowBatcher()

    def build(match):
        p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                            match=match, num_threads=2)
        p.initialize()
        return p

    pa, pb = build(3), build(5)
    batcher.hold()
    ta = threading.Thread(target=batcher.consensus, args=(pa,))
    tb = threading.Thread(target=batcher.consensus, args=(pb,))
    ta.start()
    tb.start()
    time.sleep(0.3)  # both jobs' windows pooled under different keys
    batcher.release()
    ta.join(60)
    tb.join(60)
    assert pa.serve_batch["shared_iterations"] == 0
    assert pb.serve_batch["shared_iterations"] == 0
    assert batcher.counters["iterations"] == 2
    assert batcher.counters["max_jobs_in_iteration"] == 1
    out_a = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                     for s in pa._stitch(True))
    out_b = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                     for s in pb._stitch(True))
    assert out_a == polish_solo(dataset)
    assert out_b == polish_solo(dataset, match=5)
    assert out_a != out_b  # the scores genuinely differ on this input
    batcher.close()


def test_batcher_persistent_engine_cache_and_host_overhead(dataset):
    """The persistent dispatch loop: two same-key jobs reuse ONE cached
    (pipeline, engine) pair on the lane (engine construction leaves the
    per-iteration hot path), the measured per-iteration host overhead
    accumulates in the counters, and output stays byte-identical to a
    solo run."""
    batcher = WindowBatcher()

    def run_job():
        p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                            num_threads=2)
        p.initialize()
        batcher.consensus(p)
        return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                        for s in p._stitch(True))

    out1 = run_job()
    out2 = run_job()
    assert out1 == out2 == polish_solo(dataset)
    lanes = batcher._lanes
    assert lanes is not None
    # one engine key -> ONE cached pair across both iterations
    assert sum(len(lane.engines) for lane in lanes) == 1
    snap = batcher.snapshot()
    assert snap["iterations"] == 2
    assert snap["host_s"] >= 0.0
    # the merged pipeline view carries the iterations' stage seconds
    assert snap["pipeline"]["chunks"] >= 1
    batcher.close()
    # close() shut the cached pipelines' fallback executors down
    for lane in lanes:
        for pipeline, _ in lane.engines.values():
            assert pipeline._executor is None


def test_deprecated_round_knobs_warn_and_alias():
    """gather_window_s aliases to max_wait_s, min_gather is refused
    loudly — neither is a silent ignore."""
    from racon_tpu.serve import ServeConfig

    with pytest.warns(DeprecationWarning, match="gather_window_s"):
        cfg = ServeConfig(gather_window_s=0.25)
    assert cfg.max_wait_s == 0.25
    with pytest.warns(DeprecationWarning, match="min_gather"):
        ServeConfig(min_gather=4)


# ------------------------------------------------------------ end to end
def test_submit_byte_identical_to_oneshot(client, dataset, solo_bytes):
    result = client.submit(*dataset)
    assert result.fasta == solo_bytes
    assert result.serve["queue_wait_s"] >= 0
    assert "pipeline" in result.metrics


def test_submit_missing_file_typed_error(client, dataset):
    with pytest.raises(ServeError) as exc_info:
        client.submit(dataset[0], dataset[1], "/nonexistent/draft.fa.gz")
    assert exc_info.value.code == "bad-request"


def test_submit_unknown_option_typed_error(client, dataset):
    with pytest.raises(ServeError) as exc_info:
        client.submit(*dataset, options={"wndow_length": 500})
    assert exc_info.value.code == "bad-request"
    assert "wndow_length" in str(exc_info.value)


def test_poisoned_job_fails_typed_server_survives(client, dataset,
                                                  solo_bytes, server):
    """The acceptance gate: an injected DeviceError fails exactly one
    job with a typed error; the warm server then completes a clean job
    byte-identically. Both phases are poisoned in turn."""
    # alignment-phase poison (device aligner armed for this job only)
    with pytest.raises(JobFailed) as exc_info:
        client.submit(*dataset, fault_plan="device:chunk=0:raise",
                      strict=True, options={"tpu_aligner_batches": 1})
    assert exc_info.value.error_type == "DeviceError"
    # consensus-phase poison (host loop pack stage; isolation iteration)
    solo_before = server.batcher.counters["solo_iterations"]
    with pytest.raises(JobFailed) as exc_info:
        client.submit(*dataset, fault_plan="pack:chunk=0:raise",
                      strict=True)
    assert exc_info.value.error_type == "DeviceError"
    # the server survives and the next clean job is byte-identical
    assert client.submit(*dataset).fasta == solo_bytes
    assert client.ping()["type"] == "pong"
    assert server.batcher.counters["solo_iterations"] >= solo_before


def test_unpoisoned_fault_plan_degrades_within_job(client, dataset,
                                                   solo_bytes):
    """Without strict, the job's own resilience ladder absorbs its
    injected fault — output still byte-identical, fault counted in the
    job's OWN metrics, nothing leaks to the next job."""
    r = client.submit(*dataset, fault_plan="device:chunk=0:raise")
    assert r.fasta == solo_bytes
    assert r.metrics["resilience"]["faults"] == 1
    clean = client.submit(*dataset)
    assert clean.metrics["resilience"]["faults"] == 0


def test_job_trace_scoped_to_response(client, dataset):
    r = client.submit(*dataset, trace=True)
    assert isinstance(r.trace, list) and r.trace
    names = {ev["name"] for ev in r.trace}
    assert "polisher.initialize" in names
    # an untraced job's response carries no trace
    assert client.submit(*dataset).trace is None


def test_concurrent_traced_jobs_restore_tracer(client, dataset):
    """Overlapping trace=True jobs must not leak a dead per-job
    recorder into the process tracer (scoped() serializes): both get
    their own events, and the global tracer ends where it started."""
    from racon_tpu.obs import trace as obs_trace

    before = obs_trace.get_tracer()
    results = [None, None]

    def go(i):
        results[i] = client.submit(*dataset, trace=True)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for r in results:
        assert r is not None and r.trace
    assert obs_trace.get_tracer() is before


def test_tcp_ephemeral_port(dataset, solo_bytes):
    """--port 0 means ephemeral localhost TCP (not the unix socket);
    the bound port is published and serves byte-identical results."""
    srv = PolishServer(port=0, warmup=False).start()
    try:
        assert srv.config.port > 0
        cl = PolishClient(port=srv.config.port)
        assert cl.ping()["type"] == "pong"
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)


def test_server_connection_survives_bad_frames(server):
    """Garbage and oversized frames on a live connection get typed error
    responses and the SAME connection keeps working."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10.0)
    sock.connect(server.config.socket_path)
    try:
        # garbage JSON payload
        bad = b"!garbage!"
        sock.sendall(struct.pack(">4sI", MAGIC, len(bad)) + bad)
        resp = recv_frame(sock)
        assert resp["type"] == "error" and resp["code"] == "bad-frame"
        # same connection still serves
        send_frame(sock, {"type": "ping"})
        assert recv_frame(sock)["type"] == "pong"
        # unknown request type: typed, connection still alive
        send_frame(sock, {"type": "frobnicate"})
        resp = recv_frame(sock)
        assert resp["type"] == "error" and resp["code"] == "bad-request"
        send_frame(sock, {"type": "stats"})
        assert recv_frame(sock)["type"] == "stats"
    finally:
        sock.close()


def test_server_survives_truncated_frame_and_desync(server):
    """A client that dies mid-frame (and one that talks HTTP at us)
    costs only its own connection."""
    for payload in (struct.pack(">4sI", MAGIC, 1000) + b"partial",
                    b"GET / HTTP/1.1\r\n\r\n"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(10.0)
        sock.connect(server.config.socket_path)
        sock.sendall(payload)
        sock.close()
    # fresh connection: the server is untouched
    cl = PolishClient(socket_path=server.config.socket_path)
    assert cl.ping()["type"] == "pong"


def test_oversized_frame_typed_error(dataset, tmp_path_factory):
    sock_path = str(tmp_path_factory.mktemp("oversz") / "s.sock")
    srv = PolishServer(socket_path=sock_path, warmup=False,
                       max_frame=512).start()
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(10.0)
        sock.connect(sock_path)
        big = b"y" * 2048
        sock.sendall(struct.pack(">4sI", MAGIC, len(big)) + big)
        resp = recv_frame(sock)
        assert resp["type"] == "error"
        assert resp["code"] == "frame-too-large"
        send_frame(sock, {"type": "ping"})
        assert recv_frame(sock)["type"] == "pong"
        sock.close()
    finally:
        srv.drain(timeout=5)


# ------------------------------------------------------------------ drain
def test_drain_finishes_inflight_then_rejects(dataset, solo_bytes,
                                              tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("drain") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=1,
                       warmup=False).start()
    cl = PolishClient(socket_path=sock)
    result: list = [None]

    def go():
        result[0] = cl.submit(*dataset)

    t = threading.Thread(target=go)
    t.start()
    # wait until the job is actually admitted, then drain
    deadline = time.monotonic() + 10
    while (srv.queue.counters["admitted"] < 1
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.drain(timeout=30)
    t.join(timeout=30)
    assert result[0] is not None and result[0].fasta == solo_bytes
    assert srv.queue.counters["completed"] == 1
    # post-drain: admission is closed (transport is gone)
    with pytest.raises((ServeError, OSError)):
        cl.submit(*dataset)


@pytest.mark.slow
def test_sigterm_drain_subprocess(dataset, solo_bytes, tmp_path):
    """Full SIGTERM path in a real `racon_tpu serve` process: an
    in-flight job finishes, the process exits 0."""
    import signal
    import subprocess

    sock = str(tmp_path / "s.sock")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in [os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__)))]
                   + os.environ.get("PYTHONPATH", "").split(os.pathsep)
                   if p and "axon_site" not in p))
    proc = subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve", "--socket",
         sock, "--workers", "1", "--no-warmup"],
        env=env, stderr=subprocess.PIPE)
    try:
        cl = PolishClient(socket_path=sock, timeout=30)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                cl.ping()
                break
            except (OSError, ServeError):
                time.sleep(0.2)
        else:
            pytest.fail("server never came up")
        result: list = [None]

        def go():
            result[0] = cl.submit(*dataset)

        t = threading.Thread(target=go)
        t.start()
        time.sleep(0.2)  # let the submit land
        proc.send_signal(signal.SIGTERM)
        t.join(timeout=60)
        rc = proc.wait(timeout=60)
        assert rc == 0
        assert result[0] is not None
        assert result[0].fasta == solo_bytes
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


# ------------------------------------------------- warm polisher reuse
def test_polisher_back_to_back_runs_byte_identical(dataset):
    fresh = polish_solo(dataset)
    p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    outs, stats = [], []
    for _ in range(2):
        p.initialize()
        outs.append(b"".join(b">" + s.name.encode() + b"\n" + s.data
                             + b"\n" for s in p.polish()))
        stats.append(p.stage_stats)
    assert outs[0] == fresh
    assert outs[1] == fresh
    # counters describe one run each, not a running total
    assert stats[0]["chunks"] == stats[1]["chunks"]
    assert stats[0]["launches"] == stats[1]["launches"]


def test_polisher_rebind_warm_reuse(dataset, tmp_path):
    """rebind() points a warm polisher at new inputs; output matches a
    fresh polisher on those inputs."""
    other = make_synth_dataset(str(tmp_path), seed=99)
    p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    p.polish()
    p.rebind(*other)
    p.initialize()
    warm = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())
    assert warm == polish_solo(other)
    # per-run metrics followed the swap (fresh occupancy object)
    assert p.metrics.snapshot()["sched"] == p.scheduler.stats.snapshot()


def test_polisher_run_counters_reset_between_jobs(dataset):
    """A fault absorbed in run 1 must not appear in run 2's report."""
    from racon_tpu.resilience.faults import reset_fault_plan

    os.environ["RACON_TPU_FAULT_PLAN"] = "device:chunk=0:raise"
    reset_fault_plan()
    try:
        p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                            num_threads=2)
        p.initialize()
        p.polish()
        assert p.stage_stats["faults"] == 1
    finally:
        os.environ.pop("RACON_TPU_FAULT_PLAN", None)
        reset_fault_plan()
    p.initialize()
    p.polish()
    assert p.stage_stats["faults"] == 0


# ------------------------------------- end-to-end tracing & live progress
def _serve_pair(tmp_path_factory, transport, **kw):
    """A (server, client) pair on the requested transport."""
    kw.setdefault("warmup", False)
    if transport == "tcp":
        srv = PolishServer(port=0, **kw).start()
        return srv, PolishClient(port=srv.config.port)
    sock = str(tmp_path_factory.mktemp("ept") / "s.sock")
    srv = PolishServer(socket_path=sock, **kw).start()
    return srv, PolishClient(socket_path=sock)


@pytest.mark.parametrize("transport", ["unix", "tcp"])
def test_progress_frames_interleaved(dataset, solo_bytes,
                                     tmp_path_factory, transport):
    """The acceptance gate, on both transports: progress frames arrive
    before the result, seq and windows-done counts are monotonically
    non-decreasing, the stream ends at stitch, and the result bytes are
    untouched by the streaming."""
    srv, cl = _serve_pair(tmp_path_factory, transport)
    try:
        evs: list = []
        r = cl.submit(*dataset, on_progress=evs.append,
                      trace_id="tid-interleave")
        assert r.fasta == solo_bytes
        assert evs, "no progress frames before the result frame"
        assert all(e["type"] == "progress" for e in evs)
        assert all(e["job_id"] == r.job_id for e in evs)
        assert all(e["trace_id"] == "tid-interleave" for e in evs)
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        cons = [e for e in evs if e["phase"] == "consensus"]
        assert cons, "no consensus progress"
        dones = [e["done"] for e in cons]
        assert dones == sorted(dones), "windows-done ran backwards"
        assert cons[-1]["done"] == cons[-1]["total"] > 0
        assert "start" in {e["phase"] for e in evs}
        assert evs[-1]["phase"] == "stitch"
        # a plain submit on the same server gets NO progress frames
        # (off by default) and identical bytes
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)


def test_progress_queue_position_while_pending(dataset,
                                               tmp_path_factory):
    """A job stuck behind a busy single worker streams queued-position
    frames before it ever starts."""
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1)
    try:
        blocker_done = threading.Event()

        def blocker():
            try:
                cl.submit(*dataset,
                          fault_plan="device:chunk=0:hang=0.8")
            finally:
                blocker_done.set()

        t = threading.Thread(target=blocker)
        t.start()
        deadline = time.monotonic() + 10
        while (srv.queue.counters["admitted"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.005)
        time.sleep(0.1)  # let the worker pop it
        evs: list = []
        cl.submit(*dataset, on_progress=evs.append)
        queued = [e for e in evs if e["phase"] == "queued"]
        assert queued, f"no queued-position frames: {evs[:5]}"
        assert queued[0]["position"] >= 0
        assert queued[0]["depth"] >= 1
        # the queued frames precede every execution-phase frame
        assert evs.index(queued[-1]) < evs.index(
            next(e for e in evs if e["phase"] == "start"))
        t.join(timeout=30)
        assert blocker_done.is_set()
    finally:
        srv.drain(timeout=10)


def test_concurrent_jobs_no_progress_bleed(dataset, solo_bytes,
                                           tmp_path_factory):
    """Two concurrent progress-streaming jobs merged into SHARED device
    iterations: each stream carries only its own job id and trace id,
    both outputs stay byte-identical."""
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=2)
    srv.batcher.hold()
    try:
        evs: list = [[], []]
        results: list = [None, None]

        def go(i):
            results[i] = cl.submit(*dataset, on_progress=evs[i].append,
                                   trace_id=f"tid-{i}")

        threads = [threading.Thread(target=go, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while (srv.queue.counters["admitted"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        time.sleep(0.5)  # both jobs' windows pooled behind the hold
        srv.batcher.release()
        for t in threads:
            t.join(timeout=60)
        assert results[0] is not None and results[1] is not None
        assert results[0].job_id != results[1].job_id
        # truly shared iterations
        assert results[0].serve["batch"]["shared_iterations"] >= 1
        for i in (0, 1):
            assert results[i].fasta == solo_bytes
            assert evs[i], f"job {i} saw no progress"
            assert {e["job_id"] for e in evs[i]} == \
                {results[i].job_id}, "cross-job job_id bleed"
            assert {e["trace_id"] for e in evs[i]} == {f"tid-{i}"}, \
                "cross-job trace_id bleed"
            cons = [e for e in evs[i] if e["phase"] == "consensus"]
            dones = [e["done"] for e in cons]
            assert dones == sorted(dones)
            assert cons[-1]["done"] == cons[-1]["total"] > 0
    finally:
        srv.drain(timeout=10)


def test_bad_trace_id_rejected(client, dataset):
    with pytest.raises(ServeError) as exc_info:
        client.submit(*dataset, trace_id="no spaces allowed")
    assert exc_info.value.code == "bad-request"
    assert "trace_id" in str(exc_info.value)


def test_trace_out_merged_artifact(client, server, dataset, tmp_path):
    """The acceptance gate: one traced submit against the WARM module
    server produces a single valid Chrome-trace JSON holding both
    client- and server-side spans on one timeline, with the serve-side
    spans tagged by the minted trace id and the batch-round span
    duration pinned to the job's own round telemetry."""
    import json as _json

    path = str(tmp_path / "merged.json")
    result, doc = client.submit_traced(*dataset, trace_out=path)
    on_disk = _json.load(open(path))
    assert on_disk["traceEvents"] and "displayTimeUnit" in on_disk
    tid = doc["trace_context"]["trace_id"]
    assert tid and doc["trace_context"]["job_id"] == result.job_id

    by_pid: dict = {}
    for ev in doc["traceEvents"]:
        assert "ph" in ev and "pid" in ev
        if ev["ph"] != "M":
            assert ev["ts"] >= 0
            by_pid.setdefault(ev["pid"], set()).add(ev["name"])
    assert {"client.connect", "client.submit", "client.wait",
            "client.receive"} <= by_pid[1]
    assert {"serve.queue_wait", "serve.job",
            "polisher.initialize"} <= by_pid[2]
    # process-name metadata labels both tracks
    pnames = {ev["pid"]: ev["args"]["name"]
              for ev in doc["traceEvents"]
              if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert "client" in pnames[1] and "server" in pnames[2]
    # the serve-side spans carry the client's trace context
    qw = [ev for ev in doc["traceEvents"]
          if ev.get("name") == "serve.queue_wait"]
    assert len(qw) == 1 and qw[0]["args"]["trace_id"] == tid
    # span-duration pin: the job's iteration spans and its batch
    # telemetry are recorded from the same perf_counter endpoints —
    # the spans for the iterations this job rode sum to its device_s
    batch = result.serve["batch"]
    iters = [ev for ev in doc["traceEvents"]
             if ev.get("name") == "serve.iteration"
             and ev.get("args", {}).get("iteration")
             in batch["iteration_ids"]]
    assert len(iters) == batch["iterations"] >= 1
    assert sum(ev["dur"] for ev in iters) / 1e6 == pytest.approx(
        batch["device_s"], rel=0.05, abs=1e-3)
    assert all(tid in ev["args"]["trace_ids"] for ev in iters)
    # and the ordinary result is untouched
    assert result.fasta


def test_traced_strict_job_span_sums_pin_stage_stats(client, server,
                                                     dataset):
    """Server pipeline span sums inside the merged artifact equal the
    job's own stage stats (a strict job runs an isolation round on its
    own pipeline, so the returned metrics ARE this job's spans)."""
    result, doc = client.submit_traced(*dataset, strict=True)
    stats = result.metrics["pipeline"]
    sums: dict = {}
    for ev in doc["traceEvents"]:
        if (ev.get("ph") == "X" and ev.get("pid") == 2
                and ev["name"].startswith("pipeline.")):
            stage = ev["name"].split(".", 1)[1]
            sums[stage] = sums.get(stage, 0.0) + ev["dur"] / 1e6
    assert sums, "no pipeline spans in the server trace"
    for stage in ("pack", "device", "unpack"):
        assert sums.get(stage, 0.0) == pytest.approx(
            stats[f"{stage}_s"], rel=0.05, abs=1e-3), \
            f"{stage}: {sums.get(stage)} vs {stats[f'{stage}_s']}"


def test_trace_and_progress_over_tcp(dataset, solo_bytes,
                                     tmp_path_factory):
    """Trace-context propagation composes with progress streaming over
    localhost TCP: progress frames become client.progress instants in
    the merged artifact."""
    srv, cl = _serve_pair(tmp_path_factory, "tcp")
    try:
        evs: list = []
        result, doc = cl.submit_traced(*dataset,
                                       on_progress=evs.append)
        assert result.fasta == solo_bytes
        assert evs
        instants = [ev for ev in doc["traceEvents"]
                    if ev.get("name") == "client.progress"]
        assert len(instants) == len(evs)
        assert all(ev["pid"] == 1 for ev in instants)
    finally:
        srv.drain(timeout=10)


# --------------------------------------------- per-tenant fair scheduling
def _tjob(i, tenant, priority=0):
    return Job(f"{tenant}{i}", "s", "o", "t", {}, priority=priority,
               tenant=tenant)


def test_queue_drr_equal_weights_interleave():
    """A flooding tenant and a late light tenant with equal weights pop
    round-robin: the light tenant's first job is at most a couple of
    pops away, not behind the whole flood."""
    q = JobQueue(maxsize=32)
    for i in range(6):
        q.submit(_tjob(i, "heavy"))
    for i in range(2):
        q.submit(_tjob(i, "light"))
    assert q.position(q._classes[0].tenants["light"][0]) <= 3
    order = [q.pop(timeout=0.1).id for _ in range(8)]
    assert order.index("light0") <= 3
    assert order.index("light1") <= 5
    # FIFO within each tenant
    heavy_order = [j for j in order if j.startswith("heavy")]
    assert heavy_order == sorted(heavy_order)


def test_queue_drr_weighted_ratio():
    """A weight-3 tenant gets ~3 pops per rotation against a weight-1
    flood."""
    q = JobQueue(maxsize=32,
                 tenant_weights={"heavy": 1, "gold": 3})
    for i in range(6):
        q.submit(_tjob(i, "heavy"))
    for i in range(3):
        q.submit(_tjob(i, "gold"))
    order = [q.pop(timeout=0.1).id for _ in range(9)]
    # all three gold jobs pop within the first four slots
    assert {j for j in order[:4] if j.startswith("gold")} == \
        {"gold0", "gold1", "gold2"}


def test_queue_drr_priority_beats_weight():
    """Priority classes stay absolute: a higher-priority job pops
    before any lower-priority tenant regardless of weights."""
    q = JobQueue(maxsize=32, tenant_weights={"vip": 100})
    q.submit(_tjob(0, "vip", priority=0))
    q.submit(_tjob(0, "urgent", priority=5))
    assert q.pop(timeout=0.1).id == "urgent0"
    assert q.pop(timeout=0.1).id == "vip0"


def test_queue_single_tenant_stays_fifo():
    q = JobQueue(maxsize=8)
    for i in range(4):
        q.submit(_tjob(i, ""))
    assert [q.pop(timeout=0.1).id for _ in range(4)] == \
        ["0", "1", "2", "3"]


def test_tenant_fairness_light_tenant_bounded(dataset,
                                              tmp_path_factory):
    """The saturation-wave gate: one worker, a heavy tenant floods the
    queue, a light (weighted) tenant submits after — the light job must
    complete ahead of most of the heavy backlog, i.e. its latency is
    bounded by ~one job, not by the flood."""
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1,
                          queue_depth=16,
                          tenant_weights={"light": 4, "heavy": 1})
    try:
        done_order: list = []
        threads = []

        def go(tenant, i, **kw):
            cl.submit(*dataset, tenant=tenant, **kw)
            done_order.append(tenant)

        # first heavy job hangs briefly so the rest of the flood is
        # queued when the light tenant arrives
        t = threading.Thread(target=go, args=("heavy", 0),
                             kwargs={"fault_plan":
                                     "device:chunk=0:hang=0.8"})
        t.start()
        threads.append(t)
        deadline = time.monotonic() + 10
        while (srv.queue.counters["admitted"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.005)
        time.sleep(0.1)  # worker popped the hanging job
        for i in range(1, 5):
            th = threading.Thread(target=go, args=("heavy", i))
            th.start()
            threads.append(th)
        deadline = time.monotonic() + 10
        while (srv.queue.counters["admitted"] < 5
               and time.monotonic() < deadline):
            time.sleep(0.005)
        th = threading.Thread(target=go, args=("light", 0))
        th.start()
        threads.append(th)
        for th in threads:
            th.join(timeout=60)
        assert len(done_order) == 6
        # the light job finished ahead of most of the heavy backlog:
        # at most the in-flight job plus one racing pop precede it
        assert done_order.index("light") <= 2, done_order
        snap = srv.queue.snapshot()
        assert snap["tenants"]["light"]["completed"] == 1
        assert snap["tenants"]["light"]["weight"] == 4.0
    finally:
        srv.drain(timeout=10)


# --------------------------------------------------- streamed result parts
def test_stream_parts_byte_identical(dataset, solo_bytes, client):
    """`result_part` frames arrive before the result, in contig order,
    and their concatenation is byte-identical to the buffered FASTA —
    while the final frame carries stats but no second copy."""
    parts: list = []
    r = client.submit(*dataset, on_part=parts.append)
    assert r.streamed and r.parts == len(parts) > 0
    assert all(p["type"] == "result_part" for p in parts)
    assert [p["part"] for p in parts] == \
        list(range(1, len(parts) + 1))
    concat = b"".join(p["fasta"].encode("latin-1") for p in parts)
    assert concat == solo_bytes
    assert r.fasta == solo_bytes  # assembled from the parts
    # a buffered submit on the same server still carries the body
    assert client.submit(*dataset).fasta == solo_bytes


def test_stream_with_progress_interleaved(dataset, solo_bytes,
                                          tmp_path_factory):
    """Streaming composes with live progress on one connection: the
    client sees progress frames, then each part, then the result — and
    time-to-first-byte (first part) precedes job completion."""
    srv, cl = _serve_pair(tmp_path_factory, "tcp")
    try:
        events: list = []
        r = cl.submit(*dataset,
                      on_progress=lambda ev: events.append(("p", ev)),
                      on_part=lambda fr: events.append(("part", fr)))
        assert r.fasta == solo_bytes
        kinds = [k for k, _ in events]
        assert "p" in kinds and "part" in kinds
        # every part precedes the end of the stream and parts are in
        # order
        part_ids = [fr["part"] for k, fr in events if k == "part"]
        assert part_ids == sorted(part_ids)
    finally:
        srv.drain(timeout=10)


def test_stream_identity_under_quarantine(dataset, solo_bytes,
                                          tmp_path_factory,
                                          monkeypatch):
    """Injected per-window faults (one window quarantined onto its
    draft backbone) must not break streaming: parts still arrive in
    order and their concatenation equals the buffered submit under the
    SAME injection — which genuinely differs from the clean bytes."""
    import racon_tpu.ops.poa as poa_mod

    real = poa_mod.poa_batch
    state = {"singles": 0}

    def flaky(packed, *a, **kw):
        if len(packed) > 1:
            raise RuntimeError("chunk poisoned")  # force singles
        state["singles"] += 1
        if state["singles"] == 2:
            raise RuntimeError("window poisoned")  # quarantine one
        return real(packed, *a, **kw)

    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=1)
    try:
        monkeypatch.setattr(poa_mod, "poa_batch", flaky)
        state["singles"] = 0
        buffered = cl.submit(*dataset).fasta
        state["singles"] = 0
        parts: list = []
        streamed = cl.submit(*dataset, on_part=parts.append)
        assert [p["part"] for p in parts] == \
            list(range(1, len(parts) + 1))
        assert streamed.fasta == buffered
        assert buffered != solo_bytes  # the quarantine really landed
        b = srv.batcher.snapshot()
        assert b["pipeline"]["quarantined"] >= 2
    finally:
        srv.drain(timeout=10)


@pytest.mark.parametrize("worker_lanes", [1, 2])
def test_midstream_disconnect_kills_nothing(dataset, solo_bytes,
                                            tmp_path_factory,
                                            worker_lanes):
    """A streaming client that vanishes mid-job costs only its own
    connection: the job still completes and is accounted, the feeders
    and the next client are untouched — at one feeder lane and across
    the two-sub-mesh lane partition alike."""
    srv, cl = _serve_pair(tmp_path_factory, "unix", workers=2,
                          worker_lanes=worker_lanes)
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(15.0)
        sock.connect(srv.config.socket_path)
        send_frame(sock, {"type": "submit",
                          "sequences": dataset[0],
                          "overlaps": dataset[1],
                          "target": dataset[2],
                          "progress": True, "stream": True})
        # read ONE interleaved frame to prove the stream started, then
        # vanish
        first = recv_frame(sock)
        assert first["type"] in ("progress", "result_part")
        sock.close()
        deadline = time.monotonic() + 30
        while (srv.queue.counters["completed"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert srv.queue.counters["completed"] == 1
        assert srv.queue.counters["failed"] == 0
        # the feeder and a fresh client both still work
        assert cl.submit(*dataset).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)


def test_bad_tenant_rejected(client, dataset):
    with pytest.raises(ServeError) as exc_info:
        client.submit(*dataset, tenant="no spaces")
    assert exc_info.value.code == "bad-request"
    assert "tenant" in str(exc_info.value)


# ------------------------------------------- journal part-streamed events
def test_journal_part_streamed_and_obsreport_check(dataset, tmp_path):
    """Every successful serve job journals one `part-streamed` event
    per output contig; `obsreport --check` verifies the count equals
    the job's contig count and fails when a part line is missing."""
    import obsreport
    from racon_tpu.obs.journal import read_journal

    journal = str(tmp_path / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, journal=journal).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        r1 = cl.submit(*dataset)
        parts: list = []
        r2 = cl.submit(*dataset, on_part=parts.append)
    finally:
        srv.drain(timeout=10)
    entries = read_journal(journal)
    by_job: dict = {}
    for e in entries:
        if e.get("event") == "part-streamed":
            by_job.setdefault(e["job"], []).append(e)
    assert len(by_job[r1.job_id]) == 1  # one contig in the synth set
    assert len(by_job[r2.job_id]) == len(parts) == 1
    assert by_job[r2.job_id][0]["contig"] == "draft"
    rc = obsreport.main(["--journal", journal,
                         "--flight-dir", str(tmp_path / "none"),
                         "--check"])
    assert rc == 0
    # drop one part-streamed line: the check must go red
    with open(journal) as fh:
        lines = [ln for ln in fh]
    kept = [ln for ln in lines
            if not ('"part-streamed"' in ln
                    and f'"{r2.job_id}"' in ln)]
    assert len(kept) < len(lines)
    with open(journal, "w") as fh:
        fh.writelines(kept)
    assert obsreport.main(["--journal", journal,
                           "--flight-dir", str(tmp_path / "none"),
                           "--check"]) == 1


# ------------------------------------------------- TTY-aware progress bars
class _FakeTTY(io.StringIO):
    def isatty(self):
        return True


def _drive_bar(stream, ticks=40):
    from racon_tpu.utils.logger import Logger

    old = sys.stderr
    sys.stderr = stream
    try:
        lg = Logger()
        lg.log()
        lg.bar_total(ticks)
        for _ in range(ticks):
            lg.bar("[phase] working")
    finally:
        sys.stderr = old
    return stream.getvalue()


def test_bar_non_tty_single_line():
    out = _drive_bar(io.StringIO())
    assert "\r" not in out
    assert out.count("\n") == 1
    assert out.startswith("[phase] working [====================] 100% ")


def test_bar_tty_byte_identical_to_classic():
    out = _drive_bar(_FakeTTY())
    # the classic protocol: 19 \r redraws then the completion line
    assert out.count("\r") == 19
    assert out.startswith("[phase] working [=>                  ] 5%\r")
    assert " 100% " in out and out.endswith("s\n")


def test_bar_quiet_level_silent():
    from racon_tpu.utils.logger import set_log_level

    set_log_level("quiet")
    try:
        out = _drive_bar(io.StringIO())
    finally:
        set_log_level(None)
    assert out == ""
