"""Serve-native fragment correction + admit-time ingest tests.

The acceptance spine of the fragment traffic class (ISSUE 20):

  - a `mode: "fragment"` serve job is byte-identical to the one-shot
    CLI `-f` run — pinned over BOTH transports (unix socket and
    localhost TCP) on the wincache-off path;
  - corrected reads stream as bounded GROUPS of `result_part` frames
    (`frag` read-axis receipts tiling [0, n_reads)), never one frame
    per read, and the parts' concatenation is the job's full FASTA;
  - invalid combinations (`mode` typos, fragment + range_lo/hi,
    fragment + rounds>1, frag_lo/hi without fragment) are typed
    `bad-request` rejections, and the VALID neighbours of each are
    accepted — pinned both directions;
  - `frag_lo`/`frag_hi` child slices concatenate (in slice order) to
    the whole-job bytes — the router's merge invariant, pinned here
    without a router;
  - admit-time ingest: validate-only catches a poisoned input at the
    door (`bad-request` + `rejected-ingest` terminal, server
    survives), subsample-on-admit is seed-deterministic, normalize
    rewrites paired headers — all journaled as annotations that
    `obsreport --check` accepts;
  - flagless byte-identity: a submit with NO mode/ingest keys journals
    exactly the same `received` field set as before this PR.
"""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.serve.client import PolishClient, ServeError
from racon_tpu.serve.server import PolishServer, make_fragment_dataset

N_READS = 17  # make_fragment_dataset: (2000 - 400) // 100 + 1


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    return make_fragment_dataset(
        str(tmp_path_factory.mktemp("frag_data")))


def solo_fragment(paths) -> bytes:
    """The one-shot `-f` oracle: same defaults the CLI resolves, same
    defaults ServeConfig resolves — byte-identity is only meaningful
    because both sides share them."""
    p = create_polisher(*paths, PolisherType.kF, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish(True))


@pytest.fixture(scope="module")
def solo_bytes(dataset):
    return solo_fragment(dataset)


@pytest.fixture(scope="module")
def server(dataset, tmp_path_factory):
    sock = str(tmp_path_factory.mktemp("frag_sock") / "s.sock")
    srv = PolishServer(socket_path=sock, workers=2, warmup=False,
                       wincache=False).start()
    yield srv
    srv.drain(timeout=10)


@pytest.fixture(scope="module")
def client(server):
    return PolishClient(socket_path=server.config.socket_path)


# --------------------------------------------- identity over transports
def test_fragment_byte_identical_to_oneshot_unix(client, dataset,
                                                 solo_bytes):
    r = client.submit(*dataset, fragment=True)
    assert r.fasta == solo_bytes


def test_fragment_byte_identical_to_oneshot_tcp(dataset, solo_bytes):
    srv = PolishServer(port=0, warmup=False, wincache=False).start()
    try:
        cl = PolishClient(port=srv.config.port)
        assert cl.submit(*dataset, fragment=True).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)


def test_fragment_warm_reuse_second_job_identical(client, dataset,
                                                  solo_bytes):
    """Warm-server reuse: the SECOND fragment job on the same process
    (engines, batcher, caches all warm) must still be byte-identical."""
    assert client.submit(*dataset, fragment=True).fasta == solo_bytes


# ------------------------------------------------------ bounded groups
def test_fragment_streams_bounded_groups(dataset, solo_bytes,
                                         tmp_path_factory):
    """With frag_group below the read count, corrected reads arrive in
    bounded groups whose `frag` receipts tile [0, n_reads) — and the
    parts' concatenation is the whole-job FASTA."""
    sock = str(tmp_path_factory.mktemp("frag_grp") / "s.sock")
    srv = PolishServer(socket_path=sock, warmup=False, wincache=False,
                       frag_group=8).start()
    try:
        cl = PolishClient(socket_path=sock)
        parts: list[dict] = []
        r = cl.submit(*dataset, fragment=True, on_part=parts.append)
    finally:
        srv.drain(timeout=10)
    assert r.fasta == solo_bytes
    assert b"".join(p["fasta"].encode("latin-1")
                    for p in parts) == solo_bytes
    # bounded: more than one frame, none larger than the group knob
    assert len(parts) > 1
    assert all(p["reads"] <= 8 for p in parts)
    # receipts tile the read axis from 0
    expect = 0
    for p in parts:
        lo, hi = p["frag"]
        assert lo == expect and hi > lo
        expect = hi
    assert expect == N_READS
    assert sum(p["reads"] for p in parts) == solo_bytes.count(b">")


def test_frag_group_env_knob_strict(monkeypatch):
    from racon_tpu.errors import RaconError
    from racon_tpu.serve.server import ServeConfig

    monkeypatch.setenv("RACON_TPU_FRAG_GROUP", "12")
    assert ServeConfig().frag_group == 12
    monkeypatch.setenv("RACON_TPU_FRAG_GROUP", "soon")
    with pytest.raises(RaconError):
        ServeConfig()
    monkeypatch.delenv("RACON_TPU_FRAG_GROUP")
    assert ServeConfig().frag_group == 64
    with pytest.raises(RaconError):
        ServeConfig(frag_group=0)


# ------------------------------------------------- frag_lo/frag_hi slices
def test_frag_slices_concatenate_to_whole(client, dataset, solo_bytes):
    """The router's fragment-merge invariant, pinned without a router:
    contiguous ascending [frag_lo, frag_hi) child jobs concatenate (in
    slice order) to the whole-job bytes."""
    cuts = (0, 5, 11, N_READS)
    got = b"".join(
        client.submit(*dataset, fragment=True,
                      frag_lo=lo, frag_hi=hi).fasta
        for lo, hi in zip(cuts, cuts[1:]))
    assert got == solo_bytes


# ------------------------------------------------- validation, both ways
def test_invalid_mode_rejected_valid_modes_accepted(client, dataset,
                                                    solo_bytes):
    seqs, ovl, tgt = (os.path.abspath(p) for p in dataset)
    base = {"type": "submit", "sequences": seqs, "overlaps": ovl,
            "target": tgt}
    with pytest.raises(ServeError) as exc_info:
        client.request(dict(base, mode="fragmnt"))
    assert exc_info.value.code == "bad-request"
    assert "mode" in str(exc_info.value)
    # both spellings of the valid surface are accepted
    ok = client.request(dict(base, mode="fragment"))
    assert ok.get("fasta", "").encode("latin-1") == solo_bytes
    assert client.request(dict(base, mode="contig")).get("type") == "result"


def test_fragment_plus_range_rejected(client, dataset):
    with pytest.raises(ServeError) as exc_info:
        client.request({"type": "submit",
                        "sequences": os.path.abspath(dataset[0]),
                        "overlaps": os.path.abspath(dataset[1]),
                        "target": os.path.abspath(dataset[2]),
                        "mode": "fragment", "range_lo": 0,
                        "range_hi": 4})
    assert exc_info.value.code == "bad-request"
    assert "range" in str(exc_info.value)


def test_fragment_rounds_gt1_rejected_rounds1_accepted(client, dataset,
                                                       solo_bytes):
    with pytest.raises(ServeError) as exc_info:
        client.submit(*dataset, fragment=True, rounds=2)
    assert exc_info.value.code == "bad-request"
    assert "rounds" in str(exc_info.value)
    # rounds == 1 is the single-pass surface and stays accepted
    assert client.submit(*dataset, fragment=True,
                         rounds=1).fasta == solo_bytes


def test_frag_bounds_validation_matrix(client, dataset):
    # malformed bounds via the client helper (ints, wrong ordering)
    for lo, hi in ((3, 3), (-1, 4)):
        with pytest.raises(ServeError) as exc_info:
            client.submit(*dataset, fragment=True, frag_lo=lo,
                          frag_hi=hi)
        assert exc_info.value.code == "bad-request"
    # malformed TYPES must be rejected server-side, so raw frames (the
    # client helper would coerce them before the wire)
    base = {"type": "submit",
            "sequences": os.path.abspath(dataset[0]),
            "overlaps": os.path.abspath(dataset[1]),
            "target": os.path.abspath(dataset[2]), "mode": "fragment"}
    for lo, hi in ((True, 4), (0, "many"), (0.5, 4)):
        with pytest.raises(ServeError) as exc_info:
            client.request(dict(base, frag_lo=lo, frag_hi=hi))
        assert exc_info.value.code == "bad-request"
    # frag bounds without fragment mode
    with pytest.raises(ServeError) as exc_info:
        client.submit(*dataset, frag_lo=0, frag_hi=4)
    assert exc_info.value.code == "bad-request"
    assert "fragment" in str(exc_info.value)


# ------------------------------------------------------- admit-time ingest
def test_ingest_validate_only_accepts_clean_inputs(client, dataset,
                                                   solo_bytes):
    r = client.submit(*dataset, fragment=True, ingest=True)
    assert r.fasta == solo_bytes


def test_ingest_rejects_poisoned_input_server_survives(
        dataset, solo_bytes, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("frag_ingest")
    bad = str(tmp / "bad.fasta")
    with open(bad, "w") as fh:
        fh.write("this is not fasta\n")
    journal = str(tmp / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp / "s.sock"), warmup=False,
                       wincache=False, journal=journal).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        with pytest.raises(ServeError) as exc_info:
            cl.submit(bad, dataset[1], dataset[2], fragment=True,
                      ingest=True)
        assert exc_info.value.code == "bad-request"
        # the warm server then completes a clean job byte-identically
        assert cl.submit(*dataset, fragment=True).fasta == solo_bytes
    finally:
        srv.drain(timeout=10)
    from racon_tpu.obs.journal import read_journal
    events = [e["event"] for e in read_journal(journal)]
    assert "rejected-ingest" in events
    # the rejected job terminated at the door: no started/failed pair
    import obsreport
    assert obsreport.main(["--journal", journal,
                           "--flight-dir", str(tmp / "none"),
                           "--check"]) == 0


def test_ingest_bad_spec_rejected_before_job(client, dataset):
    for sub in ({"reference_length": 0, "coverage": 2},
                {"reference_length": 2000, "coverage": 2, "pct": 50},
                {"reference_length": 2000, "coverage": 2,
                 "seed": "lucky"}):
        with pytest.raises(ServeError) as exc_info:
            client.submit(*dataset, subsample=sub)
        assert exc_info.value.code == "bad-request"
    # a non-object subsample must be rejected server-side (raw frame:
    # the client helper would throw before the wire)
    with pytest.raises(ServeError) as exc_info:
        client.request({"type": "submit",
                        "sequences": os.path.abspath(dataset[0]),
                        "overlaps": os.path.abspath(dataset[1]),
                        "target": os.path.abspath(dataset[2]),
                        "subsample": "half"})
    assert exc_info.value.code == "bad-request"


def test_subsample_on_admit_deterministic(client, dataset):
    """Seeded subsample-on-admit: identical seeds give identical output
    bytes; a different seed picks a different read subset."""
    kw = dict(subsample={"reference_length": 2000, "coverage": 2,
                         "seed": 7})
    a = client.submit(*dataset, **kw)
    b = client.submit(*dataset, **kw)
    assert a.fasta == b.fasta
    c = client.submit(*dataset,
                      subsample={"reference_length": 2000,
                                 "coverage": 2, "seed": 8})
    assert c.fasta != a.fasta


def test_normalize_on_admit(tmp_path_factory):
    """Paired-end header normalization on admit: the client ships raw
    reads whose headers only become unique after the `preprocess`
    rename (first occurrence -> "1"), with overlaps written against
    the POST-normalization names — the server normalizes before the
    polisher parses, and the journal carries the annotation trail."""
    import gzip

    from racon_tpu.serve.server import make_synth_dataset

    tmp = tmp_path_factory.mktemp("frag_norm")
    reads, ovl, draft = make_synth_dataset(str(tmp))
    # raw paired-end-shaped reads: same names as the synth set, but
    # the PAF is rewritten to the names normalization WILL produce
    # ("r0" -> "r01"), so the job only polishes if the server actually
    # ran the preprocess rename on admit
    ovl_norm = str(tmp / "ovl_norm.paf.gz")
    with gzip.open(ovl, "rt") as fh, \
            gzip.open(ovl_norm, "wt") as out:
        for line in fh:
            cols = line.split("\t")
            cols[0] += "1"
            out.write("\t".join(cols))
    journal = str(tmp / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp / "s.sock"), warmup=False,
                       wincache=False, journal=journal).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        with pytest.raises(ServeError):
            # without normalize the PAF names match nothing: typed fail
            cl.submit(reads, ovl_norm, draft)
        r = cl.submit(reads, ovl_norm, draft, normalize=True)
        assert r.fasta.startswith(b">draft")
    finally:
        srv.drain(timeout=10)
    from racon_tpu.obs.journal import read_journal
    events = [e["event"] for e in read_journal(journal)]
    assert "ingested" in events and "normalized" in events


# -------------------------------------------- journal + flagless identity
def test_fragment_journal_and_obsreport_check(dataset, solo_bytes,
                                              tmp_path_factory):
    """Fragment jobs journal group-granularity part-streamed lines
    (`reads=N`), finished `sequences` equals the read total, and
    `obsreport --check` accepts the aggregate receipt — then goes red
    when a group line is dropped."""
    import obsreport
    from racon_tpu.obs.journal import read_journal

    tmp = tmp_path_factory.mktemp("frag_journal")
    journal = str(tmp / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp / "s.sock"), warmup=False,
                       wincache=False, frag_group=8,
                       journal=journal).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        r = cl.submit(*dataset, fragment=True)
    finally:
        srv.drain(timeout=10)
    assert r.fasta == solo_bytes
    entries = read_journal(journal)
    received = [e for e in entries if e.get("event") == "received"
                and e.get("job") == r.job_id]
    assert received and received[0].get("mode") == "fragment"
    groups = [e for e in entries if e.get("event") == "part-streamed"
              and e.get("job") == r.job_id]
    assert len(groups) == 3  # 17 reads / frag_group=8
    assert sum(e["reads"] for e in groups) == solo_bytes.count(b">")
    flight = str(tmp / "none")
    assert obsreport.main(["--journal", journal, "--flight-dir",
                           flight, "--check"]) == 0
    # drop one group line: the aggregate receipt must go red
    with open(journal) as fh:
        lines = fh.readlines()
    kept = [ln for ln in lines if '"part-streamed"' not in ln
            or f'"{r.job_id}"' not in ln
            or '"part":2' in ln or '"part":3' in ln]
    assert len(kept) == len(lines) - 1
    with open(journal, "w") as fh:
        fh.writelines(kept)
    assert obsreport.main(["--journal", journal, "--flight-dir",
                           flight, "--check"]) == 1


def test_flagless_submit_journal_fields_unchanged(dataset,
                                                  tmp_path_factory):
    """No mode / ingest keys on the frame ⇒ the journal `received`
    line carries exactly the pre-PR field set — the flagless
    byte-identity acceptance, checked at field granularity."""
    from racon_tpu.obs.journal import read_journal

    tmp = tmp_path_factory.mktemp("frag_flagless")
    journal = str(tmp / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp / "s.sock"), warmup=False,
                       journal=journal).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        r = cl.submit(*dataset)
    finally:
        srv.drain(timeout=10)
    entries = read_journal(journal)
    received = [e for e in entries if e.get("event") == "received"
                and e.get("job") == r.job_id]
    assert received
    for key in ("mode", "frag_lo", "frag_hi", "ingest", "subsample",
                "normalize"):
        assert key not in received[0]
