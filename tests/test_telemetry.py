"""Serve-grade telemetry: histograms, Prometheus exposition, flight
recorder, SLO accounting, and the non-tty progress-bar pin.

The load-bearing contracts, in ISSUE order:

  - `obs.hist.Histogram` quantile estimates agree with exact numpy
    percentiles on known distributions (within the log-bucket bound),
    survive concurrent observers without losing counts, and merge
    exactly;
  - a live `scrape` during a running job returns Prometheus text a
    minimal parser accepts — cumulative buckets monotone, `+Inf` equals
    `_count` — with non-zero latency histogram buckets;
  - the metrics-flush error path (unwritable RACON_TPU_METRICS) and a
    scrape issued mid-drain never take the server down;
  - a fault-injected job produces a parseable flight-recorder dump whose
    pipeline span sums match the stage_stats snapshot embedded in it;
  - a job that finishes past its deadline counts as an SLO miss, dumps
    a flight artifact, and surfaces in `stats`' slo view;
  - the optional localhost HTTP endpoint serves the same scrape body;
  - a subprocess whose stderr is a pipe emits ONE progress line per
    phase (the BENCH_r05 per-tick bloat stays dead).
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from racon_tpu.obs import prom
from racon_tpu.obs.flight import FlightRecorder, dump, window_events
from racon_tpu.obs.hist import Histogram, HistogramSet
from racon_tpu.serve import PolishClient, PolishServer, make_synth_dataset
from racon_tpu.serve.client import JobFailed
from racon_tpu.serve.protocol import recv_frame, send_frame
from racon_tpu.serve.queue import Job, JobQueue


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    return make_synth_dataset(str(tmp_path_factory.mktemp("telem_data")))


@pytest.fixture(scope="module")
def server(dataset, tmp_path_factory):
    d = tmp_path_factory.mktemp("telem_srv")
    srv = PolishServer(socket_path=str(d / "s.sock"), workers=2,
                       flight_dir=str(d / "flight")).start()
    yield srv
    srv.drain(timeout=10)


@pytest.fixture(scope="module")
def client(server):
    return PolishClient(socket_path=server.config.socket_path)


# -------------------------------------------------------------- histograms
@pytest.mark.parametrize("sample", ["uniform", "lognormal"])
def test_histogram_quantiles_vs_numpy(sample):
    rng = np.random.default_rng(7)
    if sample == "uniform":
        values = rng.uniform(0.001, 10.0, 20000)
    else:
        values = rng.lognormal(mean=-2.0, sigma=1.5, size=20000)
    h = Histogram()
    for v in values:
        h.observe(float(v))
    assert h.count == len(values)
    assert h.sum == pytest.approx(values.sum(), rel=1e-9)
    assert h.min == pytest.approx(values.min())
    assert h.max == pytest.approx(values.max())  # max is EXACT
    for q in (0.50, 0.95, 0.99):
        exact = float(np.percentile(values, q * 100))
        est = h.quantile(q)
        # log buckets grow by 2**0.25 (~19%/bucket); the estimate is
        # inside the true value's bucket, so 20% relative is the bound
        assert est == pytest.approx(exact, rel=0.20), \
            f"{sample} p{int(q * 100)}: {est} vs exact {exact}"


def test_histogram_concurrent_observe():
    h = Histogram()
    n_threads, per_thread = 8, 5000

    def work(k):
        for i in range(per_thread):
            h.observe(0.001 * ((k * per_thread + i) % 100 + 1))

    threads = [threading.Thread(target=work, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per_thread  # no lost increments
    le, cum = h.cumulative()[-1]
    assert le == float("inf") and cum == h.count
    assert sum(1 for _ in h.cumulative()) >= 10


def test_histogram_merge_exact():
    a, b, both = Histogram(), Histogram(), Histogram()
    rng = np.random.default_rng(3)
    for v in rng.uniform(0.01, 2.0, 500):
        a.observe(float(v))
        both.observe(float(v))
    for v in rng.lognormal(0.0, 1.0, 500):
        b.observe(float(v))
        both.observe(float(v))
    a.merge(b)
    assert a.count == both.count
    assert a.sum == pytest.approx(both.sum)
    assert a.max == both.max and a.min == both.min
    assert [c for _, c in a.cumulative()] == \
        [c for _, c in both.cumulative()]


def test_histogram_edge_cases():
    h = Histogram()
    assert h.snapshot() == {"count": 0}
    assert h.quantile(0.5) == 0.0
    h.observe(-1.0)   # clamped, not crashed
    h.observe(0.0)
    h.observe(1e9)    # overflow bucket
    assert h.count == 3
    assert h.max == 1e9
    snap = h.snapshot()
    assert snap["count"] == 3 and snap["min"] == 0.0


# ---------------------------------------------------- prometheus rendering
def parse_prom(text: str) -> dict:
    """Minimal Prometheus text parser: {family: {"type": t, "samples":
    [(full_name, labels_dict, value)]}}. Asserts line-level syntax."""
    families: dict = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)"
        r"(?:\s+#\s+\{[^}]*\}.*)?$")  # optional OpenMetrics exemplar
    cur = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split()
            cur = families.setdefault(name,
                                      {"type": typ, "samples": []})
            continue
        if line.startswith("#"):
            continue
        m = line_re.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labels_raw, value = m.groups()
        labels = {}
        if labels_raw:
            for part in labels_raw[1:-1].split(","):
                k, v = part.split("=", 1)
                assert v.startswith('"') and v.endswith('"'), line
                labels[k] = v[1:-1]
        v = float("inf") if value == "+Inf" else float(value)
        base = re.sub(r"_(bucket|sum|count|total)$", "", name)
        fam = families.get(name) or families.get(base)
        assert fam is not None, f"sample before TYPE: {line!r}"
        fam["samples"].append((name, labels, v))
    return families


def check_histogram_family(fam: dict) -> int:
    """Cumulative-bucket invariants; returns the family's count."""
    assert fam["type"] == "histogram"
    buckets = [(lbl["le"], v) for n, lbl, v in fam["samples"]
               if n.endswith("_bucket")]
    count = [v for n, _, v in fam["samples"] if n.endswith("_count")]
    assert buckets and len(count) == 1
    cums = [v for _, v in buckets]
    assert cums == sorted(cums), "buckets not cumulative"
    assert buckets[-1][0] == "+Inf"
    assert cums[-1] == count[0], "+Inf bucket != count"
    return int(count[0])


def test_prom_render_parseable():
    hs = HistogramSet()
    for v in (0.01, 0.1, 0.1, 5.0):
        hs.observe("job.latency", v)
    text = prom.render(
        counters={"serve.jobs.completed": 4,
                  "serve.jobs.failed": (1, "jobs that failed")},
        gauges={"serve.inflight": 2, "serve.draining": False},
        hists=hs)
    fams = parse_prom(text)
    assert fams["racon_tpu_serve_jobs_completed_total"]["type"] == \
        "counter"
    assert fams["racon_tpu_serve_inflight"]["type"] == "gauge"
    n = check_histogram_family(fams["racon_tpu_job_latency_seconds"])
    assert n == 4
    sums = [v for name, _, v in
            fams["racon_tpu_job_latency_seconds"]["samples"]
            if name.endswith("_sum")]
    assert sums[0] == pytest.approx(5.21)


def test_prom_histogram_consistent_under_concurrent_observe():
    """The scrape body must satisfy bucket{le="+Inf"} == _count even
    while another thread keeps observing — one atomic export per
    histogram, not three racing reads."""
    hs = HistogramSet()
    hs.observe("x", 0.01)
    stop = threading.Event()

    def observer():
        i = 0
        while not stop.is_set():
            hs.observe("x", 0.001 * (i % 50 + 1))
            i += 1

    t = threading.Thread(target=observer)
    t.start()
    try:
        for _ in range(200):
            fams = parse_prom(prom.render(hists=hs))
            check_histogram_family(fams["racon_tpu_x_seconds"])
    finally:
        stop.set()
        t.join()


def test_nearest_rank_percentiles():
    from racon_tpu.serve.queue import nearest_rank

    vals = list(range(1, 101))  # ranks 1..100
    assert nearest_rank(vals, 0.99) == 99  # NOT the max
    assert nearest_rank(vals, 0.95) == 95
    assert nearest_rank(vals, 0.50) == 50
    assert nearest_rank(vals, 1.00) == 100
    assert nearest_rank([5.0], 0.99) == 5.0
    assert nearest_rank([1, 2], 0.50) == 1


# --------------------------------------------------------- flight recorder
def test_flight_ring_bounded():
    rec = FlightRecorder(capacity=16)
    for i in range(200):
        rec.complete(f"span{i}", 0.0, 0.001)
    events = [e for e in rec.events() if e["ph"] != "M"]
    assert len(events) == 16  # ring evicted the oldest 184
    names = [e["name"] for e in events]
    assert names[-1] == "span199" and names[0] == "span184"


def test_flight_constant_memory_across_thread_churn():
    """A long-lived server spawns fresh pipeline threads per job; the
    recorder must not retain one buffer (or one track id) per dead
    thread — rings and tracks both stay bounded."""
    rec = FlightRecorder(capacity=64)

    def job(k):
        for i in range(50):
            rec.complete("pipeline.pack", 0.0, 0.001, {"k": k})

    for wave in range(20):  # 100 short-lived threads, 5 repeating names
        threads = [threading.Thread(target=job, args=(wave,),
                                    name=f"racon-tpu-worker-{i}")
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert len(rec._buffers) == 1          # ONE shared ring, ever
    assert len(rec._threads) == 5          # tracks keyed by name
    events = rec.events()
    assert len([e for e in events if e["ph"] != "M"]) == 64
    assert len([e for e in events if e["ph"] == "M"]) == 5


def test_scoped_trace_tees_into_flight_ring():
    """A per-job scoped trace must not blind the always-on flight ring:
    spans recorded during the scope land in BOTH recorders."""
    from racon_tpu.obs import trace as obs_trace

    flight = obs_trace.install(FlightRecorder(capacity=64))
    try:
        with obs_trace.scoped() as rec:
            obs_trace.get_tracer().complete("during.scope", 0.0, 0.001)
            with obs_trace.span("via.module"):
                pass
        scoped_names = {e["name"] for e in rec.events()
                        if e["ph"] != "M"}
        ring_names = {e["name"] for e in flight.events()
                      if e["ph"] != "M"}
        assert {"during.scope", "via.module"} <= scoped_names
        assert {"during.scope", "via.module"} <= ring_names
        assert obs_trace.get_tracer() is flight  # restored on exit
    finally:
        obs_trace.reset()


def test_flight_window_and_dump(tmp_path):
    rec = FlightRecorder()
    t0 = time.perf_counter()
    rec.complete("early", t0, t0 + 0.001)
    cut = time.perf_counter()
    rec.complete("late", cut + 0.001, cut + 0.002)
    kept = window_events(rec, since=cut)
    names = {e["name"] for e in kept if e["ph"] != "M"}
    assert names == {"late"}
    assert any(e["ph"] == "M" for e in kept)  # thread meta preserved
    path = str(tmp_path / "dump.json")
    dump(rec, path, since=cut, flight={"job_id": "j1", "reason": "test"})
    doc = json.load(open(path))
    assert doc["flight"]["job_id"] == "j1"
    assert {e["name"] for e in doc["traceEvents"]
            if e["ph"] != "M"} == {"late"}


# ----------------------------------------------------------- SLO (queue)
def test_queue_slo_hit_and_miss_accounting():
    q = JobQueue(maxsize=4)
    hit = Job("h", "s", "o", "t", {}, deadline_s=30.0)
    q.submit(hit)
    assert q.pop(timeout=0.5) is hit
    assert q.task_done(hit, True, 0.01) is False
    miss = Job("m", "s", "o", "t", {}, deadline_s=0.01)
    q.submit(miss)
    job = q.pop(timeout=0.5)
    if job is not None:  # raced past the deadline -> consumed as expired
        time.sleep(0.02)
        assert q.task_done(job, True, 0.02) is True
        assert q.counters["deadline_miss"] == 1
    assert q.counters["deadline_hit"] == 1
    snap = q.snapshot()
    assert snap["recent"]["jobs"] >= 1
    assert snap["recent"]["p50_s"] >= 0


# ------------------------------------------------------- live serve scrape
def test_scrape_during_running_job_nonzero_latency(client, dataset,
                                                   server):
    """The acceptance gate: Prometheus text mid-job, parseable, with
    populated latency histogram buckets."""
    done = threading.Event()
    result: list = [None]

    def go():
        try:
            result[0] = client.submit(*dataset)
        finally:
            done.set()

    t = threading.Thread(target=go)
    t.start()
    texts = [client.scrape()]
    while not done.is_set() and len(texts) < 500:
        texts.append(client.scrape())
    t.join(timeout=60)
    assert result[0] is not None
    fams = parse_prom(texts[-1])
    hist_fams = {n: f for n, f in fams.items()
                 if f["type"] == "histogram"}
    assert hist_fams, "no histograms in scrape"
    populated = {n: check_histogram_family(f)
                 for n, f in hist_fams.items()}
    assert any(c > 0 for c in populated.values()), populated
    # the load-bearing families are present by name
    for want in ("racon_tpu_pipeline_pack_seconds",
                 "racon_tpu_job_queue_wait_seconds",
                 "racon_tpu_serve_iteration_seconds"):
        assert want in fams, sorted(hist_fams)
    assert check_histogram_family(
        fams["racon_tpu_serve_iteration_seconds"]) > 0


def test_scrape_rpc_matches_http(dataset, tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, metrics_port=0,
                       flight_dir=str(tmp_path / "fl")).start()
    try:
        import urllib.error
        import urllib.request

        assert srv.config.metrics_port > 0  # ephemeral port published
        cl = PolishClient(socket_path=srv.config.socket_path)
        cl.submit(*dataset)
        url = f"http://127.0.0.1:{srv.config.metrics_port}"
        body = urllib.request.urlopen(f"{url}/metrics",
                                      timeout=10).read().decode()
        fams_http = parse_prom(body)
        fams_rpc = parse_prom(cl.scrape())
        assert set(fams_http) == set(fams_rpc)
        health = json.loads(urllib.request.urlopen(
            f"{url}/healthz", timeout=10).read())
        assert health["ok"] is True and health["draining"] is False
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{url}/nope", timeout=10)
        # the polish server is untouched by HTTP traffic
        assert cl.ping()["type"] == "pong"
    finally:
        srv.drain(timeout=10)


def test_scrape_during_drain_and_unwritable_metrics(dataset, tmp_path,
                                                    monkeypatch):
    """Neither an unwritable RACON_TPU_METRICS path nor a scrape issued
    mid-drain may take the server down."""
    monkeypatch.setenv("RACON_TPU_METRICS",
                       str(tmp_path / "no_such_dir" / "m.json"))
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, workers=1,
                       flight_dir=str(tmp_path / "fl")).start()
    cl = PolishClient(socket_path=srv.config.socket_path)
    cl.submit(*dataset)  # something worth flushing
    # pre-open a connection: drain closes the listener immediately, but
    # established connections are served until the drain completes
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10.0)
    sock.connect(srv.config.socket_path)
    try:
        # an in-flight job with an injected hang keeps the drain open
        # long enough to scrape INTO it deterministically
        slow_result: list = [None]

        def go():
            try:
                slow_result[0] = cl.submit(
                    *dataset, fault_plan="device:chunk=0:hang=0.5")
            except Exception as exc:  # noqa: BLE001 — asserted below
                slow_result[0] = exc
        slow = threading.Thread(target=go)
        slow.start()
        deadline = time.monotonic() + 10
        while (srv.queue.counters["admitted"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        drainer = threading.Thread(target=srv.drain, kwargs={
            "timeout": 30})
        drainer.start()
        while not srv._draining.is_set():
            time.sleep(0.005)
        send_frame(sock, {"type": "scrape"})
        resp = recv_frame(sock)
        assert resp["type"] == "metrics"
        parse_prom(resp["text"])
        slow.join(timeout=30)
        drainer.join(timeout=30)
        assert srv._stopped.is_set()  # drained cleanly despite both
        assert not isinstance(slow_result[0], Exception), slow_result
    finally:
        sock.close()
    assert not os.path.exists(str(tmp_path / "no_such_dir"))


# ------------------------------------------------- flight dumps on failure
def test_failed_job_flight_dump_spans_match_stats(dataset, tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, workers=1,
                       flight_dir=str(tmp_path / "flight")).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        with pytest.raises(JobFailed) as exc_info:
            cl.submit(*dataset, fault_plan="unpack:chunk=0:corrupt",
                      strict=True)
        assert exc_info.value.error_type == "ChunkCorrupt"
        dumps = cl.debug()["dumps"]
        assert len(dumps) == 1 and "job-failed" in dumps[0]
        doc = json.load(open(dumps[0]))
        flight = doc["flight"]
        assert flight["reason"] == "job-failed"
        assert flight["error_type"] == "ChunkCorrupt"
        stats = flight["stage_stats"]
        assert stats["faults"] == 1
        assert stats["pack_s"] > 0  # chunk 0 packed before the poison
        # span sums pin to the embedded stage stats: same perf_counter
        # endpoints, so only serialization rounding separates them
        sums: dict = {}
        for ev in doc["traceEvents"]:
            for field in ("name", "ph", "pid", "tid"):
                assert field in ev
            if ev["ph"] == "X" and ev["name"].startswith("pipeline."):
                stage = ev["name"].split(".", 1)[1]
                sums[stage] = sums.get(stage, 0.0) + ev["dur"] / 1e6
        for stage, key in (("pack", "pack_s"), ("device", "device_s"),
                           ("unpack", "unpack_s"),
                           ("fallback", "fallback_s")):
            assert sums.get(stage, 0.0) == pytest.approx(
                stats[key], rel=0.05, abs=1e-3), \
                f"{stage}: {sums.get(stage)} vs {stats[key]}"
        # the server survives and the ring keeps recording
        assert cl.ping()["type"] == "pong"
        # the FAILED job's latency observations reached the lifetime
        # scrape view — p99s must not be built from healthy jobs only
        fams = parse_prom(cl.scrape())
        assert check_histogram_family(
            fams["racon_tpu_pipeline_pack_seconds"]) > 0
    finally:
        srv.drain(timeout=10)


def test_deadline_miss_counts_and_dumps(dataset, tmp_path):
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, workers=1,
                       flight_dir=str(tmp_path / "flight")).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        # the injected hang holds the job well past its deadline while
        # the idle worker pops it immediately: deterministic MISS (the
        # job still completes — distinct from expired-in-queue)
        r = cl.submit(*dataset, deadline_s=0.3,
                      fault_plan="device:chunk=0:hang=0.8")
        assert r.fasta  # ran to completion, late
        snap = cl.stats()
        assert snap["slo"]["deadline_miss"] == 1
        assert snap["slo"]["miss_rate"] == 1.0
        dumps = snap["flight"]["dumps"]
        assert len(dumps) == 1 and "deadline-miss" in dumps[0]
        doc = json.load(open(dumps[0]))
        assert doc["flight"]["reason"] == "deadline-miss"
        # an on-time job counts as a hit against the same numbers
        cl.submit(*dataset, deadline_s=60.0)
        snap = cl.stats()
        assert snap["slo"]["deadline_hit"] == 1
        assert snap["slo"]["miss_rate"] == 0.5
        assert snap["slo"]["recent"]["jobs"] == 2
    finally:
        srv.drain(timeout=10)


def test_invalid_metrics_port_rejected(monkeypatch):
    from racon_tpu.errors import RaconError
    from racon_tpu.serve import ServeConfig

    monkeypatch.setenv("RACON_TPU_SERVE_METRICS_PORT", "8o80")  # typo
    with pytest.raises(RaconError):
        ServeConfig()
    monkeypatch.delenv("RACON_TPU_SERVE_METRICS_PORT")
    with pytest.raises(RaconError):
        ServeConfig(metrics_port=-2)
    assert ServeConfig(metrics_port=0).metrics_port == 0
    assert ServeConfig().metrics_port is None


def test_debug_rpc_returns_ring(client, dataset):
    client.submit(*dataset)
    d = client.debug()
    assert d["type"] == "debug"
    assert d["flight_installed"]
    names = {e["name"] for e in d["events"]}
    assert any(n.startswith("pipeline.") for n in names), names
    capped = client.debug(max_events=5)
    assert len([e for e in capped["events"] if e["ph"] != "M"]) <= 5


def test_job_latency_namespace_in_polisher_metrics(dataset):
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*dataset, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    p.polish()
    snap = p.metrics.snapshot()
    assert snap["latency"]["phase.consensus"]["count"] == 1
    assert snap["latency"]["phase.initialize"]["p50"] > 0
    assert snap["latency"]["pipeline.pack"]["count"] >= 1
    # ONE device sample per chunk (dispatch + wait summed), so the
    # device distribution is comparable with the other stages
    assert snap["latency"]["pipeline.device"]["count"] == \
        p.stage_stats["chunks"]
    flat = p.metrics.flat()
    assert "latency.phase.consensus.p99" in flat


# ----------------------------------------------- durable serve journal
def test_journal_rotation_and_reader(tmp_path):
    from racon_tpu.obs.journal import Journal, read_journal

    p = str(tmp_path / "j.jsonl")
    j = Journal(p, max_bytes=600)
    for i in range(60):
        j.record("tick", job=f"j{i}", i=i)
    assert j.events == 60 and j.dropped == 0
    j.close()
    assert os.path.exists(p + ".1")  # rotated exactly one generation
    assert os.path.getsize(p) <= 600
    entries = read_journal(p)
    assert entries, "reader lost everything"
    seq = [e["i"] for e in entries]
    # both generations read in order: a contiguous most-recent suffix
    assert seq == list(range(seq[0], 60))
    assert all(e["event"] == "tick" and "t" in e for e in entries)


def test_journal_stage_preserves_order(tmp_path):
    """stage() (the under-queue-lock path) keeps its relative order
    against later record() writes, and close() drains the tail."""
    from racon_tpu.obs.journal import Journal, read_journal

    p = str(tmp_path / "j.jsonl")
    j = Journal(p)
    j.record("received", job="a")
    j.stage("admitted", job="a")       # no disk I/O here
    j.record("started", job="a")       # drains the staged line first
    j.stage("admitted", job="b")
    j.close()                          # drains the tail
    events = [(e["job"], e["event"]) for e in read_journal(p)]
    assert events == [("a", "received"), ("a", "admitted"),
                      ("a", "started"), ("b", "admitted")]
    assert j.events == 4 and j.dropped == 0


def test_journal_consistency_checker():
    from racon_tpu.obs.journal import check_consistency

    def ev(event, job):
        return {"t": 0.0, "event": event, "job": job}

    ok = [ev("received", "a"), ev("admitted", "a"), ev("started", "a"),
          ev("finished", "a"),
          ev("received", "b"), ev("rejected-full", "b"),
          ev("received", "c"), ev("admitted", "c"), ev("expired", "c"),
          {"t": 0.0, "event": "serve-start"}]
    assert check_consistency(ok) == []
    # started but no terminal
    assert check_consistency([ev("received", "x"), ev("started", "x")])
    # two terminal states
    assert check_consistency(
        [ev("started", "x"), ev("finished", "x"), ev("failed", "x")])
    # finished without started, full lifecycle visible
    assert check_consistency([ev("received", "x"), ev("finished", "x")])
    # rotation cut the head: finished-without-started is NOT flagged
    # when `received` fell outside the window
    assert check_consistency([ev("finished", "x")]) == []


def test_serve_journal_lifecycle(dataset, tmp_path):
    from racon_tpu.obs.journal import check_consistency, read_journal

    jp = str(tmp_path / "journal.jsonl")
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, workers=1, journal=jp,
                       flight_dir=str(tmp_path / "fl")).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        ok_job = cl.submit(*dataset, trace_id="tid-journal")
        with pytest.raises(JobFailed):
            cl.submit(*dataset, fault_plan="unpack:chunk=0:corrupt",
                      strict=True)
        late = cl.submit(*dataset, deadline_s=0.3,
                         fault_plan="device:chunk=0:hang=0.8")
        assert late.fasta
    finally:
        srv.drain(timeout=15)
    entries = read_journal(jp)
    assert check_consistency(entries) == []
    events = [e["event"] for e in entries]
    assert events[0] == "serve-start" and events[-1] == "serve-stop"
    assert "drain" in events
    by_job: dict = {}
    for e in entries:
        if e.get("job"):
            by_job.setdefault(e["job"], []).append(e)
    assert len(by_job) == 3
    ok_events = [e["event"] for e in by_job[ok_job.job_id]]
    assert ok_events == ["received", "admitted", "started",
                         "part-streamed", "iterations", "finished"]
    # the trace id rides every line of its job
    assert all(e.get("trace") == "tid-journal"
               for e in by_job[ok_job.job_id])
    failed = next(evs for evs in by_job.values()
                  if any(e["event"] == "failed" for e in evs))
    assert next(e for e in failed if e["event"] == "failed")[
        "error_type"] == "ChunkCorrupt"
    missed = next(evs for evs in by_job.values()
                  if any(e["event"] == "deadline-miss" for e in evs))
    assert [e["event"] for e in missed][-1] == "finished"


def test_bad_flight_dir_or_journal_fails_start(tmp_path):
    from racon_tpu.errors import RaconError

    not_a_dir = tmp_path / "file"
    not_a_dir.write_text("x")
    with pytest.raises(RaconError, match="flight"):
        PolishServer(socket_path=str(tmp_path / "a.sock"),
                     warmup=False, flight_dir=str(not_a_dir)).start()
    with pytest.raises(RaconError, match="journal"):
        PolishServer(socket_path=str(tmp_path / "b.sock"),
                     warmup=False, flight_dir=str(tmp_path / "fl"),
                     journal=str(tmp_path / "missing" / "j.jsonl")
                     ).start()


def test_flight_dir_env_resolution(monkeypatch, tmp_path):
    from racon_tpu.serve import ServeConfig

    monkeypatch.delenv("RACON_TPU_SERVE_FLIGHT_DIR", raising=False)
    monkeypatch.setenv("RACON_TPU_FLIGHT_DIR", str(tmp_path / "proc"))
    assert ServeConfig().flight_dir == str(tmp_path / "proc")
    monkeypatch.setenv("RACON_TPU_SERVE_FLIGHT_DIR",
                       str(tmp_path / "serve"))
    assert ServeConfig().flight_dir == str(tmp_path / "serve")
    assert ServeConfig(flight_dir="").flight_dir == ""  # kwarg wins
    assert ServeConfig(flight_dir="/x").flight_dir_explicit
    monkeypatch.delenv("RACON_TPU_SERVE_FLIGHT_DIR")
    monkeypatch.delenv("RACON_TPU_FLIGHT_DIR")
    cfg = ServeConfig()
    assert cfg.flight_dir == "/tmp/racon_tpu_flight"
    # the built-in default is NOT strict-validated at startup: a plain
    # `racon_tpu serve` keeps the PR-6 best-effort-per-dump posture
    assert not cfg.flight_dir_explicit


def test_scrape_restart_and_queue_gauges(client, server):
    """The restart-detection series: uptime + wall-clock start time,
    plus the live queue-depth gauges."""
    fams = parse_prom(client.scrape())
    for name in ("racon_tpu_serve_uptime_seconds",
                 "racon_tpu_serve_start_time_seconds",
                 "racon_tpu_serve_queue_depth",
                 "racon_tpu_serve_queue_oldest_wait_seconds"):
        assert name in fams, sorted(fams)
        assert fams[name]["type"] == "gauge"
    start = fams["racon_tpu_serve_start_time_seconds"]["samples"][0][2]
    assert abs(start - time.time()) < 3600  # wall clock, recent
    uptime = fams["racon_tpu_serve_uptime_seconds"]["samples"][0][2]
    assert 0 < uptime < 3600


def test_obsreport_tool(dataset, tmp_path):
    """tools/obsreport.py renders the journal alongside flight dumps
    and its --check passes on a consistent journal."""
    jp = str(tmp_path / "journal.jsonl")
    fl = str(tmp_path / "flight")
    srv = PolishServer(socket_path=str(tmp_path / "s.sock"),
                       warmup=False, workers=1, journal=jp,
                       flight_dir=fl).start()
    try:
        cl = PolishClient(socket_path=srv.config.socket_path)
        ok_job = cl.submit(*dataset)
        with pytest.raises(JobFailed):
            cl.submit(*dataset, fault_plan="unpack:chunk=0:corrupt",
                      strict=True)
    finally:
        srv.drain(timeout=15)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if "axon" not in k.lower()}
    env["PYTHONPATH"] = repo
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "obsreport.py"),
         "--journal", jp, "--flight-dir", fl, "--check"],
        env=env, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert f"job {ok_job.job_id}" in proc.stdout
    assert "finished" in proc.stdout and "failed" in proc.stdout
    assert "flight dump:" in proc.stdout  # the failed job's artifact
    assert "consistency: OK" in proc.stdout


# --------------------------------------------- progress bars through pipes
def test_bar_subprocess_pipe_one_line_per_phase():
    """The BENCH_r05 bloat pin: a subprocess whose stderr is a PIPE (the
    bench.py / servebench capture shape) must emit exactly ONE completion
    line per phase — no per-tick redraws, no carriage returns even after
    text-mode universal-newline translation."""
    code = (
        "import sys\n"
        "from racon_tpu.utils.logger import Logger\n"
        "lg = Logger()\n"
        "for phase in ('one', 'two'):\n"
        "    lg.log()\n"
        "    lg.bar_total(40)\n"
        "    for _ in range(40):\n"
        "        lg.bar('[phase] ' + phase)\n"
    )
    env = {k: v for k, v in os.environ.items() if "axon" not in k.lower()}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "\r" not in proc.stderr
    lines = proc.stderr.splitlines()
    assert len(lines) == 2, lines  # ONE line per phase, not one per 5%
    for phase, line in zip(("one", "two"), lines):
        assert line.startswith(
            f"[phase] {phase} [====================] 100% ")
