"""Fleet-wide distributed tracing tests (serve/router.py +
serve/client.py + obs/trace.py + tools/tracereport.py) — the ISSUE's
pinned contracts:

  - clock sync: `PolishClient.clock_sync()` against a server whose
    mono clock is skewed by +/-50ms recovers the injected offset to
    within the min-RTT bracket (rtt/2) — the accuracy claim the
    merged-timeline construction rests on;
  - rebase: `obs.trace.rebase_events` onto colliding pids keeps every
    replica's events on its own process track (same thread tids on
    two replicas must not interleave), prefixes process_name metadata,
    and never mutates the input events;
  - routed trace matrix: a 2-replica routed `submit_traced` job over
    unix (contig-sharded) AND TCP (range-sharded) produces ONE valid
    Chrome-trace JSON with client/router/per-replica tracks on a
    common clock, and `tools/tracereport.py` walks it: the per-stage
    attribution sums to the job wall (exact by construction) with
    every check green (--check rc 0) — including with a REQUEUE
    injected via a dying replica (the kill -9 shape, deterministic);
  - per-tenant device-cost accounting: each replica's tenant
    device-seconds buckets sum to its total lane busy seconds, the
    labeled counter federates across a 2-replica fleet through
    FleetAggregator, and the federated sum equals the fleet total;
  - flagless pin: an untraced, untenanted routed job's response frame
    carries NO trace/trace_replicas/shards_detail keys and the replica
    scrape has no tenant device-seconds family — the trace plane is
    invisible until armed.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools"))

import tracereport

from racon_tpu.core.polisher import PolisherType, create_polisher
from racon_tpu.obs.fleet import FleetAggregator
from racon_tpu.obs.journal import check_consistency, read_journal
from racon_tpu.obs.trace import rebase_events
from racon_tpu.serve import (PolishClient, PolishRouter, PolishServer,
                             make_synth_dataset)
from racon_tpu.serve.client import ServeError
from racon_tpu.serve.protocol import ProtocolError, recv_frame, send_frame

TENANT_FAMILY = "racon_tpu_serve_tenant_device_seconds_total"


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def dataset4(tmp_path_factory):
    """Four independent contigs — contig-shards across 2 replicas."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("trace_data4")),
                              contigs=4)


@pytest.fixture(scope="module")
def dataset1(tmp_path_factory):
    """ONE contig (4 windows at wl=500) — forces range sharding."""
    return make_synth_dataset(str(tmp_path_factory.mktemp("trace_data1")))


def _polish_solo(paths) -> bytes:
    p = create_polisher(*paths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    return b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                    for s in p.polish())


@pytest.fixture(scope="module")
def solo4(dataset4):
    return _polish_solo(dataset4)


@pytest.fixture(scope="module")
def solo1(dataset1):
    return _polish_solo(dataset1)


@pytest.fixture(scope="module")
def trace_replicas(tmp_path_factory):
    d = tmp_path_factory.mktemp("trace_reps")
    socks = [str(d / f"rep{i}.sock") for i in range(2)]
    servers = [PolishServer(socket_path=s, workers=2).start()
               for s in socks]
    yield socks
    for srv in servers:
        srv.drain(timeout=10)


def _wait_routable(cli: PolishClient, want: int, deadline_s: float = 30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with contextlib.suppress(Exception):
            hz = cli.request({"type": "healthz"})
            if hz.get("routable") == want:
                return hz
        time.sleep(0.1)
    raise AssertionError(f"router never reached routable == {want}")


# ------------------------------------------------------------ clock sync
class _SkewedPingServer:
    """Frame-protocol stub whose pong reports a mono clock shifted by
    `skew_s` from this process's perf_counter — a replica on another
    host, seen over a localhost-fast link."""

    def __init__(self, sock_path: str, skew_s: float):
        self.skew_s = skew_s
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(4)
        self._lst.settimeout(0.2)
        self.path = sock_path
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                while True:
                    req = recv_frame(conn)
                    if req is None:
                        break
                    if req.get("type") == "ping":
                        send_frame(conn, {
                            "type": "pong",
                            "mono_s": time.perf_counter() + self.skew_s})
                    else:
                        send_frame(conn, {"type": "ok"})
            except (OSError, ProtocolError):
                pass
            finally:
                with contextlib.suppress(OSError):
                    conn.close()

    def close(self):
        self._stop.set()
        with contextlib.suppress(OSError):
            self._lst.close()


@pytest.mark.parametrize("skew_s", [0.05, -0.05])
def test_clock_sync_recovers_injected_skew(skew_s, tmp_path):
    """offset_s must land within the min-RTT bracket of the true skew:
    both clocks are THIS process's perf_counter, so the injected shift
    is exactly the offset clock_sync should estimate."""
    stub = _SkewedPingServer(str(tmp_path / "skew.sock"), skew_s)
    try:
        cl = PolishClient(socket_path=stub.path)
        clock = cl.clock_sync(samples=5)
        assert clock["rtt_s"] > 0
        # rtt/2 is the claimed accuracy; a small epsilon absorbs the
        # perf_counter reads between the skew injection and the pong
        assert abs(clock["offset_s"] - skew_s) <= \
            clock["rtt_s"] / 2.0 + 0.005
    finally:
        stub.close()


def test_clock_sync_requires_mono_sample(tmp_path):
    """A pre-tracing server (pong without mono_s) answers clock_sync
    with a typed error, not a silent zero offset."""
    class _Bare(_SkewedPingServer):
        def _loop(self):
            while not self._stop.is_set():
                try:
                    conn, _ = self._lst.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                with contextlib.suppress(OSError, ProtocolError):
                    while True:
                        req = recv_frame(conn)
                        if req is None:
                            break
                        send_frame(conn, {"type": "pong"})
                with contextlib.suppress(OSError):
                    conn.close()

    stub = _Bare(str(tmp_path / "bare.sock"), 0.0)
    try:
        with pytest.raises(ServeError) as exc_info:
            PolishClient(socket_path=stub.path).clock_sync()
        assert "mono_s" in str(exc_info.value)
    finally:
        stub.close()


# --------------------------------------------------------------- rebase
def test_rebase_events_keeps_colliding_tracks_distinct():
    """Two replicas emit events with IDENTICAL thread tids and names
    (every PolishServer numbers its workers from zero) — rebasing onto
    pids 3 and 4 must keep each set on its own process track."""
    def replica_events():
        return [
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 7,
             "args": {"name": "worker-0"}},
            {"name": "serve.iteration", "ph": "X", "pid": 0, "tid": 7,
             "ts": 10.0, "dur": 5.0, "args": {"trace_ids": ["t.s0"]}},
            {"name": "serve.queue_wait", "ph": "X", "pid": 0, "tid": 7,
             "ts": 2.0, "dur": 1.0, "args": {"trace_id": "t.s0"}},
        ]

    a_src, b_src = replica_events(), replica_events()
    a = rebase_events(a_src, pid=3, shift_us=100.0, name="replica a")
    b = rebase_events(b_src, pid=4, shift_us=200.0, name="replica b")
    # every event landed on its OWN pid — no cross-track bleed
    assert {ev["pid"] for ev in a} == {3}
    assert {ev["pid"] for ev in b} == {4}
    # process_name metadata labels each track
    for evs, pid, label in ((a, 3, "replica a"), (b, 4, "replica b")):
        metas = [ev for ev in evs if ev["ph"] == "M"
                 and ev["name"] == "process_name"]
        assert len(metas) == 1 and metas[0]["pid"] == pid
        assert metas[0]["args"]["name"] == label
    # spans shifted onto their own timelines; thread metadata keeps its
    # timestampless shape (the tid collision is fine ACROSS pids —
    # Chrome tracks are keyed (pid, tid))
    span_a = next(ev for ev in a if ev["name"] == "serve.iteration")
    span_b = next(ev for ev in b if ev["name"] == "serve.iteration")
    assert span_a["ts"] == 110.0 and span_b["ts"] == 210.0
    assert span_a["tid"] == span_b["tid"] == 7
    assert all("ts" not in ev for ev in a if ev["ph"] == "M")
    # inputs were not mutated
    assert a_src[1]["pid"] == 0 and a_src[1]["ts"] == 10.0


# ------------------------------------------------------ routed trace pins
def _track_names(doc: dict) -> dict[int, str]:
    return {ev["pid"]: ev["args"]["name"]
            for ev in doc["traceEvents"]
            if ev.get("ph") == "M" and ev.get("name") == "process_name"}


def _assert_report_green(doc: dict, path: str):
    """The tracereport acceptance core: per-stage attribution sums to
    the span wall exactly, every consistency check passes, and the CLI
    exits 0 under --check."""
    rep = tracereport.analyze(doc)
    assert rep["routed"]
    assert sum(rep["stages"].values()) == pytest.approx(
        rep["wall_s"], abs=1e-6)
    eps = 2.0 * rep["bracket_s"] + 1e-3
    for name, v in rep["stages"].items():
        assert v >= -eps, f"stage {name} = {v}"
    assert tracereport.check(doc, rep) == []
    assert tracereport.main([path, "--check"]) == 0


def test_routed_trace_unix_contig_sharded(dataset4, solo4,
                                          trace_replicas, tmp_path):
    """The acceptance gate over unix sockets: a 2-replica contig-
    sharded traced job yields ONE merged Chrome-trace JSON with
    router + both replica + client tracks on a common clock, and
    tracereport's critical path + attribution come out green."""
    router = PolishRouter(replicas=",".join(trace_replicas),
                          socket_path=str(tmp_path / "r.sock"),
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        path = str(tmp_path / "merged.json")
        result, doc = cli.submit_traced(*dataset4, trace_out=path,
                                        tenant="acme")
        assert result.fasta == solo4
        assert json.load(open(path)) == doc
        # tracks: client(1), router(2), one process per replica (3+)
        names = _track_names(doc)
        assert "client" in names[1] and "router" in names[2]
        rep_pids = [p for p, n in names.items() if "replica" in n]
        assert len(rep_pids) == 2
        for spec in trace_replicas:
            assert any(spec in names[p] for p in rep_pids)
        # every replica track really carries serve-side spans
        for p in rep_pids:
            have = {ev["name"] for ev in doc["traceEvents"]
                    if ev.get("pid") == p and ev.get("ph") == "X"}
            assert "serve.job" in have and "serve.iteration" in have
        # per-replica clock metadata rode into the context
        ctx = doc["trace_context"]
        assert len(ctx["replicas"]) == 2
        assert all(r["rtt_s"] >= 0 for r in ctx["replicas"])
        assert ctx["stats"]["router"]["shards"] == 2
        assert len(ctx["stats"]["router"]["shards_detail"]) == 2
        _assert_report_green(doc, path)
    finally:
        router.drain()


def test_routed_trace_tcp_range_sharded(dataset1, solo1, tmp_path):
    """The same gate over localhost TCP with sub-contig RANGE sharding
    (one contig across two replicas): distinct tracks, green report."""
    servers = [PolishServer(port=0, workers=2).start() for _ in range(2)]
    specs = [f"127.0.0.1:{s.config.port}" for s in servers]
    router = PolishRouter(replicas=",".join(specs), port=0,
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(port=router.config.port)
        _wait_routable(cli, 2)
        path = str(tmp_path / "merged_tcp.json")
        result, doc = cli.submit_traced(*dataset1, trace_out=path)
        assert result.fasta == solo1
        assert result.router["range"] is True
        assert result.router["range_shards"] == 2
        names = _track_names(doc)
        rep_pids = [p for p, n in names.items() if "replica" in n]
        assert len(rep_pids) == 2
        assert {names[p] for p in rep_pids} == \
            {f"racon_tpu replica {s}" for s in specs}
        _assert_report_green(doc, path)
    finally:
        router.drain()
        for s in servers:
            s.drain(timeout=10)


class _DyingTracedReplica:
    """Protocol-complete fake replica that streams its shard's TRUE
    first polished contig and then drops the connection — the
    deterministic kill -9 shape (tests/test_router.py). It never
    COMPLETES a shard, so the router's per-owner trace pull must never
    ask it for spans (it has no flight ring to answer with)."""

    def __init__(self, sock_path: str, polished_records: dict):
        self.path = sock_path
        self.polished = polished_records
        self.submits = 0
        self._stop = threading.Event()
        self._lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._lst.bind(sock_path)
        self._lst.listen(8)
        self._lst.settimeout(0.2)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                req = recv_frame(conn)
                if req is None:
                    return
                rtype = req.get("type")
                if rtype == "healthz":
                    send_frame(conn, {"type": "healthz", "ok": True,
                                      "draining": False})
                elif rtype == "scrape":
                    send_frame(conn, {"type": "metrics", "text": ""})
                elif rtype == "ping":
                    send_frame(conn, {"type": "pong"})
                elif rtype == "submit":
                    self.submits += 1
                    from racon_tpu.io.parsers import \
                        create_sequence_parser
                    contigs: list = []
                    create_sequence_parser(req["target"],
                                           "test").parse(contigs, -1)
                    name = contigs[0].name
                    send_frame(conn, {"type": "result_part",
                                      "job_id": "stub", "part": 0,
                                      "name": name,
                                      "fasta": self.polished[name]})
                    with contextlib.suppress(OSError):
                        conn.shutdown(socket.SHUT_RDWR)
                    return
                else:
                    send_frame(conn, {"type": "ok"})
        except (OSError, ProtocolError):
            return
        finally:
            with contextlib.suppress(OSError):
                conn.close()

    def close(self):
        self._stop.set()
        with contextlib.suppress(OSError):
            self._lst.close()


def _records_by_name(fasta: bytes) -> dict:
    out = {}
    for chunk in fasta.split(b">")[1:]:
        header, _, _body = chunk.partition(b"\n")
        out[header.split()[0].decode()] = (b">" + chunk).decode("latin-1")
    return out


def test_routed_trace_with_requeue_injected(dataset4, solo4,
                                            trace_replicas, tmp_path):
    """The failover x tracing composition: a shard's replica dies after
    one streamed part, the shard requeues to a survivor — the merged
    artifact records the router.requeue instant, carries NO spans from
    the lost attempt (per-owner pulls), and tracereport still sums the
    attribution to the wall with every check green."""
    stub = _DyingTracedReplica(str(tmp_path / "stub.sock"),
                               _records_by_name(solo4))
    journal = str(tmp_path / "router.jsonl")
    router = PolishRouter(
        replicas=f"{stub.path},{trace_replicas[0]}",
        socket_path=str(tmp_path / "r.sock"), journal=journal,
        health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        path = str(tmp_path / "merged_requeue.json")
        result, doc = cli.submit_traced(*dataset4, trace_out=path)
        assert result.fasta == solo4
        assert result.router["requeues"] >= 1
        assert stub.submits >= 1  # the dying replica really got a shard
        # the requeue is a first-class instant on the router track
        requeues = [ev for ev in doc["traceEvents"]
                    if ev.get("name") == "router.requeue"]
        assert len(requeues) == result.router["requeues"]
        # only the SURVIVOR contributed a replica track: the stub never
        # completed a shard, so the per-owner pull skipped it
        names = _track_names(doc)
        rep_names = [n for n in names.values() if "replica" in n]
        assert rep_names == [f"racon_tpu replica {trace_replicas[0]}"]
        _assert_report_green(doc, path)
    finally:
        router.drain()
        stub.close()
    entries = read_journal(journal)
    events = [e["event"] for e in entries]
    assert "replica-down" in events and "requeued" in events
    assert check_consistency(entries) == []


# --------------------------------------------- tenant device accounting
def test_tenant_device_seconds_federate_across_fleet(dataset1,
                                                     tmp_path):
    """The cost-accounting pin: per-replica tenant buckets sum to that
    replica's total lane busy seconds, and the labeled counter
    federates through FleetAggregator with the fleet sum equal to the
    sum of the replica totals."""
    socks = [str(tmp_path / f"acct{i}.sock") for i in range(2)]
    servers = [PolishServer(socket_path=s, workers=2,
                            warmup=False).start() for s in socks]
    try:
        PolishClient(socket_path=socks[0]).submit(*dataset1,
                                                  tenant="gold")
        PolishClient(socket_path=socks[1]).submit(*dataset1,
                                                  tenant="blue")
        PolishClient(socket_path=socks[1]).submit(*dataset1)  # untenanted
        totals = []
        for s in socks:
            b = PolishClient(socket_path=s).stats()["batcher"]
            buckets = b["tenant_device_s"]
            lane_busy = sum(l["busy_s"] for l in b["lanes"])
            # proration invariant: the buckets partition lane busy time
            assert sum(buckets.values()) == pytest.approx(
                lane_busy, abs=2e-3)
            totals.append(sum(buckets.values()))
        # the "" bucket rides along only where untenanted traffic ran
        b1 = PolishClient(socket_path=socks[1]).stats()["batcher"]
        assert "" in b1["tenant_device_s"]

        snap = FleetAggregator(endpoints=socks).poll()
        assert snap.healthy
        series = snap.counter_series[TENANT_FAMILY]
        by_tenant = {lbl["tenant"]: v for _, (lbl, v) in series.items()}
        assert by_tenant["gold"] > 0 and by_tenant["blue"] > 0
        assert sum(by_tenant.values()) == pytest.approx(
            sum(totals), abs=2e-3)
        # the federated scrape body renders the labeled family too
        agg = FleetAggregator(endpoints=socks)
        agg.poll()
        assert TENANT_FAMILY + '{tenant="gold"}' in agg.prometheus_text()
    finally:
        for srv in servers:
            srv.drain(timeout=10)


def test_flagless_routed_job_has_no_trace_surface(dataset1, solo1,
                                                  tmp_path):
    """The byte-identity discipline: with no --trace-out and no tenant,
    the routed response frame carries none of the trace-plane keys and
    the replica scrape has no tenant device-seconds family."""
    socks = [str(tmp_path / f"plain{i}.sock") for i in range(2)]
    servers = [PolishServer(socket_path=s, workers=2,
                            warmup=False).start() for s in socks]
    router = PolishRouter(replicas=",".join(socks),
                          socket_path=str(tmp_path / "r.sock"),
                          health_interval_s=0.2).start()
    try:
        cli = PolishClient(socket_path=router.config.socket_path)
        _wait_routable(cli, 2)
        raw = cli.request({"type": "submit",
                           "sequences": dataset1[0],
                           "overlaps": dataset1[1],
                           "target": dataset1[2]})
        assert raw["fasta"].encode("latin-1") == solo1
        assert "trace" not in raw
        assert "trace_replicas" not in raw
        assert "trace_base_mono" not in raw
        assert "shards_detail" not in raw["router"]
        for s in socks:
            text = PolishClient(socket_path=s).scrape()
            assert "tenant_device_seconds" not in text
    finally:
        router.drain()
        for srv in servers:
            srv.drain(timeout=10)
