"""Tests for the wrapper / rampler / preprocess tooling (the reference's
scripts/ layer, SURVEY.md §2a Wrapper/Preprocess + §2b rampler)."""

import io
import os

import pytest

from racon_tpu import preprocess, rampler
from racon_tpu.io.parsers import create_sequence_parser

DATA = "/root/reference/test/data/"

needs_data = pytest.mark.skipif(not os.path.isdir(DATA),
                                reason="sample data missing")


def _load(path):
    seqs = []
    create_sequence_parser(path, "test").parse(seqs, -1)
    return seqs


def write_fasta(path, records):
    with open(path, "wb") as f:
        for name, data in records:
            f.write(b">" + name + b"\n" + data + b"\n")


def test_rampler_split(tmp_path):
    src = tmp_path / "tgt.fasta"
    write_fasta(src, [(b"a", b"A" * 600), (b"b", b"C" * 600),
                      (b"c", b"G" * 600), (b"d", b"T" * 100)])
    parts = rampler.split(str(src), 1000, str(tmp_path))
    assert [os.path.basename(p) for p in parts] == \
        ["tgt_0.fasta", "tgt_1.fasta", "tgt_2.fasta"]
    sizes = [[len(s.data) for s in _load(p)] for p in parts]
    assert sizes == [[600], [600], [600, 100]]


def test_rampler_split_rejects_bad_chunk(tmp_path):
    src = tmp_path / "tgt.fasta"
    write_fasta(src, [(b"a", b"ACGT")])
    from racon_tpu.errors import RaconError
    with pytest.raises(RaconError):
        rampler.split(str(src), 0, str(tmp_path))


def test_rampler_subsample(tmp_path):
    src = tmp_path / "reads.fasta"
    write_fasta(src, [(str(i).encode(), b"ACGT" * 100) for i in range(50)])
    out = rampler.subsample(str(src), 1000, 4, str(tmp_path))
    assert os.path.basename(out) == "reads_4x.fasta"
    seqs = _load(out)
    total = sum(len(s.data) for s in seqs)
    # stops once >= ref_len * coverage
    assert 4000 <= total < 4000 + 400
    # no duplicates
    assert len({s.name for s in seqs}) == len(seqs)


def test_rampler_subsample_seed_deterministic(tmp_path, monkeypatch):
    """Seeded subsample (ISSUE 20 satellite): the same explicit seed
    always picks the same reads; different seeds pick differently; the
    env knob is honoured when no explicit seed is given; a typo'd env
    value is a hard error, not a silently random sample."""
    from racon_tpu.errors import RaconError

    src = tmp_path / "reads.fasta"
    write_fasta(src, [(str(i).encode(), b"ACGT" * 100)
                      for i in range(50)])

    def pick(dirname, **kw):
        os.makedirs(dirname, exist_ok=True)
        out = rampler.subsample(str(src), 1000, 4, str(dirname), **kw)
        return [s.name for s in _load(out)]

    assert pick(tmp_path / "a", seed=7) == pick(tmp_path / "b", seed=7)
    assert pick(tmp_path / "a2", seed=7) != pick(tmp_path / "c", seed=8)
    # env knob drives the default; explicit seed still wins over it
    monkeypatch.setenv("RACON_TPU_SUBSAMPLE_SEED", "7")
    assert pick(tmp_path / "d") == pick(tmp_path / "a3", seed=7)
    monkeypatch.setenv("RACON_TPU_SUBSAMPLE_SEED", "lucky")
    with pytest.raises(RaconError):
        pick(tmp_path / "e")
    assert pick(tmp_path / "f", seed=7) == pick(tmp_path / "a4", seed=7)
    # unseeded runs stay deterministic too (the fixed default)
    monkeypatch.delenv("RACON_TPU_SUBSAMPLE_SEED")
    assert pick(tmp_path / "g") == pick(tmp_path / "h")
    # coverage math is seed-independent: every pick stops at the same
    # >= ref_len * coverage budget
    for sub in (tmp_path / "a", tmp_path / "c", tmp_path / "g"):
        total = sum(len(s.data)
                    for s in _load(str(sub / "reads_4x.fasta")))
        assert 4000 <= total < 4000 + 400


def test_preprocess_uniquifies_pairs(tmp_path):
    fq = tmp_path / "pairs.fastq"
    fq.write_bytes(b"@r1 x\nACGT\n+\nIIII\n@r1 y\nTTTT\n+\nIIII\n"
                   b"@r2\nGGGG\n+\nIIII\n")
    buf = io.BytesIO()
    preprocess.process([str(fq)], out=buf)
    lines = buf.getvalue().split(b"\n")
    assert lines[0] == b"@r11" and lines[4] == b"@r12" and lines[8] == b"@r21"


@needs_data
def test_wrapper_split_run_matches_whole(tmp_path):
    """Polishing through the wrapper with --split must produce the same
    single contig as the plain CLI (one target => one chunk per split of
    its bytes; sample layout is one contig so split larger than it)."""
    out = io.BytesIO()
    from racon_tpu.wrapper import run
    run(DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.sam.gz",
        DATA + "sample_layout.fasta.gz", split=10_000_000, threads=2,
        out=out)
    seqs = out.getvalue()
    assert seqs.count(b">") == 1
    assert seqs.startswith(b">utg000001l")


@needs_data
def test_wrapper_shards_concatenate_to_unsharded(tmp_path):
    """Multi-host file-level scatter/gather (SURVEY.md §5): polishing the
    same --split workload as 2 shards and concatenating the outputs in
    shard order must reproduce the unsharded run byte-for-byte.

    The sample layout is a single contig (rampler never splits
    mid-sequence), so the multi-chunk workload is synthesized: four
    contigs sliced from the real lambda layout, reads sliced from each
    contig with exact PAF overlaps."""
    import random

    from racon_tpu.wrapper import run

    layout = _load(DATA + "sample_layout.fasta.gz")[0].data
    rng = random.Random(3)
    contigs, reads, paf = [], [], []
    for c in range(4):
        tig = layout[c * 9000:(c + 1) * 9000]
        name = f"tig{c}".encode()
        contigs.append((name, tig))
        for r in range(12):
            beg = rng.randrange(0, len(tig) - 2000)
            end = beg + 2000
            rname = f"read{c}_{r}".encode()
            reads.append((rname, tig[beg:end]))
            paf.append(f"read{c}_{r}\t2000\t0\t2000\t+\t{name.decode()}\t"
                       f"{len(tig)}\t{beg}\t{end}\t2000\t2000\t255")
    tgt = tmp_path / "tigs.fasta"
    rds = tmp_path / "reads.fasta"
    ovl = tmp_path / "ovl.paf"
    write_fasta(tgt, contigs)
    write_fasta(rds, reads)
    ovl.write_text("\n".join(paf) + "\n")

    def polish(num_shards=1, shard_id=0):
        out = io.BytesIO()
        run(str(rds), str(ovl), str(tgt), split=9_500, threads=2,
            num_shards=num_shards, shard_id=shard_id, out=out)
        return out.getvalue()

    # the split geometry itself: four one-contig chunks to scatter
    assert len(rampler.split(str(tgt), 9_500, str(tmp_path))) == 4

    whole = polish()
    assert whole.count(b">") == 4
    sharded = polish(2, 0) + polish(2, 1)
    assert sharded == whole


@needs_data
def test_wrapper_shards_across_processes(tmp_path):
    """The multi-host dress rehearsal (round-4 verdict #10): the same
    scatter/gather as test_wrapper_shards_concatenate_to_unsharded, but
    each shard runs in its OWN OS process through the real CLI entry
    (`python -m racon_tpu.wrapper`) — the way two hosts would actually
    run it, DCN being a shared filesystem here. Concatenating the two
    processes' stdout in shard order must reproduce a third, unsharded
    process's stdout byte-for-byte."""
    import random
    import subprocess
    import sys as _sys

    layout = _load(DATA + "sample_layout.fasta.gz")[0].data
    rng = random.Random(3)
    contigs, reads, paf = [], [], []
    for c in range(4):
        tig = layout[c * 9000:(c + 1) * 9000]
        name = f"tig{c}".encode()
        contigs.append((name, tig))
        for r in range(12):
            beg = rng.randrange(0, len(tig) - 2000)
            end = beg + 2000
            rname = f"read{c}_{r}".encode()
            reads.append((rname, tig[beg:end]))
            paf.append(f"read{c}_{r}\t2000\t0\t2000\t+\t{name.decode()}\t"
                       f"{len(tig)}\t{beg}\t{end}\t2000\t2000\t255")
    tgt = tmp_path / "tigs.fasta"
    rds = tmp_path / "reads.fasta"
    ovl = tmp_path / "ovl.paf"
    write_fasta(tgt, contigs)
    write_fasta(rds, reads)
    ovl.write_text("\n".join(paf) + "\n")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")

    def polish_proc(extra):
        proc = subprocess.run(
            [_sys.executable, "-m", "racon_tpu.wrapper", str(rds),
             str(ovl), str(tgt), "--split", "9500", "-t", "1"] + extra,
            capture_output=True, timeout=300, env=env, cwd=str(tmp_path))
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        return proc.stdout

    # the two shard processes run CONCURRENTLY, like real hosts would
    procs = [subprocess.Popen(
        [_sys.executable, "-m", "racon_tpu.wrapper", str(rds), str(ovl),
         str(tgt), "--split", "9500", "-t", "1", "--num-shards", "2",
         "--shard-id", str(s)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=str(tmp_path)) for s in (0, 1)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()[-2000:]
        outs.append(out)

    whole = polish_proc([])
    assert whole.count(b">") == 4
    assert outs[0] + outs[1] == whole


def test_wrapper_shard_validation(tmp_path):
    from racon_tpu.errors import RaconError
    from racon_tpu.wrapper import run

    src = tmp_path / "t.fasta"
    write_fasta(src, [(b"a", b"ACGT" * 50)])
    with pytest.raises(RaconError, match="shard_id"):
        run(str(src), str(src), str(src), num_shards=2, shard_id=5)
    with pytest.raises(RaconError, match="--split"):
        run(str(src), str(src), str(src), num_shards=2, shard_id=0)
