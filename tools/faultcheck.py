"""Fault-matrix checker: the resilience layer's pass/fail grid.

Runs a small synthetic polishing job (mixed read lengths, so the device
aligner has both device chunks and host-fallback work) through every
fault-injection point — pack raise, device raise, device hang, unpack
corrupt, fallback raise — in both the alignment phase (device aligner
armed) and the consensus phase (host engine loop), at pipeline depths 0
and 2 AND with the occupancy-aware batch scheduler armed (depth 2 +
adaptive buckets + sorted packing: a repacked chunk must route through
the same fault hooks), plus a persistent-failure case that must degrade
to the per-window pass. Each cell passes when the injected run

  - exits cleanly (no exception reaches the driver),
  - fired its armed fault (`faults` counter >= 1),
  - and either reproduces the clean run's bytes (the watchdog/retry/
    fallback ladder absorbed the fault) or reports quarantined windows,
  - within a wall-clock bound (hang cases: the watchdog deadline, not
    the injected stall, must set the pace),
  - leaving no orphaned racon-tpu worker thread behind.

A depth2+fused column runs device consensus through the FUSED
single-launch align→window-slice→POA program (RACON_TPU_FUSED=1, fused
engine): faults injected inside the fused dispatch must fall back to
the SPLIT chained path byte-identically — the program's declared
fallback, gated against a split-vs-fused clean-identity check up front.

A 5th SERVE column runs each row's fault as a per-job fault plan against
a live PolishServer (racon_tpu/serve/): the poisoned job must fail with
a TYPED error response (DeviceError / DeviceTimeout / ChunkCorrupt — the
job is submitted strict, so nothing degrades it away), the server must
survive, and the NEXT clean job on the same warm server must reproduce
the clean run's bytes exactly.

An AUDIT section (two gated cells) exercises the identity-audit
sentinel (racon_tpu/obs/audit.py) against the one failure class nothing
above can represent: SILENT data corruption (`device:chunk=N:sdc`, a
wrong-bytes-no-exception flip). A sampled-corruption run (audit rate
1.0) MUST be caught within the iteration — typed `audit-mismatch`
journal event, labeled mismatch counter, online winner-table demotion
on disk, and the job's FASTA repaired back to the clean bytes — while
an unsampled-corruption run (rate 0) documents the miss: the corrupted
bytes ship and no audit event fires. Both cells are gated; together
they pin that detection is real AND that it comes from the sampling,
not from some hidden always-on check.

A RANGE section (one gated cell) exercises window-range sharding: a
single-contig job split by target-coordinate range across two real
replica subprocesses, one killed -9 mid-job — the requeued window range
must complete on the survivor with the reassembled contig
byte-identical to a solo run, the `range-plan`/`requeued` lines on the
ledger, and obsreport's segment-receipt check tiling clean.

A FRAGMENT section (two gated cells) exercises the serve-native
fragment-correction mode (`mode: "fragment"`) and its admit-time ingest
plane: a fragment submit pointing at a poisoned (non-FASTA) reads file
with `ingest` validation armed must fail TYPED (`bad-request`,
`rejected-ingest` on the ledger) while a CONCURRENT contig job on the
same server completes byte-identically — and the warm server's next
clean fragment job reproduces the solo kF bytes; then a fragment job
read-range-sharded across two real replica subprocesses with one
killed -9 mid-job must complete via the requeue byte-identically, the
`frag-plan`/`requeued` lines on the ledger and obsreport's
fragment-receipt check tiling the read axis clean.

A TRACE section (one gated cell) exercises the distributed-trace plane
under the same fault: a TRACED routed job (`submit_traced`) with one
replica killed -9 mid-job must complete byte-identically AND leave a
merged Chrome trace that tells the story straight — the
`router.requeue` instant present, `tools/tracereport.py --check` green
(the per-stage attribution still partitions the wall, the requeue
count still matches the router block), the journal still consistent.

A PREEMPT section (two gated cells) exercises the preemptive-QoS layer:
a gold-priority job preempting a running free job on a one-worker
server (both outputs byte-identical to an undisturbed run, balanced
`preempted`/`resumed` journal pair), and a cancel RPC landing during an
injected `device:hang` (watchdog absorbs the hang, the job fails with
the typed `cancelled` error, the warm server's next clean job is
byte-identical).

Usage: python tools/faultcheck.py [--quick]
  --quick drops the hang cases (the slow rows; the pytest suite tags the
  same cases with the `slow`/`faults` markers so tier-1 skips them too).

Prints the grid and exits 0 only when every cell passed — the CI gate
for the resilience acceptance criteria.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/racon_tpu_jax_cache")
sys.path = [p for p in sys.path if "axon_site" not in p]

ACGT = b"ACGT"

#: (name, aligner_batches, fault spec, watchdog timeout, slow)
MATRIX = [
    ("align pack raise", 1, "pack:chunk=0:raise", 0.0, False),
    ("align device raise", 1, "device:chunk=0:raise", 0.0, False),
    ("align device hang", 1, "device:chunk=0:hang=5", 0.5, True),
    ("align unpack corrupt", 1, "unpack:chunk=0:corrupt", 0.0, False),
    ("align fallback raise", 1, "fallback:chunk=0:raise", 0.0, False),
    ("consensus pack raise", 0, "pack:chunk=0:raise", 0.0, False),
    ("consensus device raise", 0, "device:chunk=0:raise", 0.0, False),
    ("consensus device hang", 0, "device:chunk=0:hang=5", 0.5, True),
    ("consensus unpack corrupt", 0, "unpack:chunk=0:corrupt", 0.0, False),
    ("consensus device persistent", 0,
     "device:chunk=0:raise,device:chunk=0:raise", 0.0, False),
]

WALL_CAP = 120.0  # hard per-cell budget; a wedged run fails, not hangs CI


def make_dataset(dirname: str, rng: random.Random):
    truth = bytes(rng.choice(ACGT) for _ in range(2000))

    def mutate(s, rate):
        out = bytearray()
        for c in s:
            r = rng.random()
            if r < rate / 3:
                continue
            if r < 2 * rate / 3:
                out.append(rng.choice(ACGT))
                out.append(c)
                continue
            if r < rate:
                out.append(rng.choice(ACGT))
                continue
            out.append(c)
        return bytes(out)

    draft = mutate(truth, 0.04)
    jobs = [(start, 400) for start in range(0, len(truth) - 400, 100)]
    jobs += [(0, 1300), (600, 1300)]  # overlength: host-fallback pairs
    reads, paf = [], []
    for k, (start, read_len) in enumerate(jobs):
        read = mutate(truth[start:start + read_len], 0.05)
        reads.append((f"r{k}", read))
        t_end = min(start + read_len, len(draft))
        paf.append(f"r{k}\t{len(read)}\t0\t{len(read)}\t+\tdraft\t"
                   f"{len(draft)}\t{start}\t{t_end}\t{read_len}\t"
                   f"{read_len}\t60")
    paths = (os.path.join(dirname, "reads.fasta.gz"),
             os.path.join(dirname, "ovl.paf.gz"),
             os.path.join(dirname, "draft.fasta.gz"))
    with gzip.open(paths[0], "wb") as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb") as f:
        f.write(("\n".join(paf) + "\n").encode())
    with gzip.open(paths[2], "wb") as f:
        f.write(b">draft\n" + draft + b"\n")
    return paths


def polish(paths, depth: int, aligner: int, timeout: float,
           adaptive: bool = False, poa: int = 0,
           engine: str | None = None):
    from racon_tpu.core.polisher import PolisherType, create_polisher

    p = create_polisher(*paths, PolisherType.kC, 500, -1.0, 0.3,
                        num_threads=2, tpu_aligner_batches=aligner,
                        tpu_poa_batches=poa, tpu_engine=engine,
                        tpu_pipeline_depth=depth,
                        tpu_device_timeout=timeout,
                        tpu_adaptive_buckets=adaptive)
    p.initialize()
    out = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                   for s in p.polish())
    return out, p.stage_stats


def orphans(grace: float = 3.0) -> list[str]:
    # racon-tpu-serve-* threads are the live job server's own pool
    # (the serve column keeps one server up across the whole grid) —
    # deliberately long-lived, not orphans of an injected run
    deadline = time.perf_counter() + grace
    while time.perf_counter() < deadline:
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("racon-tpu")
                 and not t.name.startswith("racon-tpu-serve")]
        if not alive:
            return []
        time.sleep(0.05)
    return alive


def validate_trace(trace_path, stats):
    """The trace-validation gate: the injected run's trace must be valid
    Chrome trace-event JSON whose resilience instant events match the
    run's degradation counters. Returns None when OK, else a failure
    string."""
    import json

    try:
        with open(trace_path) as fh:
            doc = json.load(fh)
        events = doc["traceEvents"]
    except Exception as exc:
        return f"FAIL trace unparseable ({type(exc).__name__}: {exc})"
    for ev in events:
        for field in ("name", "ph", "pid", "tid"):
            if field not in ev:
                return f"FAIL trace event missing {field!r}"
        if ev["ph"] == "X" and (ev["dur"] < 0 or ev["ts"] < 0):
            return "FAIL trace span with negative ts/dur"
    for key in ("faults", "quarantined"):
        seen = sum(ev.get("args", {}).get("n", 1) for ev in events
                   if ev["name"] == f"resilience.{key}")
        if seen != stats[key]:
            return (f"FAIL trace {key} events {seen} != "
                    f"counter {stats[key]}")
    return None


def run_cell(paths, clean, depth, aligner, spec, timeout,
             adaptive=False, trace=False, pallas=False, fused=False):
    trace_path = None
    if trace:
        fd, trace_path = tempfile.mkstemp(suffix=".json",
                                          prefix="racon_trace_")
        os.close(fd)
    try:
        return _run_cell(paths, clean, depth, aligner, spec, timeout,
                         adaptive, trace_path, pallas, fused)
    finally:
        if trace_path is not None:
            try:
                os.unlink(trace_path)
            except OSError:
                pass


def _run_cell(paths, clean, depth, aligner, spec, timeout,
              adaptive, trace_path, pallas=False, fused=False):
    from racon_tpu.obs import trace as obs_trace
    from racon_tpu.resilience.faults import reset_fault_plan

    trace = trace_path is not None
    os.environ["RACON_TPU_FAULT_PLAN"] = spec
    os.environ["RACON_TPU_DEVICE_RETRIES"] = "1"
    os.environ["RACON_TPU_RETRY_BACKOFF"] = "0.01"
    if pallas:
        # the Pallas kernel plane (interpret mode on this CPU backend):
        # injected faults must quarantine / fall back exactly like the
        # XLA chunks — the fault hooks live at the pipeline layer, so a
        # Pallas-dispatched chunk routes through the identical ladder
        os.environ["RACON_TPU_PALLAS"] = "1"
    if fused:
        # the fused single-launch program (device consensus armed with
        # the fused engine): a fault inside the fused dispatch must
        # fall back to the SPLIT chained path byte-identically — the
        # declared fallback — before anything reaches the host tail
        os.environ["RACON_TPU_FUSED"] = "1"
    reset_fault_plan()
    if trace:
        obs_trace.configure(trace_path)
    t0 = time.perf_counter()
    try:
        out, stats = polish(paths, depth, aligner, timeout, adaptive,
                            poa=1 if fused else 0,
                            engine="fused" if fused else None)
    except Exception as exc:
        return f"FAIL crashed ({type(exc).__name__}: {exc})"
    finally:
        wall = time.perf_counter() - t0
        os.environ.pop("RACON_TPU_FAULT_PLAN", None)
        os.environ.pop("RACON_TPU_PALLAS", None)
        os.environ.pop("RACON_TPU_FUSED", None)
        reset_fault_plan()
        if trace:
            try:
                obs_trace.save(trace_path)
            finally:
                obs_trace.reset()
    if wall > WALL_CAP:
        return f"FAIL over budget ({wall:.0f}s)"
    if stats["faults"] < 1:
        return "FAIL fault never fired"
    left = orphans()
    if left:
        return f"FAIL orphaned threads {left}"
    traced = ""
    if trace:
        bad = validate_trace(trace_path, stats)
        if bad is not None:
            return bad
        traced = " traced"
    expect = clean["fused", aligner] if fused else clean[depth, aligner]
    if out == expect:
        how = "identical"
    elif stats["quarantined"] > 0:
        how = f"quarantined {stats['quarantined']}"
    else:
        return "FAIL output diverged without quarantine"
    extras = [f"{k} {stats[k]}" for k in ("retries", "timeouts")
              if stats[k]]
    return (f"pass  {how}{traced}"
            + (f" ({', '.join(extras)})" if extras else ""))


def run_serve_lanes_cell(client, paths, clean, aligner, spec, timeout):
    """One serve-lanes2 cell: the row's fault as a per-job strict plan
    against the shared --worker-lanes 2 server, CONCURRENT with a clean
    job. Isolation jobs run solo on one lane, so the injected fault may
    fail only the poisoned job (typed) while the clean job on the other
    lane(s) returns bytes identical to the clean run."""
    from racon_tpu.serve.client import JobFailed, ServeError

    os.environ["RACON_TPU_DEVICE_RETRIES"] = "0"
    opts = {"tpu_aligner_batches": aligner}
    if timeout:
        opts["tpu_device_timeout"] = timeout
    clean_result: dict = {}

    def clean_job():
        try:
            clean_result["resp"] = client.submit(
                *paths, options={"tpu_aligner_batches": aligner},
                retries=3)
        except Exception as exc:  # noqa: BLE001 — checked below
            clean_result["exc"] = exc

    t = threading.Thread(target=clean_job)
    t.start()
    t0 = time.perf_counter()
    try:
        client.submit(*paths, fault_plan=spec, strict=True, options=opts)
        t.join(WALL_CAP)
        return "FAIL poisoned job succeeded"
    except JobFailed as exc:
        etype = exc.error_type
        if etype not in ("DeviceError", "DeviceTimeout", "ChunkCorrupt"):
            t.join(WALL_CAP)
            return f"FAIL untyped failure ({etype})"
    except ServeError as exc:
        t.join(WALL_CAP)
        return f"FAIL {exc.code}: {exc}"
    except Exception as exc:
        t.join(WALL_CAP)
        return f"FAIL {type(exc).__name__}: {exc}"
    if time.perf_counter() - t0 > WALL_CAP:
        return f"FAIL over budget ({time.perf_counter() - t0:.0f}s)"
    t.join(WALL_CAP)
    if "exc" in clean_result:
        return (f"FAIL concurrent clean job died "
                f"({type(clean_result['exc']).__name__}: "
                f"{clean_result['exc']})")
    if "resp" not in clean_result:
        return "FAIL concurrent clean job never finished"
    if clean_result["resp"].fasta != clean[2, aligner]:
        return "FAIL concurrent clean job diverged"
    return f"pass  {etype}, clean lane identical"


def run_serve_cell(client, paths, clean, aligner, spec, timeout):
    """One serve-column cell: the row's fault as a per-job plan, strict,
    against the shared live server (see module docstring)."""
    from racon_tpu.serve.client import JobFailed, ServeError

    # the poisoned job must actually FAIL: no watchdog retry may absorb
    # its one-shot fault (other columns set RETRIES=1; per-job faults
    # are parsed fresh per submit, so only the retry knob leaks)
    os.environ["RACON_TPU_DEVICE_RETRIES"] = "0"
    opts = {"tpu_aligner_batches": aligner}
    if timeout:
        opts["tpu_device_timeout"] = timeout
    t0 = time.perf_counter()
    try:
        client.submit(*paths, fault_plan=spec, strict=True, options=opts)
        return "FAIL poisoned job succeeded"
    except JobFailed as exc:
        if exc.error_type not in ("DeviceError", "DeviceTimeout",
                                  "ChunkCorrupt"):
            return f"FAIL untyped failure ({exc.error_type})"
        etype = exc.error_type
    except ServeError as exc:
        return f"FAIL {exc.code}: {exc}"
    except Exception as exc:
        return f"FAIL {type(exc).__name__}: {exc}"
    if time.perf_counter() - t0 > WALL_CAP:
        return f"FAIL over budget ({time.perf_counter() - t0:.0f}s)"
    try:
        after = client.submit(*paths,
                              options={"tpu_aligner_batches": aligner})
    except Exception as exc:
        return f"FAIL server did not survive ({type(exc).__name__}: {exc})"
    if after.fasta != clean[2, aligner]:
        return "FAIL clean job after fault diverged"
    return f"pass  {etype}, next clean"


def run_audit_cells(tmp: str, paths) -> list[tuple[str, str]]:
    """The identity-audit sentinel section (module docstring): one
    server with audit rate 1.0, a planted autotuner winner table, a
    journal and a flight dir; a silent `sdc` corruption must be caught
    (and repaired, and demoted) when sampled, and must ship (with no
    audit events) when unsampled."""
    from racon_tpu.obs.journal import read_journal
    from racon_tpu.sched.autotune import Autotuner, reset_autotuner_cache
    from racon_tpu.serve import PolishClient, PolishServer

    cells: list[tuple[str, str]] = []
    at_path = os.path.join(tmp, "audit_autotune.json")
    prev_cache = os.environ.get("RACON_TPU_AUTOTUNE_CACHE")
    os.environ["RACON_TPU_AUTOTUNE_CACHE"] = at_path
    reset_autotuner_cache()
    try:
        # plant an aggressive session winner so the online demotion has
        # a concrete persisted entry to veto
        at = Autotuner(at_path)
        at.record("session", (64, 128), (3, -5, -4, 8),
                  {"kernel": "pallas", "dtype": "int16", "ms": {},
                   "identical": True})
        at.save()
        reset_autotuner_cache()
        sock = os.path.join(tmp, "audit.sock")
        journal = os.path.join(tmp, "audit_journal.jsonl")
        server = PolishServer(socket_path=sock, workers=1,
                              warmup=False, quality_threshold=-1.0,
                              audit_rate=1.0, journal=journal,
                              flight_dir=os.path.join(tmp, "audit_fl"))
        server.start()
        client = PolishClient(socket_path=sock)
        # small windows keep the device-session oracle compiles cheap
        opts = {"tpu_poa_batches": 1, "window_length": 100}
        try:
            clean = client.submit(*paths, options=opts).fasta
            bad = client.submit(*paths, options=opts,
                                fault_plan="device:chunk=1:sdc").fasta
            snap = server.auditor.snapshot()
            events = [e for e in read_journal(journal)
                      if e.get("event") == "audit-mismatch"]
            table = Autotuner(at_path).table
            demoted_on_disk = any(e.get("demoted") for e in
                                  table.values()
                                  if isinstance(e, dict))
            checks = [("repaired", bad == clean),
                      ("journal", len(events) >= 1),
                      ("counter", snap["mismatches"] >= 1),
                      ("demoted", snap["demotions"] >= 1
                       and demoted_on_disk)]
            failed = [n for n, ok in checks if not ok]
            cells.append((
                "audit sdc sampled",
                f"pass  caught ({snap['mismatches']} mismatches, "
                f"{snap['demotions']} demotions, FASTA identical)"
                if not failed else f"FAIL {' '.join(failed)}"))
            # unsampled half: the SAME corruption at rate 0 must ship —
            # the miss is the sampling tradeoff, documented and gated
            pre = snap["mismatches"]
            server.auditor.set_rate(0.0)
            missed = client.submit(*paths, options=opts,
                                   fault_plan="device:chunk=1:sdc").fasta
            snap2 = server.auditor.snapshot()
            checks = [("shipped-corrupt", missed != clean),
                      ("no-audit-event", snap2["mismatches"] == pre)]
            failed = [n for n, ok in checks if not ok]
            cells.append((
                "audit sdc unsampled",
                "pass  missed (corruption shipped, no audit event — "
                "the documented sampling tradeoff)"
                if not failed else f"FAIL {' '.join(failed)}"))
        finally:
            server.drain(timeout=30)
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red pair of cells, not a crashed grid
        detail = f"FAIL crashed ({type(exc).__name__}: {exc})"
        while len(cells) < 2:
            cells.append((("audit sdc sampled", "audit sdc unsampled")
                          [len(cells)], detail))
    finally:
        if prev_cache is None:
            os.environ.pop("RACON_TPU_AUTOTUNE_CACHE", None)
        else:
            os.environ["RACON_TPU_AUTOTUNE_CACHE"] = prev_cache
        reset_autotuner_cache()
    return cells


def run_router_cells(tmp: str) -> list[tuple[str, str]]:
    """The replicated-fabric section (serve/router.py): two REAL
    `racon_tpu serve` replica subprocesses behind one in-process
    router, then kill -9 one replica mid-job. The job must complete via
    the journal-backed requeue with FASTA byte-identical to a solo run
    (each contig exactly once), the `requeued` event must be on the
    router's ledger, and a CONCURRENT job sharing the fabric must come
    back undisturbed on the surviving replica."""
    import signal
    import subprocess

    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.obs.journal import read_journal
    from racon_tpu.serve import (PolishClient, PolishRouter,
                                 make_synth_dataset)

    names = ("router kill -9 mid-job", "router survivor concurrent job")
    cells: list[tuple[str, str]] = []
    data_dir = os.path.join(tmp, "router_data")
    os.makedirs(data_dir, exist_ok=True)
    rpaths = make_synth_dataset(data_dir, contigs=4)
    p = create_polisher(*rpaths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    clean = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                     for s in p.polish())
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_DEVICE_RETRIES="1")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [q for q in env.get("PYTHONPATH", "").split(os.pathsep)
           if q and "axon_site" not in q])
    socks = [os.path.join(tmp, f"router_rep{i}.sock") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve", "--socket", s,
         "--workers", "2", "--no-warmup"],
        env=env, stderr=subprocess.DEVNULL) for s in socks]
    router = None
    journal = os.path.join(tmp, "router_journal.jsonl")
    try:
        for s in socks:
            probe = PolishClient(socket_path=s, timeout=30)
            deadline = time.perf_counter() + 90
            while time.perf_counter() < deadline:
                try:
                    probe.request({"type": "ping"})
                    break
                except Exception:  # noqa: BLE001 — still starting
                    time.sleep(0.2)
            else:
                raise RuntimeError(f"replica {s} never came up")
        router = PolishRouter(replicas=",".join(socks),
                              socket_path=os.path.join(
                                  tmp, "router.sock"),
                              journal=journal,
                              health_interval_s=0.5).start()
        client = PolishClient(socket_path=router.config.socket_path)
        # a watchdog-absorbed hang plan (bytes unchanged — the MATRIX
        # hang rows pin that) keeps every shard busy long enough for
        # the kill to land genuinely mid-job
        slow = {"fault_plan": "device:chunk=0:hang=8",
                "options": {"tpu_device_timeout": 2.0}}
        main_res: dict = {}
        side_res: dict = {}

        def run_job(out: dict):
            mine = PolishClient(socket_path=router.config.socket_path)
            try:
                out["fasta"] = mine.submit(*rpaths, stream=True,
                                           **slow).fasta
            except Exception as exc:  # noqa: BLE001 — checked below
                out["exc"] = exc

        t_main = threading.Thread(target=run_job, args=(main_res,))
        t_side = threading.Thread(target=run_job, args=(side_res,))
        t_main.start()
        t_side.start()
        time.sleep(1.0)  # shards dispatched and stalled on chunk 0
        procs[0].send_signal(signal.SIGKILL)  # the real kill -9
        t_main.join(WALL_CAP)
        t_side.join(WALL_CAP)
        events = [e["event"] for e in read_journal(journal)]
        for name, res, wants_requeue in ((names[0], main_res, True),
                                         (names[1], side_res, False)):
            checks = [("completed", "fasta" in res),
                      ("identical", res.get("fasta") == clean)]
            if wants_requeue:
                checks.append(("requeued-journaled",
                               "requeued" in events
                               and "replica-down" in events))
            failed = [n for n, ok in checks if not ok]
            if "exc" in res:
                failed.append(f"({type(res['exc']).__name__}: "
                              f"{res['exc']})")
            cells.append((name,
                          "pass  " + ("requeued, identical"
                                      if wants_requeue
                                      else "undisturbed, identical")
                          if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red pair of cells, not a crashed grid
        detail = f"FAIL crashed ({type(exc).__name__}: {exc})"
        while len(cells) < 2:
            cells.append((names[len(cells)], detail))
    finally:
        if router is not None:
            router.drain()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
    return cells


def run_range_cells(tmp: str) -> list[tuple[str, str]]:
    """The window-range sharding section (serve/router.py sub-contig
    fan-out): a SINGLE-contig job range-sharded across two REAL
    `racon_tpu serve` replica subprocesses, with one replica killed -9
    mid-job. The requeue must re-run the dead replica's window range on
    the survivor and the reassembled contig must be byte-identical to a
    solo run; the ledger must carry the `range-plan` and `requeued`
    lines, stay lifecycle-consistent, AND pass obsreport's
    segment-receipt tiling check (each accepted segment journaled
    exactly once, covering the window axis with no gap or overlap)."""
    import signal
    import subprocess

    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.obs.journal import check_consistency, read_journal
    from racon_tpu.serve import (PolishClient, PolishRouter,
                                 make_synth_dataset)

    name = "range-shard kill -9 mid-job"
    cells: list[tuple[str, str]] = []
    data_dir = os.path.join(tmp, "range_data")
    os.makedirs(data_dir, exist_ok=True)
    rpaths = make_synth_dataset(data_dir)  # ONE contig: the mega-contig
    p = create_polisher(*rpaths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    clean = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                     for s in p.polish())
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_DEVICE_RETRIES="1")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [q for q in env.get("PYTHONPATH", "").split(os.pathsep)
           if q and "axon_site" not in q])
    socks = [os.path.join(tmp, f"range_rep{i}.sock") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve", "--socket", s,
         "--workers", "2", "--no-warmup"],
        env=env, stderr=subprocess.DEVNULL) for s in socks]
    router = None
    journal = os.path.join(tmp, "range_journal.jsonl")
    try:
        for s in socks:
            probe = PolishClient(socket_path=s, timeout=30)
            deadline = time.perf_counter() + 90
            while time.perf_counter() < deadline:
                try:
                    probe.request({"type": "ping"})
                    break
                except Exception:  # noqa: BLE001 — still starting
                    time.sleep(0.2)
            else:
                raise RuntimeError(f"replica {s} never came up")
        router = PolishRouter(replicas=",".join(socks),
                              socket_path=os.path.join(tmp,
                                                       "range_rt.sock"),
                              journal=journal,
                              health_interval_s=0.5).start()
        # same pacing trick as the contig-shard section: a
        # watchdog-absorbed hang keeps both range shards busy long
        # enough for the kill to land genuinely mid-job
        slow = {"fault_plan": "device:chunk=0:hang=8",
                "options": {"tpu_device_timeout": 2.0}}
        res: dict = {}

        def run_job(out: dict):
            mine = PolishClient(socket_path=router.config.socket_path)
            try:
                out["resp"] = mine.submit(*rpaths, stream=True, **slow)
            except Exception as exc:  # noqa: BLE001 — checked below
                out["exc"] = exc

        t = threading.Thread(target=run_job, args=(res,))
        t.start()
        time.sleep(1.0)  # both range shards dispatched and stalled
        procs[0].send_signal(signal.SIGKILL)  # the real kill -9
        t.join(WALL_CAP)
        entries = read_journal(journal)
        events = [e["event"] for e in entries]
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import obsreport
        resp = res.get("resp")
        checks = [("completed", resp is not None),
                  ("identical",
                   resp is not None and resp.fasta == clean),
                  ("range-sharded",
                   resp is not None
                   and resp.router.get("range") is True),
                  ("range-plan-journaled", "range-plan" in events),
                  ("requeued-journaled", "requeued" in events
                   and "replica-down" in events),
                  ("journal-consistent",
                   check_consistency(entries) == []),
                  ("segments-tile",
                   obsreport.check_parts_routed(entries) == [])]
        failed = [n for n, ok in checks if not ok]
        if "exc" in res:
            failed.append(f"({type(res['exc']).__name__}: "
                          f"{res['exc']})")
        cells.append((name,
                      "pass  requeued, segments tiled, identical"
                      if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red cell, not a crashed grid
        cells.append((name,
                      f"FAIL crashed ({type(exc).__name__}: {exc})"))
    finally:
        if router is not None:
            router.drain()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
    return cells


def run_fragment_cells(tmp: str) -> list[tuple[str, str]]:
    """The fragment-correction section (serve mode: "fragment" + the
    admit-time ingest plane). Two gated cells:

      1. poisoned ingest: a fragment submit pointing at a non-FASTA
         reads file with `ingest` validation armed must fail TYPED
         (`bad-request`, `rejected-ingest` journaled, no started/failed
         pair) while a CONCURRENT contig job on the same server
         completes byte-identically — and the warm server then serves
         a clean fragment job byte-identical to the solo kF run;
      2. kill -9 mid-fragment-job: a fragment job read-range-sharded
         across two REAL `racon_tpu serve` replica subprocesses, one
         killed -9 mid-job. The requeue must re-run the dead replica's
         [frag_lo, frag_hi) slice on the survivor, the merged
         corrected reads must be byte-identical to a solo kF run, the
         ledger must carry `frag-plan` and `requeued`, stay
         lifecycle-consistent, and pass obsreport's fragment-receipt
         tiling check (each read group journaled exactly once,
         covering the read axis with no gap or overlap)."""
    import signal
    import subprocess

    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.obs.journal import check_consistency, read_journal
    from racon_tpu.serve import (PolishClient, PolishRouter,
                                 PolishServer, ServeError,
                                 make_synth_dataset)
    from racon_tpu.serve.server import make_fragment_dataset

    names = ("fragment poisoned ingest, contig alongside",
             "fragment kill -9 mid-job")
    cells: list[tuple[str, str]] = []
    frag_dir = os.path.join(tmp, "frag_data")
    os.makedirs(frag_dir, exist_ok=True)
    fpaths = make_fragment_dataset(frag_dir)
    pf = create_polisher(*fpaths, PolisherType.kF, 500, 10.0, 0.3,
                         num_threads=2)
    pf.initialize()
    clean_frag = b"".join(b">" + s.name.encode() + b"\n" + s.data
                          + b"\n" for s in pf.polish(True))
    contig_dir = os.path.join(tmp, "frag_contig_data")
    os.makedirs(contig_dir, exist_ok=True)
    cpaths = make_synth_dataset(contig_dir)
    pc = create_polisher(*cpaths, PolisherType.kC, 500, 10.0, 0.3,
                         num_threads=2)
    pc.initialize()
    clean_contig = b"".join(b">" + s.name.encode() + b"\n" + s.data
                            + b"\n" for s in pc.polish())

    # ---- cell 1: poisoned fragment ingest, contig riding alongside
    journal1 = os.path.join(tmp, "frag_journal1.jsonl")
    try:
        bad = os.path.join(tmp, "frag_bad.fasta")
        with open(bad, "w") as fh:
            fh.write("this is not fasta\n")
        srv = PolishServer(socket_path=os.path.join(tmp, "frag.sock"),
                           workers=2, warmup=False,
                           journal=journal1).start()
        try:
            res: dict = {}

            def contig_job(out: dict):
                mine = PolishClient(
                    socket_path=srv.config.socket_path)
                try:
                    out["resp"] = mine.submit(*cpaths)
                except Exception as exc:  # noqa: BLE001 — checked
                    out["exc"] = exc

            t = threading.Thread(target=contig_job, args=(res,))
            t.start()
            client = PolishClient(socket_path=srv.config.socket_path)
            typed = None
            try:
                client.submit(bad, fpaths[1], fpaths[2],
                              fragment=True, ingest=True)
            except ServeError as exc:
                typed = exc
            # the warm server still serves fragment work afterwards
            after = client.submit(*fpaths, fragment=True)
            t.join(WALL_CAP)
        finally:
            srv.drain(timeout=30)
        entries = read_journal(journal1)
        events = [e["event"] for e in entries]
        checks = [("typed-reject", typed is not None
                   and typed.code == "bad-request"),
                  ("rejected-ingest-journaled",
                   "rejected-ingest" in events),
                  ("contig-survived", res.get("resp") is not None
                   and res["resp"].fasta == clean_contig),
                  ("fragment-after-reject-identical",
                   after.fasta == clean_frag),
                  ("journal-consistent",
                   check_consistency(entries) == [])]
        failed = [n for n, ok in checks if not ok]
        if "exc" in res:
            failed.append(f"({type(res['exc']).__name__}: "
                          f"{res['exc']})")
        cells.append((names[0],
                      "pass  typed bad-request, contig unharmed"
                      if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed cell is a red
        # cell, not a crashed grid
        cells.append((names[0],
                      f"FAIL crashed ({type(exc).__name__}: {exc})"))

    # ---- cell 2: kill -9 one of two replicas mid-fragment-job
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_DEVICE_RETRIES="1")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [q for q in env.get("PYTHONPATH", "").split(os.pathsep)
           if q and "axon_site" not in q])
    socks = [os.path.join(tmp, f"frag_rep{i}.sock") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve", "--socket", s,
         "--workers", "2", "--no-warmup"],
        env=env, stderr=subprocess.DEVNULL) for s in socks]
    router = None
    journal2 = os.path.join(tmp, "frag_journal2.jsonl")
    try:
        for s in socks:
            probe = PolishClient(socket_path=s, timeout=30)
            deadline = time.perf_counter() + 90
            while time.perf_counter() < deadline:
                try:
                    probe.request({"type": "ping"})
                    break
                except Exception:  # noqa: BLE001 — still starting
                    time.sleep(0.2)
            else:
                raise RuntimeError(f"replica {s} never came up")
        router = PolishRouter(replicas=",".join(socks),
                              socket_path=os.path.join(tmp,
                                                       "frag_rt.sock"),
                              journal=journal2,
                              health_interval_s=0.5).start()
        # the same pacing trick as the range section: a
        # watchdog-absorbed hang keeps both fragment shards busy long
        # enough for the kill to land genuinely mid-job
        slow = {"fault_plan": "device:chunk=0:hang=8",
                "options": {"tpu_device_timeout": 2.0}}
        res2: dict = {}

        def run_job(out: dict):
            mine = PolishClient(socket_path=router.config.socket_path)
            try:
                out["resp"] = mine.submit(*fpaths, fragment=True,
                                          stream=True, **slow)
            except Exception as exc:  # noqa: BLE001 — checked below
                out["exc"] = exc

        t = threading.Thread(target=run_job, args=(res2,))
        t.start()
        time.sleep(1.0)  # both fragment shards dispatched and stalled
        procs[0].send_signal(signal.SIGKILL)  # the real kill -9
        t.join(WALL_CAP)
        entries = read_journal(journal2)
        events = [e["event"] for e in entries]
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import obsreport
        resp = res2.get("resp")
        checks = [("completed", resp is not None),
                  ("identical",
                   resp is not None and resp.fasta == clean_frag),
                  ("fragment-sharded",
                   resp is not None
                   and resp.router.get("fragment") is True),
                  ("frag-plan-journaled", "frag-plan" in events),
                  ("requeued-journaled", "requeued" in events
                   and "replica-down" in events),
                  ("journal-consistent",
                   check_consistency(entries) == []),
                  ("read-groups-tile",
                   obsreport.check_parts_routed(entries) == [])]
        failed = [n for n, ok in checks if not ok]
        if "exc" in res2:
            failed.append(f"({type(res2['exc']).__name__}: "
                          f"{res2['exc']})")
        cells.append((names[1],
                      "pass  requeued, read groups tiled, identical"
                      if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red cell, not a crashed grid
        cells.append((names[1],
                      f"FAIL crashed ({type(exc).__name__}: {exc})"))
    finally:
        if router is not None:
            router.drain()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
    return cells


def run_trace_cells(tmp: str) -> list[tuple[str, str]]:
    """The distributed-trace section (serve/router.py trace collection
    + tools/tracereport.py): a TRACED routed job over two real replica
    subprocesses, one killed -9 mid-job. The job must complete via the
    journal-backed requeue byte-identically AND the merged Chrome
    trace must tell that story honestly: the `router.requeue` instant
    present for the re-dispatched shard, the dead replica simply
    absent as a track (trace_pull is best-effort), `tracereport
    --check` green — the per-stage attribution still partitions the
    job wall and the requeue-instant count still matches the router
    block's `requeues` — and the router journal still
    lifecycle-consistent. A crash that corrupts the trace artifact or
    double-counts the requeued shard's spans is a red cell here, not a
    plausible-looking report."""
    import signal
    import subprocess

    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.obs.journal import check_consistency, read_journal
    from racon_tpu.serve import (PolishClient, PolishRouter,
                                 make_synth_dataset)

    name = "traced requeue kill -9 mid-job"
    cells: list[tuple[str, str]] = []
    data_dir = os.path.join(tmp, "trace_data")
    os.makedirs(data_dir, exist_ok=True)
    rpaths = make_synth_dataset(data_dir, contigs=4)
    p = create_polisher(*rpaths, PolisherType.kC, 500, 10.0, 0.3,
                        num_threads=2)
    p.initialize()
    clean = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                     for s in p.polish())
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RACON_TPU_DEVICE_RETRIES="1")
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [q for q in env.get("PYTHONPATH", "").split(os.pathsep)
           if q and "axon_site" not in q])
    socks = [os.path.join(tmp, f"trace_rep{i}.sock") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve", "--socket", s,
         "--workers", "2", "--no-warmup"],
        env=env, stderr=subprocess.DEVNULL) for s in socks]
    router = None
    journal = os.path.join(tmp, "trace_journal.jsonl")
    trace_out = os.path.join(tmp, "trace_merged.json")
    try:
        for s in socks:
            probe = PolishClient(socket_path=s, timeout=30)
            deadline = time.perf_counter() + 90
            while time.perf_counter() < deadline:
                try:
                    probe.request({"type": "ping"})
                    break
                except Exception:  # noqa: BLE001 — still starting
                    time.sleep(0.2)
            else:
                raise RuntimeError(f"replica {s} never came up")
        router = PolishRouter(replicas=",".join(socks),
                              socket_path=os.path.join(
                                  tmp, "trace_router.sock"),
                              journal=journal,
                              health_interval_s=0.5).start()
        # the same watchdog-absorbed hang plan the router cell uses:
        # bytes unchanged, every shard busy long enough for the kill
        # to land genuinely mid-job
        slow = {"fault_plan": "device:chunk=0:hang=8",
                "options": {"tpu_device_timeout": 2.0}}
        res: dict = {}

        def run_job(out: dict):
            mine = PolishClient(socket_path=router.config.socket_path)
            try:
                r, _doc = mine.submit_traced(*rpaths,
                                             trace_out=trace_out,
                                             **slow)
                out["fasta"] = r.fasta
            except Exception as exc:  # noqa: BLE001 — checked below
                out["exc"] = exc

        t = threading.Thread(target=run_job, args=(res,))
        t.start()
        time.sleep(1.0)  # shards dispatched and stalled on chunk 0
        procs[0].send_signal(signal.SIGKILL)  # the real kill -9
        t.join(WALL_CAP)
        entries = read_journal(journal)
        events = [e["event"] for e in entries]
        requeue_spans = 0
        if os.path.exists(trace_out):
            with open(trace_out) as fh:
                doc = json.load(fh)
            requeue_spans = sum(
                1 for ev in doc.get("traceEvents") or []
                if ev.get("ph") == "i"
                and ev.get("name") == "router.requeue")
        report = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tracereport.py"),
             trace_out, "--check"],
            env=env, capture_output=True, text=True)
        checks = [("completed", "fasta" in res),
                  ("identical", res.get("fasta") == clean),
                  ("requeued-journaled", "requeued" in events
                   and "replica-down" in events),
                  ("journal-consistent",
                   not check_consistency(entries)),
                  ("requeue-span", requeue_spans >= 1),
                  ("tracereport-check",
                   report.returncode == 0)]
        failed = [n for n, ok in checks if not ok]
        if "exc" in res:
            failed.append(f"({type(res['exc']).__name__}: "
                          f"{res['exc']})")
        if report.returncode != 0:
            failed.append(
                "(" + (report.stderr.strip().splitlines() or ["?"])[-1]
                + ")")
        cells.append((name,
                      "pass  requeue span present, report consistent"
                      if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red cell, not a crashed grid
        cells.append((name,
                      f"FAIL crashed ({type(exc).__name__}: {exc})"))
    finally:
        if router is not None:
            router.drain()
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=10)
    return cells


def run_preempt_cells(tmp: str) -> list[tuple[str, str]]:
    """The preemptive-QoS section (serve QoS: --preempt + cancel RPC):
    a gold-priority job preempts a running free job on a one-worker
    server — the free job's pooled windows are withdrawn and parked,
    gold runs, the free job resumes — and BOTH outputs must be
    byte-identical to an undisturbed run, with the balanced
    `preempted`/`resumed` pair on the journal. Then a cancel RPC lands
    during an injected `device:hang`: the watchdog absorbs the hang,
    the cancelled job fails with the typed `cancelled` error instead of
    shipping unwanted bytes, and the same warm server's next clean job
    reproduces the clean bytes exactly."""
    from racon_tpu.obs.journal import read_journal
    from racon_tpu.serve import (JobCancelled, PolishClient,
                                 PolishServer, make_synth_dataset)

    names = ("preempt gold over free", "cancel during device hang")
    cells: list[tuple[str, str]] = []
    data_dir = os.path.join(tmp, "preempt_data")
    os.makedirs(data_dir, exist_ok=True)
    ppaths = make_synth_dataset(data_dir, contigs=3)
    sock = os.path.join(tmp, "preempt.sock")
    journal = os.path.join(tmp, "preempt_journal.jsonl")
    server = None
    try:
        server = PolishServer(socket_path=sock, workers=1, warmup=False,
                              quality_threshold=-1.0, preempt=True,
                              journal=journal).start()
        client = PolishClient(socket_path=sock)
        clean = client.submit(*ppaths).fasta  # the undisturbed bytes

        def run_job(out: dict, **kw):
            mine = PolishClient(socket_path=sock)
            try:
                out["fasta"] = mine.submit(*ppaths, **kw).fasta
            except Exception as exc:  # noqa: BLE001 — checked below
                out["exc"] = exc

        free_res: dict = {}
        gold_res: dict = {}
        # hold the device feeder so the free job is deterministically
        # mid-flight (windows pooled, not yet dispatched) when gold
        # arrives — the admission-time preemption path, not a race
        server.batcher.hold()
        try:
            t_free = threading.Thread(target=run_job, args=(free_res,),
                                      kwargs={"tenant": "free"})
            t_free.start()
            deadline = time.perf_counter() + 60
            while (time.perf_counter() < deadline
                   and not server._running_jobs):
                time.sleep(0.02)
            t_gold = threading.Thread(target=run_job, args=(gold_res,),
                                      kwargs={"tenant": "gold",
                                              "priority": 5})
            t_gold.start()
            while (time.perf_counter() < deadline
                   and server.qos["preemptions"] < 1):
                time.sleep(0.02)
        finally:
            server.batcher.release()
        t_free.join(WALL_CAP)
        t_gold.join(WALL_CAP)
        events = [e["event"] for e in read_journal(journal)]
        checks = [("preempted", server.qos["preemptions"] >= 1),
                  ("preempted-journaled", "preempted" in events),
                  ("resumed-journaled", "resumed" in events),
                  ("free-identical", free_res.get("fasta") == clean),
                  ("gold-identical", gold_res.get("fasta") == clean)]
        failed = [n for n, ok in checks if not ok]
        for res in (free_res, gold_res):
            if "exc" in res:
                failed.append(f"({type(res['exc']).__name__}: "
                              f"{res['exc']})")
        cells.append((names[0],
                      "pass  preempted+resumed, both identical"
                      if not failed else f"FAIL {' '.join(failed)}"))

        # cell 2: cancel landing mid-hang on the SAME warm server —
        # the hang plan parks the job inside the device dispatch for
        # ~2s (watchdog timeout), a window no scheduler trick is
        # needed to hit
        poison_res: dict = {}
        t_poison = threading.Thread(
            target=run_job, args=(poison_res,),
            kwargs={"fault_plan": "device:chunk=0:hang=8",
                    "options": {"tpu_device_timeout": 2.0},
                    "trace_id": "faultcheck-cancel"})
        t_poison.start()
        time.sleep(1.0)  # job admitted and stalled inside the hang
        try:
            cres = client.cancel(trace_id="faultcheck-cancel")
        except Exception as exc:  # noqa: BLE001 — checked below
            cres = {"error": f"{type(exc).__name__}: {exc}"}
        t_poison.join(WALL_CAP)
        try:
            after = client.submit(*ppaths).fasta
        except Exception:  # noqa: BLE001 — dead server is the failure
            after = None
        checks = [("cancel-acked", cres.get("type") == "ok"),
                  ("typed-cancelled",
                   isinstance(poison_res.get("exc"), JobCancelled)),
                  ("server-survived-identical", after == clean)]
        failed = [n for n, ok in checks if not ok]
        cells.append((names[1],
                      "pass  cancelled typed, watchdog absorbed, "
                      "server clean"
                      if not failed else f"FAIL {' '.join(failed)}"))
    except Exception as exc:  # noqa: BLE001 — a crashed section is a
        # red pair of cells, not a crashed grid
        detail = f"FAIL crashed ({type(exc).__name__}: {exc})"
        while len(cells) < 2:
            cells.append((names[len(cells)], detail))
    finally:
        if server is not None:
            server.drain(timeout=30)
    return cells


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the slow hang-injection rows")
    args = ap.parse_args()

    os.environ["RACON_TPU_ALIGNER_MAXLEN"] = "1024"
    os.environ.pop("RACON_TPU_STRICT", None)
    rows = [m for m in MATRIX if not (args.quick and m[4])]

    failures = 0
    with tempfile.TemporaryDirectory(prefix="racon_faultcheck_") as tmp:
        paths = make_dataset(tmp, random.Random(11))
        clean = {}
        for depth in (0, 2):
            for aligner in (0, 1):
                clean[depth, aligner] = polish(paths, depth, aligner,
                                               0.0)[0]
        # scheduler-on column: the clean adaptive run must be
        # byte-identical to the static one (the scheduler contract) —
        # checked once here, so every adaptive cell compares against the
        # same bytes the static cells do
        for aligner in (0, 1):
            sched_clean = polish(paths, 2, aligner, 0.0, adaptive=True)[0]
            if sched_clean != clean[2, aligner]:
                print("[faultcheck] FAIL: adaptive-bucket clean run "
                      "diverged from static", file=sys.stderr)
                return 1
        # pallas-column clean gate: the kernel-plane contract is that a
        # clean RACON_TPU_PALLAS=1 run is byte-identical to the XLA one
        # — checked once, so every pallas cell compares against the
        # same bytes the other columns do
        os.environ["RACON_TPU_PALLAS"] = "1"
        try:
            for aligner in (0, 1):
                pallas_clean = polish(paths, 2, aligner, 0.0)[0]
                if pallas_clean != clean[2, aligner]:
                    print("[faultcheck] FAIL: pallas clean run diverged "
                          "from XLA", file=sys.stderr)
                    return 1
        finally:
            os.environ.pop("RACON_TPU_PALLAS", None)
        # fused-column clean gate: the fused single-launch program
        # (device consensus, fused engine, RACON_TPU_FUSED=1) must be
        # byte-identical to the SPLIT chained path on a clean run —
        # the identity that makes split the fused program's declared
        # fault fallback; every fused cell compares against this
        for aligner in (0, 1):
            try:
                os.environ["RACON_TPU_FUSED"] = "0"
                split_clean = polish(paths, 2, aligner, 0.0, poa=1,
                                     engine="fused")[0]
                os.environ["RACON_TPU_FUSED"] = "1"
                fused_clean = polish(paths, 2, aligner, 0.0, poa=1,
                                     engine="fused")[0]
            finally:
                os.environ.pop("RACON_TPU_FUSED", None)
            if fused_clean != split_clean:
                print("[faultcheck] FAIL: fused single-launch clean "
                      "run diverged from the split path",
                      file=sys.stderr)
                return 1
            clean["fused", aligner] = fused_clean
        width = max(len(m[0]) for m in rows)
        print(f"{'injection point':<{width}}  depth0"
              f"{'':<30}depth2{'':<30}depth2+sched"
              f"{'':<24}depth2+trace{'':<24}depth2+pallas"
              f"{'':<23}depth2+fused{'':<24}serve{'':<31}serve-lanes2",
              file=sys.stderr)
        # the 4th column runs with span tracing armed: the injected run
        # must additionally produce a valid Chrome trace whose
        # fault/quarantine instant events match the degradation
        # counters; the 5th runs the Pallas kernel plane (aligner rows
        # dispatch the resident wavefront kernel in interpret mode);
        # the 6th runs device consensus through the FUSED single-launch
        # program — injected faults must fall back to the split chained
        # path byte-identically
        columns = ((0, False, False, False, False),
                   (2, False, False, False, False),
                   (2, True, False, False, False),
                   (2, False, True, False, False),
                   (2, False, False, True, False),
                   (2, False, False, False, True))
        # the final (serve) column submits the fault as a per-job plan
        # against ONE live warm server shared by every row — surviving
        # the whole poisoned sequence is itself part of the gate
        from racon_tpu.serve import PolishClient, PolishServer

        serve_sock = os.path.join(tmp, "faultcheck.sock")
        server = PolishServer(socket_path=serve_sock, workers=2,
                              quality_threshold=-1.0,
                              warmup=False).start()
        client = PolishClient(socket_path=serve_sock)
        # the 7th column shares a SECOND live server running two
        # sub-mesh worker lanes: the poisoned strict job (solo on one
        # lane) must fail typed while a CONCURRENT clean job on the
        # other lane stays byte-identical — lane-level fault isolation
        lanes_sock = os.path.join(tmp, "faultcheck_lanes.sock")
        lanes_server = PolishServer(socket_path=lanes_sock, workers=2,
                                    worker_lanes=2,
                                    quality_threshold=-1.0,
                                    warmup=False).start()
        lanes_client = PolishClient(socket_path=lanes_sock)
        try:
            for name, aligner, spec, timeout, _slow in rows:
                cells = []
                for depth, adaptive, traced, pallas, fused in columns:
                    cell = run_cell(paths, clean, depth, aligner, spec,
                                    timeout, adaptive, trace=traced,
                                    pallas=pallas, fused=fused)
                    failures += cell.startswith("FAIL")
                    cells.append(f"{cell:<36}")
                cell = run_serve_cell(client, paths, clean, aligner,
                                      spec, timeout)
                failures += cell.startswith("FAIL")
                cells.append(f"{cell:<36}")
                cell = run_serve_lanes_cell(lanes_client, paths, clean,
                                            aligner, spec, timeout)
                failures += cell.startswith("FAIL")
                cells.append(f"{cell:<36}")
                print(f"{name:<{width}}  {''.join(cells)}",
                      file=sys.stderr)
        finally:
            os.environ.pop("RACON_TPU_DEVICE_RETRIES", None)
            try:
                server.drain(timeout=30)
            finally:
                # a failed drain of the first server must not leak the
                # lanes server's threads/socket
                lanes_server.drain(timeout=30)
        # the identity-audit section: silent corruption vs the sentinel
        audit_cells = run_audit_cells(tmp, paths)
        for name, cell in audit_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
        # the replicated-fabric section: kill -9 a replica behind the
        # router mid-job — requeue must finish the job byte-identically
        router_cells = run_router_cells(tmp)
        for name, cell in router_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
        # the window-range sharding section: kill -9 one of two
        # replicas mid-range-sharded SINGLE-contig job — the requeued
        # window range must complete byte-identically with the
        # segment receipts tiling the contig exactly once
        range_cells = run_range_cells(tmp)
        for name, cell in range_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
        # the fragment-correction section: a poisoned fragment ingest
        # fails typed while a concurrent contig job survives; kill -9
        # one of two replicas mid-fragment-job — the requeued read
        # range must complete byte-identically with the read-group
        # receipts tiling the read axis exactly once
        fragment_cells = run_fragment_cells(tmp)
        for name, cell in fragment_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
        # the distributed-trace section: kill -9 under a TRACED routed
        # job — the merged trace must show the requeue and survive
        # tracereport --check with the journal still consistent
        trace_cells = run_trace_cells(tmp)
        for name, cell in trace_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
        # the preemptive-QoS section: gold preempts free byte-
        # identically; a cancel RPC lands during a watchdog-absorbed
        # hang and the server survives
        preempt_cells = run_preempt_cells(tmp)
        for name, cell in preempt_cells:
            failures += cell.startswith("FAIL")
            print(f"{name:<{width}}  {cell}", file=sys.stderr)
    n_cells = ((len(columns) + 2) * len(rows) + len(audit_cells)
               + len(router_cells) + len(range_cells)
               + len(fragment_cells) + len(trace_cells)
               + len(preempt_cells))
    print(f"[faultcheck] {'FAIL' if failures else 'PASS'}: "
          f"{n_cells - failures}/{n_cells} cells green",
          file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
