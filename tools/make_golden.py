"""Regenerate the committed golden polished FASTA (tests/data/).

The reference's GPU CI pins a whole-run golden output and requires an exact
byte diff (/root/reference/ci/gpu/cuda_test.sh:30-44, ci/gpu/golden-output.txt,
5.2 MB). This repo's analogue: the host engine's full polished FASTA for the
lambda sample, which BOTH engines must reproduce byte-for-byte
(tests/test_golden.py::test_golden_output_exact_diff*) — the device engine
is byte-identical to host by design (ops/poa_graph.py).

Run from the repo root after an intentional algorithm change:
    python tools/make_golden.py
and commit the updated file with the change that caused it.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from racon_tpu.core.polisher import create_polisher, PolisherType  # noqa: E402

DATA = "/root/reference/test/data/"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "data", "sample_golden.fasta")


def polish_fasta(device_batches: int = 0) -> bytes:
    """The canonical sample polish (the configuration of the reference's
    first golden fixture, racon_test.cpp:88-109) as FASTA bytes."""
    p = create_polisher(
        DATA + "sample_reads.fastq.gz", DATA + "sample_overlaps.paf.gz",
        DATA + "sample_layout.fasta.gz", PolisherType.kC, 500, 10.0, 0.3,
        True, 5, -4, -8, num_threads=4, tpu_poa_batches=device_batches)
    p.initialize()
    out = bytearray()
    for seq in p.polish():
        out += b">" + seq.name.encode() + b"\n" + seq.data + b"\n"
    return bytes(out)


def main() -> int:
    data = polish_fasta()
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "wb") as fh:
        fh.write(data)
    print(f"wrote {OUT} ({len(data)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
