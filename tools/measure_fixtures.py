"""Print this implementation's measured value for every golden fixture.

Used to (re)pin tests/test_golden.py exactly, the way the reference pins
each backend's numbers (test/racon_test.cpp:107,312 etc.). Run after an
intentional algorithm change, then update the pins together with it.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from test_golden import (  # noqa: E402
    run_pipeline, reference_distance, total_length, PolisherType)


def main() -> int:
    fixtures = [
        ("consensus_with_qualities",
         dict(reads="sample_reads.fastq.gz", overlaps="sample_overlaps.paf.gz",
              target="sample_layout.fasta.gz")),
        ("consensus_without_qualities",
         dict(reads="sample_reads.fasta.gz", overlaps="sample_overlaps.paf.gz",
              target="sample_layout.fasta.gz")),
        ("consensus_with_qualities_and_alignments",
         dict(reads="sample_reads.fastq.gz", overlaps="sample_overlaps.sam.gz",
              target="sample_layout.fasta.gz")),
        ("consensus_without_qualities_and_with_alignments",
         dict(reads="sample_reads.fasta.gz", overlaps="sample_overlaps.sam.gz",
              target="sample_layout.fasta.gz")),
        ("consensus_with_qualities_larger_window",
         dict(reads="sample_reads.fastq.gz", overlaps="sample_overlaps.paf.gz",
              target="sample_layout.fasta.gz", window_length=1000)),
        ("consensus_with_qualities_edit_distance",
         dict(reads="sample_reads.fastq.gz", overlaps="sample_overlaps.paf.gz",
              target="sample_layout.fasta.gz", match=1, mismatch=-1, gap=-1)),
    ]
    for name, kw in fixtures:
        polished = run_pipeline(kw.pop("reads"), kw.pop("overlaps"),
                                kw.pop("target"), **kw)
        print(f"{name}: n={len(polished)} distance="
              f"{reference_distance(polished[0])}", flush=True)

    frags = [
        ("fragment_correction_with_qualities",
         dict(reads="sample_reads.fastq.gz",
              overlaps="sample_ava_overlaps.paf.gz",
              target="sample_reads.fastq.gz",
              match=1, mismatch=-1, gap=-1)),
        ("fragment_correction_with_qualities_full",
         dict(reads="sample_reads.fastq.gz",
              overlaps="sample_ava_overlaps.paf.gz",
              target="sample_reads.fastq.gz", type_=PolisherType.kF,
              match=1, mismatch=-1, gap=-1, drop_unpolished=False)),
        ("fragment_correction_without_qualities_full",
         dict(reads="sample_reads.fasta.gz",
              overlaps="sample_ava_overlaps.paf.gz",
              target="sample_reads.fasta.gz", type_=PolisherType.kF,
              match=1, mismatch=-1, gap=-1, drop_unpolished=False)),
        ("fragment_correction_with_qualities_full_mhap",
         dict(reads="sample_reads.fastq.gz",
              overlaps="sample_ava_overlaps.mhap.gz",
              target="sample_reads.fastq.gz", type_=PolisherType.kF,
              match=1, mismatch=-1, gap=-1, drop_unpolished=False)),
    ]
    for name, kw in frags:
        polished = run_pipeline(kw.pop("reads"), kw.pop("overlaps"),
                                kw.pop("target"), **kw)
        print(f"{name}: n={len(polished)} total_bp={total_length(polished)}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
