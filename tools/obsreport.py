"""Serve observability report: journal timelines + flight dump index.

The serve layer leaves two kinds of evidence behind: the durable event
journal (`serve --journal`, obs/journal.py — one JSONL line per job
lifecycle transition) and the flight-recorder dump artifacts
(`<flight-dir>/flight_<job>_<reason>.json` — a Chrome trace windowed to
a failed / deadline-missed job). Each is useful alone; the question an
operator actually asks — "what happened to job X, and is there a
post-mortem for it" — needs them TOGETHER. This tool renders that view:

    python tools/obsreport.py --journal /var/log/racon/journal.jsonl \
        [--flight-dir /tmp/racon_tpu_flight] [--job j42] [--check]

Per job: the transition timeline with +deltas from the first event, the
terminal state, the trace id (when the client minted one), and the
flight dump that names the job, if any. Annotation events — the SLO
burn tracker's `alert` lines, the identity-audit sentinel's
`audit-mismatch` lines (rendered in the OWNING job's timeline, next to
the iteration that produced the corrupted bytes, carrying the
dual-stream flight dump path), its `audit-lane` quarantine/rejoin
transitions, and any event type this tool does not know — render in
the timeline of the job they name (an alert next to the deadline-miss
that tripped it) but are IGNORED by the consistency check: `--check`
red means a lifecycle invariant broke, never "a newer server emits a
newer event type". The summary counts events by
type and runs the journal consistency check (`--check` turns problems
into a nonzero exit — the CI shape; `tools/servebench.py` runs the same
check inside its gate). `--check` additionally verifies the streamed-
results lifecycle: every successfully `finished` job must carry exactly
one `part-streamed` event per output contig (the server journals one
per stitched part — continuous batching stitches EVERY serve job
incrementally), so a lost or duplicated part shows up as a red check,
not a silent hole in the stream — and the iterative-rounds lifecycle:
a `rounds=N` job journals a `round-started` / `round-finished` pair
per round (annotation events, rendered in the job's timeline with the
round's wall clock and window-cache hit count), and `--check` pins
the two counts equal per job, so a round that died mid-loop (or a
duplicated boundary line) is a red check, not a plausible-looking
timeline — and the preemption lifecycle: every `preempted` a job
journals must be balanced by exactly one `resumed` (the server emits
`resumed` with reason=terminal when a job ends while still parked), so
a job left parked forever — a leaked withdrawal — is a red check.
The router-journal twin of the part-streamed receipt is checked at
SEGMENT granularity: a range-sharded job (serve/router.py window-range
sharding) journals one `part-routed` line per accepted segment with its
`lo`/`hi` window-grid coordinates, and `--check` pins that each
contig's segments, sorted by `lo`, tile the coordinate axis from 0
with no gap, overlap, or duplicate — so a segment merged twice (a
requeue dedupe bug) or a hole silently dropped from a reassembled
contig is a red check; whole-contig `part-routed` lines are pinned to
exactly one per contig per job.

Fragment jobs (`mode: "fragment"`, serve/protocol.py "Fragment jobs")
stream corrected reads in BOUNDED GROUPS, so their receipts aggregate:
each `part-streamed` line carries `reads=N` (the corrected reads in
that group) and `--check` pins the SUM of reads — not the line count —
against the finished job's `sequences`. Their router twin journals
`part-routed` lines with `frag_lo`/`frag_hi` read-axis coordinates
(no contig name), checked with the same tile-from-zero discipline as
range segments but allowing empty groups (`frag_lo == frag_hi` — a
group whose reads all dropped still advances the receipt). Admit-time
ingest annotations (`ingested`, `normalized`, `subsampled`,
`frag-plan`) render in the owning job's timeline like any annotation;
`rejected-ingest` is a terminal state (a job refused at admission
validation never starts).

Fleet elasticity renders alongside the jobs it served: the PR-18
autoscaler journals `autoscale-up` / `autoscale-down` with no job
field (a scale decision belongs to the fleet, not one job), so each
one is interleaved — tagged `[fleet]` — into the timeline of every
job whose lifetime it fell inside: the operator reads "this job
queued, the fleet scaled up, the shard dispatched" as one sequence.
Shard `hold` annotations (the router held a dispatch for the
autoscale idle-hold window) carry their job and render natively.
`--check` adds `check_autoscale`: every `autoscale-down` must name a
replica a prior `autoscale-up` spawned and not already drained — the
autoscaler only ever drains replicas it created, so a down without
its up (or a double-down) means the elasticity ledger lost a
transition."""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load_flight_dumps(dirname: str) -> list[dict]:
    """The `flight` header objects of every dump artifact in `dirname`,
    each annotated with its path. Unreadable artifacts are reported as
    such, not fatal — this is a post-mortem tool."""
    out = []
    for path in sorted(glob.glob(os.path.join(dirname,
                                              "flight_*.json"))):
        info = {"path": path}
        try:
            with open(path) as fh:
                doc = json.load(fh)
            info.update(doc.get("flight") or {})
            info["events"] = len(doc.get("traceEvents") or [])
        except (OSError, ValueError) as exc:
            info["error"] = f"{type(exc).__name__}: {exc}"
        out.append(info)
    return out


def job_timelines(entries: list[dict]) -> dict[str, list[dict]]:
    """Journal entries grouped by job, in journal order; entries
    without a job id (serve-start / drain / serve-stop) are skipped —
    render_summary reports them."""
    jobs: dict[str, list[dict]] = {}
    for e in entries:
        if e.get("job"):
            jobs.setdefault(str(e["job"]), []).append(e)
    return jobs


def _fields(e: dict) -> str:
    skip = {"t", "event", "job", "trace"}
    parts = [f"{k}={e[k]}" for k in e if k not in skip]
    return f" ({', '.join(parts)})" if parts else ""


def fleet_events(entries: list[dict]) -> list[dict]:
    """The jobless elasticity transitions (`autoscale-up` /
    `autoscale-down`) in journal order — render_job interleaves each
    into every job whose lifetime it fell inside."""
    return [e for e in entries
            if e.get("event") in ("autoscale-up", "autoscale-down")
            and not e.get("job")]


def render_job(job: str, events: list[dict], dumps: list[dict],
               out, fleet: list[dict] | None = None) -> None:
    trace = next((e["trace"] for e in events if e.get("trace")), None)
    t0 = events[0].get("t", 0.0)
    t_last = events[-1].get("t", t0)
    head = f"job {job}"
    if trace:
        head += f"  trace={trace}"
    print(head, file=out)
    names = {e.get("event") for e in events}
    lines = [(e.get("t", t0), e.get("event", "?"), _fields(e), "")
             for e in events]
    for e in fleet or []:
        t = e.get("t", t0)
        if t0 <= t <= t_last:
            lines.append((t, e.get("event", "?"), _fields(e),
                          " [fleet]"))
    lines.sort(key=lambda x: x[0])
    for t, name, fields, tag in lines:
        print(f"  +{t - t0:8.3f}s  {name:<18}{fields}{tag}",
              file=out)
    # dumps exist only for failed / deadline-missed jobs; job ids
    # restart per server lifetime, so a dump naming a job whose journal
    # shows a clean finish is a STALE artifact from an earlier server —
    # don't misattach it to this job's timeline
    if names & {"failed", "deadline-miss", "expired"}:
        for d in dumps:
            if d.get("job_id") == job:
                print(f"  flight dump: {d['path']} "
                      f"(reason={d.get('reason')}, "
                      f"error={d.get('error_type')})", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render serve journal timelines alongside "
                    "flight-recorder dumps (see module docstring)")
    ap.add_argument("--journal",
                    default=os.environ.get("RACON_TPU_SERVE_JOURNAL"),
                    help="journal path (default: "
                         "RACON_TPU_SERVE_JOURNAL)")
    ap.add_argument("--flight-dir",
                    default=os.environ.get("RACON_TPU_SERVE_FLIGHT_DIR")
                    or os.environ.get("RACON_TPU_FLIGHT_DIR")
                    or "/tmp/racon_tpu_flight",
                    help="flight dump directory to index alongside "
                         "(default: the serve layer's resolution "
                         "chain)")
    ap.add_argument("--job", default=None,
                    help="render only this job id")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the journal fails its "
                         "consistency check (CI shape)")
    args = ap.parse_args(argv)

    from racon_tpu.obs.journal import check_consistency, read_journal

    if not args.journal:
        print("[obsreport] error: no journal path (pass --journal or "
              "set RACON_TPU_SERVE_JOURNAL)", file=sys.stderr)
        return 2
    entries = read_journal(args.journal)
    if not entries:
        print(f"[obsreport] error: no journal entries at "
              f"{args.journal}", file=sys.stderr)
        return 2

    dumps = (load_flight_dumps(args.flight_dir)
             if args.flight_dir and os.path.isdir(args.flight_dir)
             else [])
    jobs = job_timelines(entries)

    out = sys.stdout
    fleet = fleet_events(entries)
    shown = 0
    for job, events in jobs.items():
        if args.job and job != args.job:
            continue
        render_job(job, events, dumps, out, fleet=fleet)
        shown += 1
    if args.job and not shown:
        print(f"[obsreport] error: job {args.job!r} not in journal "
              f"({len(jobs)} jobs)", file=sys.stderr)
        return 2

    counts: dict[str, int] = {}
    for e in entries:
        counts[str(e.get("event"))] = counts.get(str(e.get("event")),
                                                 0) + 1
    print(f"summary: {len(entries)} events / {len(jobs)} jobs — "
          + ", ".join(f"{k}={v}" for k, v in sorted(counts.items())),
          file=out)
    unmatched = [d for d in dumps
                 if d.get("job_id") and d["job_id"] not in jobs]
    print(f"flight dumps: {len(dumps)} in {args.flight_dir}"
          + (f" ({len(unmatched)} for jobs outside the journal window)"
             if unmatched else ""), file=out)

    problems = check_consistency(entries)
    problems += check_parts_streamed(entries)
    problems += check_parts_routed(entries)
    problems += check_rounds(entries)
    problems += check_preemptions(entries)
    problems += check_autoscale(entries)
    for p in problems:
        print(f"consistency: {p}", file=out)
    print(f"consistency: {'OK' if not problems else 'FAIL'} "
          f"({len(problems)} problems)", file=out)
    return 1 if (args.check and problems) else 0


def check_parts_streamed(entries: list[dict]) -> list[str]:
    """Streamed-results invariant: a job that `finished` successfully
    with N output sequences must have journaled exactly N
    `part-streamed` events (one per stitched contig). Fragment jobs
    stream reads in bounded GROUPS: their part-streamed lines carry
    `reads=N`, and each such line accounts for N output sequences
    instead of one. Jobs whose `finished` line predates the
    part-streamed era (no `sequences` field) or that never finished
    are skipped — this is a per-job receipt, not a schema
    migration."""
    parts: dict[str, int] = {}
    finished: dict[str, int] = {}
    received: set[str] = set()
    for e in entries:
        job = e.get("job")
        if not job:
            continue
        if e.get("event") == "received":
            received.add(str(job))
        elif e.get("event") == "part-streamed":
            n = e["reads"] if isinstance(e.get("reads"), int) else 1
            parts[str(job)] = parts.get(str(job), 0) + n
        elif e.get("event") == "finished" \
                and isinstance(e.get("sequences"), int):
            finished[str(job)] = e["sequences"]
    problems: list[str] = []
    for job, n_seqs in sorted(finished.items()):
        if job not in received:
            # the journal's rotation window cut this job's early
            # events (check_consistency applies the same tolerance):
            # its part-streamed lines may be in the discarded
            # generation, which is history loss, not a stream bug
            continue
        n_parts = parts.get(job, 0)
        if n_parts != n_seqs:
            problems.append(
                f"job {job}: {n_parts} part-streamed events for "
                f"{n_seqs} output sequences")
    return problems


def check_parts_routed(entries: list[dict]) -> list[str]:
    """Router part-receipt invariant, at segment granularity: the
    router journals one `part-routed` line per contig it forwards —
    and under window-range sharding, one per accepted SEGMENT, tagged
    with the segment's `lo`/`hi` window-grid coordinates. Per (job,
    contig): range segments sorted by `lo` must tile the axis from 0 —
    every `lo` equal to the previous `hi`, no overlap, no duplicate —
    because the merge ledger dedupes requeue replays BEFORE journaling;
    a violation means a segment was merged twice or a hole shipped
    inside a reassembled contig. Whole-contig lines (no `lo`) must
    appear exactly once per contig. Fragment-sharded jobs journal
    read-axis receipts instead (`frag_lo`/`frag_hi`, no contig name):
    per job, sorted by `frag_lo`, they must tile the read axis from
    0 — same discipline, different axis (a group whose reads all
    dropped still advances the receipt, so `reads` may be 0 but the
    range never runs backwards). Jobs whose `received` line fell out
    of the rotation window are skipped (the shared tolerance)."""
    segs: dict[tuple[str, str], list[tuple[int, int]]] = {}
    frags: dict[str, list[tuple[int, int]]] = {}
    whole: dict[tuple[str, str], int] = {}
    received: set[str] = set()
    for e in entries:
        job = e.get("job")
        if not job:
            continue
        if e.get("event") == "received":
            received.add(str(job))
        elif e.get("event") == "part-routed":
            key = (str(job), str(e.get("name")))
            if isinstance(e.get("lo"), int) \
                    and isinstance(e.get("hi"), int):
                segs.setdefault(key, []).append((e["lo"], e["hi"]))
            elif isinstance(e.get("frag_lo"), int) \
                    and isinstance(e.get("frag_hi"), int):
                frags.setdefault(str(job), []).append(
                    (e["frag_lo"], e["frag_hi"]))
            else:
                whole[key] = whole.get(key, 0) + 1
    problems: list[str] = []
    for (job, name), ranges in sorted(segs.items()):
        if job not in received:
            continue
        ranges.sort()
        expect = 0
        for lo, hi in ranges:
            if lo != expect or hi <= lo:
                problems.append(
                    f"job {job}: contig {name!r} segments do not tile "
                    f"— got [{lo},{hi}) where window {expect} was due")
                break
            expect = hi
    for job, ranges in sorted(frags.items()):
        if job not in received:
            continue
        ranges.sort()
        expect = 0
        for lo, hi in ranges:
            if lo != expect or hi < lo:
                problems.append(
                    f"job {job}: fragment groups do not tile — got "
                    f"[{lo},{hi}) where read {expect} was due")
                break
            expect = hi
    for (job, name), n in sorted(whole.items()):
        if job not in received:
            continue
        if n != 1:
            problems.append(
                f"job {job}: contig {name!r} routed {n} times "
                f"(expected exactly once)")
    return problems


def check_rounds(entries: list[dict]) -> list[str]:
    """Iterative-rounds invariant: every `round-started` a job journals
    must be balanced by exactly one `round-finished` (the server emits
    the pair around each round of a `rounds=N` job). An unbalanced
    count means a round died mid-loop without its boundary line — or a
    duplicated/lost journal write. Jobs whose `received` line fell out
    of the journal's rotation window are skipped (the same tolerance
    check_consistency and check_parts_streamed apply): their early
    round lines may be in the discarded generation."""
    started: dict[str, int] = {}
    finished: dict[str, int] = {}
    received: set[str] = set()
    for e in entries:
        job = e.get("job")
        if not job:
            continue
        if e.get("event") == "received":
            received.add(str(job))
        elif e.get("event") == "round-started":
            started[str(job)] = started.get(str(job), 0) + 1
        elif e.get("event") == "round-finished":
            finished[str(job)] = finished.get(str(job), 0) + 1
    problems: list[str] = []
    for job in sorted(set(started) | set(finished)):
        if job not in received:
            continue
        n_started = started.get(job, 0)
        n_finished = finished.get(job, 0)
        if n_started != n_finished:
            problems.append(
                f"job {job}: {n_started} round-started events vs "
                f"{n_finished} round-finished")
    return problems


def check_preemptions(entries: list[dict]) -> list[str]:
    """Preemption invariant: every `preempted` a job journals must be
    balanced by exactly one `resumed` — the server resumes a parked
    job when capacity frees, and a job that TERMINATES while parked
    still gets its `resumed` line (reason=terminal) from the
    post-terminal cleanup. An unbalanced count means a withdrawal
    leaked: a job parked forever with its windows held hostage. Jobs
    whose `received` line fell out of the journal's rotation window
    are skipped (the same tolerance the other per-job checks apply)."""
    preempted: dict[str, int] = {}
    resumed: dict[str, int] = {}
    received: set[str] = set()
    for e in entries:
        job = e.get("job")
        if not job:
            continue
        if e.get("event") == "received":
            received.add(str(job))
        elif e.get("event") == "preempted":
            preempted[str(job)] = preempted.get(str(job), 0) + 1
        elif e.get("event") == "resumed":
            resumed[str(job)] = resumed.get(str(job), 0) + 1
    problems: list[str] = []
    for job in sorted(set(preempted) | set(resumed)):
        if job not in received:
            continue
        n_pre = preempted.get(job, 0)
        n_res = resumed.get(job, 0)
        if n_pre != n_res:
            problems.append(
                f"job {job}: {n_pre} preempted events vs "
                f"{n_res} resumed")
    return problems


def check_autoscale(entries: list[dict]) -> list[str]:
    """Elasticity-ledger invariant: the autoscaler only drains
    replicas IT spawned (the operator's configured fleet is the floor
    it never touches), so every `autoscale-down` must name a replica
    with a prior, not-yet-drained `autoscale-up` — a down without its
    up, or a second down for the same spawn, means the up/down ledger
    lost a transition. Ups left open at the end of the journal are
    fine: spawned replicas legitimately outlive the window (the next
    idle pass, or the router's drain, retires them)."""
    live: dict[str, int] = {}
    problems: list[str] = []
    for e in entries:
        ev = e.get("event")
        if ev not in ("autoscale-up", "autoscale-down"):
            continue
        spec = str(e.get("replica"))
        if ev == "autoscale-up":
            live[spec] = live.get(spec, 0) + 1
        elif live.get(spec, 0) > 0:
            live[spec] -= 1
        else:
            problems.append(
                f"autoscale-down for {spec!r} without a prior "
                "autoscale-up (or already drained)")
    return problems


if __name__ == "__main__":
    sys.exit(main())
